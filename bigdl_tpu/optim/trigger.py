"""Triggers — when to stop / validate / checkpoint.

Reference parity: optim/Trigger.scala — `everyEpoch`, `severalIteration`,
`maxEpoch`, `maxIteration`, `minLoss`, `maxScore`, `and`, `or`.

A trigger is called with the driver-side training state dict
(`epoch` 1-based, `neval` 0-based completed iterations, `loss`, `score`)
and returns bool. `every_epoch` is stateful (fires on epoch transition),
like the reference's `everyEpoch` cached epoch.
"""

from __future__ import annotations

from typing import Callable, Dict


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool]):
        self._fn = fn

    def __call__(self, state: Dict) -> bool:
        return self._fn(state)

    # ------------------------------------------------------------ factories
    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        return Trigger(lambda s: s["epoch"] > n)

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] >= n)

    @staticmethod
    def every_epoch() -> "Trigger":
        holder = {"last": 1}

        def fn(s):
            if s["epoch"] > holder["last"]:
                holder["last"] = s["epoch"]
                return True
            return False

        return Trigger(fn)

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] > 0 and s["neval"] % n == 0)

    @staticmethod
    def min_loss(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss") is not None and s["loss"] < v)

    @staticmethod
    def max_score(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("score") is not None and s["score"] > v)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers))

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers))
