"""Per-iteration training metrics.

Reference parity: optim/Metrics.scala (`set`, `add`, `summary`) — there a
set of distributed accumulators aggregated to the driver and printed each
iteration; here simple host-side aggregates (multi-host reduction happens
naturally because every host computes identical global values under SPMD).

ISSUE 5: every `add`/`set` also mirrors into the unified telemetry
registry (`bigdl_tpu.obs`) — phase stopwatches become label-series of
the `training_phase_seconds` histogram, scalar sets become gauges — so
`optim.Metrics` is a thin front-end over the one process-wide metrics
plane rather than a private dict. The local dict stays for the
per-iteration `summary()` log line (running means, cheap). `Timer`
additionally records a host span into the active tracer, so the
training phases (data_fetch / dispatch / ...) appear on the
Chrome-trace timeline next to the serving spans.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

from bigdl_tpu import obs


class Metrics:
    def __init__(self):
        self._data: Dict[str, Tuple[float, int]] = {}
        self._hist = obs.get_registry().histogram(
            "training_phase_seconds",
            "per-step phase stopwatches (optim.Metrics timers)",
            labelnames=("phase",))
        self._gauges = obs.get_registry().gauge(
            "training_metric", "optim.Metrics scalar sets",
            labelnames=("name",))

    def set(self, name: str, value: float) -> None:
        self._data[name] = (float(value), 1)
        if obs.enabled():
            self._gauges.labels(name=name).set(float(value))

    def add(self, name: str, value: float) -> None:
        total, n = self._data.get(name, (0.0, 0))
        self._data[name] = (total + float(value), n + 1)
        if obs.enabled():
            self._hist.labels(phase=name).observe(float(value))

    def get(self, name: str) -> float:
        total, n = self._data.get(name, (0.0, 0))
        return total / max(n, 1)

    def summary(self) -> str:
        parts = [f"{k}={total / max(n, 1):.4g}" for k, (total, n) in self._data.items()]
        return " ".join(parts)

    def reset(self) -> None:
        self._data.clear()


class Timer:
    """Context-manager stopwatch feeding a Metrics entry (and, when the
    span tracer is enabled, a host span of the same name)."""

    def __init__(self, metrics: Metrics, name: str):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        self._span = obs.get_tracer().span(self.name.removesuffix("_s"),
                                           cat="train")
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.add(self.name, time.perf_counter() - self._t0)
        self._span.__exit__(None, None, None)
        return False
