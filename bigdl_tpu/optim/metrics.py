"""Per-iteration training metrics.

Reference parity: optim/Metrics.scala (`set`, `add`, `summary`) — there a
set of distributed accumulators aggregated to the driver and printed each
iteration; here simple host-side aggregates (multi-host reduction happens
naturally because every host computes identical global values under SPMD).
"""

from __future__ import annotations

import time
from typing import Dict, Tuple


class Metrics:
    def __init__(self):
        self._data: Dict[str, Tuple[float, int]] = {}

    def set(self, name: str, value: float) -> None:
        self._data[name] = (float(value), 1)

    def add(self, name: str, value: float) -> None:
        total, n = self._data.get(name, (0.0, 0))
        self._data[name] = (total + float(value), n + 1)

    def get(self, name: str) -> float:
        total, n = self._data.get(name, (0.0, 0))
        return total / max(n, 1)

    def summary(self) -> str:
        parts = [f"{k}={total / max(n, 1):.4g}" for k, (total, n) in self._data.items()]
        return " ".join(parts)

    def reset(self) -> None:
        self._data.clear()


class Timer:
    """Context-manager stopwatch feeding a Metrics entry."""

    def __init__(self, metrics: Metrics, name: str):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.add(self.name, time.perf_counter() - self._t0)
        return False
