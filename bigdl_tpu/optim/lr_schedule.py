"""Learning-rate schedules.

Reference parity: optim/SGD.scala#LearningRateSchedule — `Default`, `Step`,
`MultiStep`, `Poly`, `Exponential`, `Plateau`, `Warmup`, `NaturalExp`,
`SequentialSchedule`, `EpochDecay`, `EpochStep`.

Design: schedules run on the HOST each iteration (exactly where the
reference runs `updateHyperParameter` — on the driver) and the resulting
rate enters the jitted train step as a traced scalar argument, so a
changing LR never triggers recompilation.

`rate(state)` gets a dict with `neval` (0-based iteration), `epoch`
(1-based), and optionally `score`/`loss`, and returns the positive LR.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence


class LearningRateSchedule:
    def __init__(self):
        self.base_lr: float = 0.0  # set by the OptimMethod that owns this

    def rate(self, state: Dict) -> float:
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + neval * lr_decay) (reference: SGD.Default)."""

    def __init__(self, learning_rate_decay: float = 0.0):
        super().__init__()
        self.decay = learning_rate_decay

    def rate(self, state):
        return self.base_lr / (1.0 + state["neval"] * self.decay)


class Step(LearningRateSchedule):
    """lr * gamma^(floor(neval / step_size)) (reference: SGD.Step)."""

    def __init__(self, step_size: int, gamma: float):
        super().__init__()
        self.step_size = step_size
        self.gamma = gamma

    def rate(self, state):
        return self.base_lr * self.gamma ** (state["neval"] // self.step_size)


class MultiStep(LearningRateSchedule):
    """Decay by gamma at each listed iteration (reference: SGD.MultiStep)."""

    def __init__(self, step_sizes: Sequence[int], gamma: float):
        super().__init__()
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def rate(self, state):
        k = sum(1 for s in self.step_sizes if state["neval"] >= s)
        return self.base_lr * self.gamma ** k


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor((epoch-1)/step)) (reference: SGD.EpochStep)."""

    def __init__(self, step_size: int, gamma: float):
        super().__init__()
        self.step_size = step_size
        self.gamma = gamma

    def rate(self, state):
        return self.base_lr * self.gamma ** ((state["epoch"] - 1) // self.step_size)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay_fn(epoch) (reference: SGD.EpochDecay)."""

    def __init__(self, decay_fn):
        super().__init__()
        self.decay_fn = decay_fn

    def rate(self, state):
        return self.base_lr * 0.1 ** self.decay_fn(state["epoch"])


class Poly(LearningRateSchedule):
    """lr * (1 - neval/max_iter)^power (reference: SGD.Poly)."""

    def __init__(self, power: float, max_iteration: int):
        super().__init__()
        self.power = power
        self.max_iteration = max_iteration

    def rate(self, state):
        frac = min(state["neval"] / self.max_iteration, 1.0)
        return self.base_lr * (1.0 - frac) ** self.power


class Exponential(LearningRateSchedule):
    """lr * decay_rate^(neval/decay_step), optionally staircased
    (reference: SGD.Exponential)."""

    def __init__(self, decay_step: int, decay_rate: float, staircase: bool = False):
        super().__init__()
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.staircase = staircase

    def rate(self, state):
        e = state["neval"] / self.decay_step
        if self.staircase:
            e = math.floor(e)
        return self.base_lr * self.decay_rate ** e


class NaturalExp(LearningRateSchedule):
    def __init__(self, decay_step: int, gamma: float):
        super().__init__()
        self.decay_step = decay_step
        self.gamma = gamma

    def rate(self, state):
        return self.base_lr * math.exp(-self.gamma * (state["neval"] // self.decay_step))


class Warmup(LearningRateSchedule):
    """Linear ramp from 0 to base lr over `delta` iterations — combined via
    SequentialSchedule (reference: SGD.Warmup)."""

    def __init__(self, delta: float):
        super().__init__()
        self.delta = delta

    def rate(self, state):
        return min(self.base_lr, (state["neval"] + 1) * self.base_lr / max(self.delta, 1))


class Plateau(LearningRateSchedule):
    """Reduce LR when the monitored metric stops improving
    (reference: SGD.Plateau). Driven by `on_metric` from the validation
    loop — host state, never traced."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "max", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._best: Optional[float] = None
        self._wait = 0
        self._cooldown_left = 0
        self._scale = 1.0

    def on_metric(self, value: float) -> None:
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._best = value if self._best is None else self._best
            return
        improved = (self._best is None
                    or (self.mode == "max" and value > self._best + self.epsilon)
                    or (self.mode == "min" and value < self._best - self.epsilon))
        if improved:
            self._best = value
            self._wait = 0
        else:
            self._wait += 1
            if self._wait >= self.patience:
                self._scale *= self.factor
                self._wait = 0
                self._cooldown_left = self.cooldown

    def rate(self, state):
        if "score" in state and state["score"] is not None:
            pass  # scores are fed through on_metric by the optimizer loop
        return max(self.base_lr * self._scale, self.min_lr)


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for `iterations` steps
    (reference: SGD.SequentialSchedule). Typical use: Warmup then Poly."""

    def __init__(self, iteration_per_schedule: Optional[List[int]] = None):
        super().__init__()
        self.schedules: List[LearningRateSchedule] = []
        self.lengths: List[int] = []

    def add(self, schedule: LearningRateSchedule, iterations: int) -> "SequentialSchedule":
        self.schedules.append(schedule)
        self.lengths.append(iterations)
        return self

    def rate(self, state):
        neval = state["neval"]
        offset = 0
        for sched, length in zip(self.schedules, self.lengths):
            if neval < offset + length or sched is self.schedules[-1]:
                sched.base_lr = self.base_lr
                sub = dict(state)
                sub["neval"] = neval - offset
                return sched.rate(sub)
            offset += length
        return self.base_lr
