"""Distributed-style evaluation and batch prediction.

Reference parity: optim/Evaluator.scala (broadcast model, mapPartitions
forward, reduce ValidationResults), optim/Predictor.scala /
LocalPredictor.scala. Here "broadcast" is free (SPMD replication) and the
reduce is the same associative `+` on ValidationResult.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optimizer import _batch_iterator, _to_device
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


class Evaluator:
    """(reference: optim/Evaluator.scala#Evaluator.test)

    `mesh`: evaluate SPMD over a device mesh (forward on each device's
    batch shard, psum the stats). Uneven/final batches are padded up to
    a multiple of the mesh axis and masked out per row — the same
    padded-row guard DistriOptimizer._validate_mesh applies, so the
    standalone Evaluator has no divisibility requirement."""

    def __init__(self, model: Module, mesh=None, axis: str = "data"):
        self.model = model
        self.mesh = mesh
        self.axis = axis

    def test(self, dataset: AbstractDataSet,
             methods: Sequence[ValidationMethod],
             batch_size: int = 32) -> Dict[str, ValidationResult]:
        if self.mesh is not None:
            return self._test_mesh(dataset, methods, batch_size)
        model = self.model
        variables = model.variables

        @jax.jit
        def fwd(params, state, bx):
            out, _ = model.apply({"params": params, "state": state}, bx,
                                 training=False)
            return out

        results = [ValidationResult(0.0, 0.0, m.name) for m in methods]
        for mb in _batch_iterator(dataset, False, batch_size):
            real = getattr(mb, "real_size", mb.size)
            out = fwd(variables["params"], variables["state"], _to_device(mb.input))
            tgt = _to_device(mb.target)
            for i, m in enumerate(methods):
                s, c = m.stats(out, tgt, real)
                results[i] = results[i] + ValidationResult(float(s), float(c))
        return {m.name: r for m, r in zip(methods, results)}

    def _test_mesh(self, dataset, methods, batch_size):
        from bigdl_tpu.parallel.data_parallel import make_dp_eval_step
        from bigdl_tpu.parallel.mesh import host_to_global
        from jax.sharding import PartitionSpec as P

        model, mesh, axis = self.model, self.mesh, self.axis
        n = mesh.shape[axis]
        variables = model.variables
        eval_fn = make_dp_eval_step(model, methods, mesh, axis)

        def pad_rows(x, rows):
            # Pad by REPEATING the last real row (mode="edge"), matching
            # MiniBatch.from_samples' padding: ValidationMethod.stats'
            # mask-array branch assumes padded rows hold real samples, so
            # zero rows would bias Loss-style metrics even though the
            # scale uses the real count.
            x = np.asarray(x)
            if x.shape[0] == rows:
                return x
            widths = [(0, rows - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            return np.pad(x, widths, mode="edge")

        def place(x, rows):
            if isinstance(x, tuple):
                return tuple(place(e, rows) for e in x)
            arr = pad_rows(x, rows)
            return host_to_global(
                mesh, P(axis, *([None] * (arr.ndim - 1))), arr)

        results = [ValidationResult(0.0, 0.0, m.name) for m in methods]
        for mb in _batch_iterator(dataset, False, batch_size):
            real = getattr(mb, "real_size", mb.size)
            rows = ((mb.size + n - 1) // n) * n
            mask = (np.arange(rows) < real).astype(np.float32)
            stats = eval_fn(variables["params"], variables["state"],
                            place(mb.input, rows), place(mb.target, rows),
                            place(mask, rows))
            for i, (s, c) in enumerate(stats):
                results[i] = results[i] + ValidationResult(float(s), float(c))
        return {m.name: r for m, r in zip(methods, results)}


class Predictor:
    """Batch inference (reference: optim/Predictor.scala). `predict` yields
    per-sample outputs; `predict_class` yields argmax ids.

    Shape-bucketed compile cache: a ragged batch is padded up (repeat
    last real row, tail sliced off the output) to a shape the jitted
    forward has already compiled, instead of presenting XLA a novel
    shape — so a dataset whose size is not a batch multiple compiles
    ONCE instead of once per ragged tail (the serving-plane
    discipline, bigdl_tpu/serving/bucketing.py). By default the
    bucket set is LEARNED: the first batch of a given size compiles
    at that exact size, and later batches pad up to the smallest
    already-compiled size that covers them — so a dataset of uniform
    small batches never pays padding, while a ragged tail reuses the
    full-batch executable. Pass `bucket_sizes` to pin an explicit
    fixed bucket set instead (each bucket used compiles once).
    `n_traces` counts compilations (the regression-test hook)."""

    def __init__(self, model: Module, batch_size: int = 32,
                 bucket_sizes: Optional[Sequence[int]] = None):
        self.model = model
        self.batch_size = batch_size
        self.bucket_sizes = tuple(sorted(bucket_sizes)) \
            if bucket_sizes else None
        if self.bucket_sizes and max(self.bucket_sizes) < batch_size:
            raise ValueError("largest bucket must cover batch_size")
        self._learned: set = set()     # sizes already compiled (default mode)
        self.n_traces = 0
        self._fwd = None

    def _jit_fwd(self):
        # held on the instance so repeated predict() calls reuse the
        # per-bucket executables instead of re-tracing
        if self._fwd is None:
            model = self.model

            def fwd(params, state, bx):
                self.n_traces += 1       # runs at trace time only
                out, _ = model.apply({"params": params, "state": state},
                                     bx, training=False)
                return out

            self._fwd = jax.jit(fwd)
        return self._fwd

    def predict(self, dataset: AbstractDataSet) -> np.ndarray:
        from bigdl_tpu.serving.bucketing import bucket_for, pad_rows

        variables = self.model.variables
        fwd = self._jit_fwd()
        outs: List[np.ndarray] = []
        for mb in _batch_iterator(dataset, False, self.batch_size):
            real = getattr(mb, "real_size", mb.size)
            if self.bucket_sizes:
                # explicit buckets; pre-batched MiniBatches LARGER than
                # every bucket run at their own shape (pad up only,
                # never split)
                rows = mb.size if mb.size > max(self.bucket_sizes) \
                    else bucket_for(mb.size, self.bucket_sizes)
            else:
                # learned buckets: reuse the smallest compiled size
                # that covers this batch; otherwise compile at the
                # exact size (no padding for uniform-size streams)
                rows = min((s for s in self._learned if s >= mb.size),
                           default=mb.size)
                self._learned.add(rows)
            out = np.asarray(fwd(variables["params"], variables["state"],
                                 _to_device(pad_rows(mb.input, rows))))
            outs.append(out[:real])
        return np.concatenate(outs, axis=0)

    def predict_class(self, dataset: AbstractDataSet) -> np.ndarray:
        return np.argmax(self.predict(dataset), axis=-1)


LocalPredictor = Predictor
