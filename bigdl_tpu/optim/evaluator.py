"""Distributed-style evaluation and batch prediction.

Reference parity: optim/Evaluator.scala (broadcast model, mapPartitions
forward, reduce ValidationResults), optim/Predictor.scala /
LocalPredictor.scala. Here "broadcast" is free (SPMD replication) and the
reduce is the same associative `+` on ValidationResult.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optimizer import _batch_iterator, _to_device
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


class Evaluator:
    """(reference: optim/Evaluator.scala#Evaluator.test)"""

    def __init__(self, model: Module):
        self.model = model

    def test(self, dataset: AbstractDataSet,
             methods: Sequence[ValidationMethod],
             batch_size: int = 32) -> Dict[str, ValidationResult]:
        model = self.model
        variables = model.variables

        @jax.jit
        def fwd(params, state, bx):
            out, _ = model.apply({"params": params, "state": state}, bx,
                                 training=False)
            return out

        results = [ValidationResult(0.0, 0.0, m.name) for m in methods]
        for mb in _batch_iterator(dataset, False, batch_size):
            real = getattr(mb, "real_size", mb.size)
            out = fwd(variables["params"], variables["state"], _to_device(mb.input))
            tgt = _to_device(mb.target)
            for i, m in enumerate(methods):
                s, c = m.stats(out, tgt, real)
                results[i] = results[i] + ValidationResult(float(s), float(c))
        return {m.name: r for m, r in zip(methods, results)}


class Predictor:
    """Batch inference (reference: optim/Predictor.scala). `predict` yields
    per-sample outputs; `predict_class` yields argmax ids."""

    def __init__(self, model: Module, batch_size: int = 32):
        self.model = model
        self.batch_size = batch_size

    def predict(self, dataset: AbstractDataSet) -> np.ndarray:
        model = self.model
        variables = model.variables

        @jax.jit
        def fwd(params, state, bx):
            out, _ = model.apply({"params": params, "state": state}, bx,
                                 training=False)
            return out

        outs: List[np.ndarray] = []
        for mb in _batch_iterator(dataset, False, self.batch_size):
            real = getattr(mb, "real_size", mb.size)
            out = np.asarray(fwd(variables["params"], variables["state"],
                                 _to_device(mb.input)))
            outs.append(out[:real])
        return np.concatenate(outs, axis=0)

    def predict_class(self, dataset: AbstractDataSet) -> np.ndarray:
        return np.argmax(self.predict(dataset), axis=-1)


LocalPredictor = Predictor
