"""L-BFGS with line search, fully under jit.

Reference parity: optim/LBFGS.scala (two-loop recursion, history of
(s, y) pairs, tolFun/tolX termination) + optim/LineSearch.scala
(`lswolfe`). The reference's optimize() takes a `feval` closure it can
re-evaluate during the line search — a different contract from the
gradient-based OptimMethod.update used by the training loop — so LBFGS
here exposes `minimize(feval, x0)` directly, mirroring
`LBFGS.optimize(feval, x)`.

TPU-first redesign: the reference's Scala loop with mutable ArrayBuffers
becomes a `lax.while_loop` over fixed-shape history buffers
((m, n) ring buffers + ring index), so the WHOLE optimization — history
updates, two-loop recursion, line search — is one XLA computation with
static shapes. The default line search is strong-Wolfe with cubic
interpolation (reference: optim/LineSearch.scala#lswolfe — bracket then
zoom, both as fixed-shape `lax.while_loop` stages); backtracking Armijo
remains available as `line_search="armijo"`. Works on any params pytree
via ravel_pytree.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree


def _cubic_min(x1, f1, g1, x2, f2, g2, lo, hi):
    """Minimizer of the cubic through (x1,f1,g1), (x2,f2,g2), clipped to
    [lo, hi]; bisection when the cubic has no real minimum (reference:
    LineSearch.scala polynomial interpolation inside lswolfe)."""
    d1 = g1 + g2 - 3.0 * (f1 - f2) / (x1 - x2)
    d2sq = d1 * d1 - g1 * g2
    d2 = jnp.sqrt(jnp.maximum(d2sq, 0.0))
    t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2.0 * d2))
    mid = 0.5 * (x1 + x2)
    t = jnp.where(d2sq >= 0.0, t, mid)
    t = jnp.where(jnp.isfinite(t), t, mid)
    return jnp.clip(t, lo, hi)


def _strong_wolfe(vg, x, t0, d, f0, g0, gtd0, c1, c2, max_ls):
    """Strong-Wolfe line search (reference: LineSearch.scala#lswolfe).

    Phase 1 brackets a step interval by cubic extrapolation; phase 2
    zooms with cubic interpolation until BOTH Wolfe conditions hold:
        f(t) <= f0 + c1 t g0·d        (sufficient decrease)
        |g(t)·d| <= -c2 g0·d          (strong curvature)
    Returns (t, f_t, g_t, evals). Both phases are one `lax.while_loop`
    with a stage flag, so the whole search stays inside jit with static
    shapes. On exhaustion the low bracket end (which always satisfies
    sufficient decrease) is returned.
    """
    BRACKET, ZOOM, DONE = 0, 1, 2

    f1, g1 = vg(x + t0 * d)

    def gtd_of(g):
        return jnp.dot(g, d)

    init = dict(
        stage=jnp.asarray(BRACKET), nev=jnp.asarray(1), it=jnp.asarray(0),
        # previous bracket-phase point (starts at t=0 = the origin)
        tp=jnp.zeros_like(t0), fp=f0, gtdp=gtd0, gp=g0,
        # current evaluated point
        t=t0, f=f1, g=g1,
        # zoom bracket [lo, hi]; lo always satisfies sufficient decrease
        lo_t=jnp.zeros_like(t0), lo_f=f0, lo_gtd=gtd0, lo_g=g0,
        hi_t=jnp.zeros_like(t0), hi_f=f0, hi_gtd=gtd0, hi_g=g0,
    )
    keys = list(init)

    def pack(d_):
        return tuple(d_[k] for k in keys)

    def unpack(c):
        return dict(zip(keys, c))

    def cond(c):
        s = unpack(c)
        return (s["stage"] != DONE) & (s["nev"] < max_ls)

    def body(c):
        s = unpack(c)
        gtd_t = gtd_of(s["g"])
        armijo_fail = (s["f"] > f0 + c1 * s["t"] * gtd0) | \
            ((s["it"] > 0) & (s["f"] >= s["fp"]))
        wolfe_ok = jnp.abs(gtd_t) <= -c2 * gtd0
        pos_slope = gtd_t >= 0.0

        def bracket_step(s):
            # -> zoom with bracket (prev, cur)
            to_zoom_a = dict(s, stage=jnp.asarray(ZOOM),
                             lo_t=s["tp"], lo_f=s["fp"], lo_gtd=s["gtdp"],
                             lo_g=s["gp"], hi_t=s["t"], hi_f=s["f"],
                             hi_gtd=gtd_t, hi_g=s["g"])
            # -> done at cur
            done = dict(s, stage=jnp.asarray(DONE))
            # -> zoom with bracket (cur, prev)
            to_zoom_b = dict(s, stage=jnp.asarray(ZOOM),
                             lo_t=s["t"], lo_f=s["f"], lo_gtd=gtd_t,
                             lo_g=s["g"], hi_t=s["tp"], hi_f=s["fp"],
                             hi_gtd=s["gtdp"], hi_g=s["gp"])
            # -> extrapolate and evaluate a larger step
            min_t = s["t"] + 0.01 * (s["t"] - s["tp"])
            max_t = s["t"] * 10.0
            t_new = _cubic_min(s["tp"], s["fp"], s["gtdp"],
                               s["t"], s["f"], gtd_t, min_t, max_t)
            f_new, g_new = vg(x + t_new * d)
            extrap = dict(s, tp=s["t"], fp=s["f"], gtdp=gtd_t, gp=s["g"],
                          t=t_new, f=f_new, g=g_new,
                          nev=s["nev"] + 1)

            branches = [to_zoom_a, done, to_zoom_b, extrap]
            sel = jnp.where(armijo_fail, 0,
                            jnp.where(wolfe_ok, 1,
                                      jnp.where(pos_slope, 2, 3)))
            return {k: _select(sel, [b[k] for b in branches])
                    for k in keys}

        def zoom_step(s):
            lo, hi = jnp.minimum(s["lo_t"], s["hi_t"]), \
                jnp.maximum(s["lo_t"], s["hi_t"])
            w = hi - lo
            t_new = _cubic_min(s["lo_t"], s["lo_f"], s["lo_gtd"],
                               s["hi_t"], s["hi_f"], s["hi_gtd"],
                               lo + 0.1 * w, hi - 0.1 * w)
            f_new, g_new = vg(x + t_new * d)
            gtd_new = gtd_of(g_new)
            nev = s["nev"] + 1

            fail = (f_new > f0 + c1 * t_new * gtd0) | (f_new >= s["lo_f"])
            new_hi = dict(s, hi_t=t_new, hi_f=f_new, hi_gtd=gtd_new,
                          hi_g=g_new, nev=nev)
            done = dict(s, t=t_new, f=f_new, g=g_new,
                        stage=jnp.asarray(DONE), nev=nev)
            flip = gtd_new * (s["hi_t"] - s["lo_t"]) >= 0.0
            move_lo = dict(
                s, hi_t=jnp.where(flip, s["lo_t"], s["hi_t"]),
                hi_f=jnp.where(flip, s["lo_f"], s["hi_f"]),
                hi_gtd=jnp.where(flip, s["lo_gtd"], s["hi_gtd"]),
                hi_g=jnp.where(flip, s["lo_g"], s["hi_g"]),
                lo_t=t_new, lo_f=f_new, lo_gtd=gtd_new, lo_g=g_new,
                nev=nev)
            wolfe_new = jnp.abs(gtd_new) <= -c2 * gtd0
            # degenerate bracket: stop on the low end
            tiny = w <= 1e-9 * jnp.maximum(hi, 1.0)
            stop = dict(s, t=s["lo_t"], f=s["lo_f"], g=s["lo_g"],
                        stage=jnp.asarray(DONE), nev=nev)
            branches = [new_hi, done, move_lo, stop]
            sel = jnp.where(tiny, 3,
                            jnp.where(fail, 0, jnp.where(wolfe_new, 1, 2)))
            return {k: _select(sel, [b[k] for b in branches])
                    for k in keys}

        out = unpack(lax.cond(s["stage"] == ZOOM,
                              lambda c: pack(zoom_step(unpack(c))),
                              lambda c: pack(bracket_step(unpack(c))),
                              pack(s)))
        out["it"] = s["it"] + 1
        return pack(out)

    out = unpack(lax.while_loop(cond, body, pack(init)))
    # Exhausted searches fall back to a sufficient-decrease point:
    # ZOOM keeps its low bracket end; BRACKET keeps the current point
    # only if it passes Armijo, else the previous one (tp=0 initially =
    # the origin, so the worst case is a zero step, never an ascent).
    zoom_fall = out["stage"] == ZOOM
    cur_bad = (out["stage"] == BRACKET) & \
        (out["f"] > f0 + c1 * out["t"] * gtd0)
    t = jnp.where(zoom_fall, out["lo_t"],
                  jnp.where(cur_bad, out["tp"], out["t"]))
    f = jnp.where(zoom_fall, out["lo_f"],
                  jnp.where(cur_bad, out["fp"], out["f"]))
    g = jnp.where(zoom_fall, out["lo_g"],
                  jnp.where(cur_bad, out["gp"], out["g"]))
    return t, f, g, out["nev"]


def _select(idx, values):
    """Index-select across same-shaped values (branchless)."""
    out = values[0]
    for i, v in enumerate(values[1:], start=1):
        out = jnp.where(idx == i, v, out)
    return out


class LBFGS:
    """minimize(feval, x0) → (x*, final_loss, n_iter).

    feval: params-pytree → scalar loss (differentiated internally).
    """

    def __init__(self, max_iter: int = 100, history_size: int = 10,
                 learningrate: float = 1.0, tolfun: float = 1e-8,
                 tolx: float = 1e-9,
                 line_search: Union[bool, str] = "wolfe",
                 ls_max_steps: int = 25, armijo_c: float = 1e-4,
                 ls_backtrack: float = 0.5, wolfe_c2: float = 0.9):
        """line_search: "wolfe" (default — reference lswolfe), "armijo"
        (backtracking sufficient-decrease only), or False (fixed step).
        True is accepted as "wolfe"."""
        self.max_iter = max_iter
        self.history_size = history_size
        self.learningrate = learningrate
        self.tolfun = tolfun
        self.tolx = tolx
        if line_search is True:
            line_search = "wolfe"
        if line_search not in ("wolfe", "armijo", False):
            raise ValueError(f"unknown line_search {line_search!r}")
        self.line_search = line_search
        self.ls_max_steps = ls_max_steps
        self.armijo_c = armijo_c
        self.ls_backtrack = ls_backtrack
        self.wolfe_c2 = wolfe_c2
        self.evals: Optional[jax.Array] = None  # feval count of last minimize

    def minimize(self, feval: Callable, x0: Any
                 ) -> Tuple[Any, jax.Array, jax.Array]:
        flat0, unravel = ravel_pytree(x0)
        n = flat0.shape[0]
        m = self.history_size

        def f(flat):
            return feval(unravel(flat))

        vg = jax.value_and_grad(f)

        def direction(g, s_hist, y_hist, rho, count, head):
            """Two-loop recursion (reference: LBFGS.scala twoLoop)."""
            q = -g
            alphas = jnp.zeros((m,))

            def bwd(i, carry):
                q, alphas = carry
                # newest-to-oldest: slot index
                j = (head - 1 - i) % m
                valid = i < count
                a = rho[j] * jnp.dot(s_hist[j], q)
                a = jnp.where(valid, a, 0.0)
                q = q - a * y_hist[j]
                return q, alphas.at[j].set(a)

            q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
            # initial Hessian scaling γ = s·y / y·y of the newest pair
            jn = (head - 1) % m
            gamma = jnp.where(
                count > 0,
                jnp.dot(s_hist[jn], y_hist[jn]) /
                jnp.maximum(jnp.dot(y_hist[jn], y_hist[jn]), 1e-10),
                1.0)
            r = q * gamma

            def fwd(i, r):
                j = (head - count + i) % m      # oldest-to-newest
                valid = i < count
                beta = rho[j] * jnp.dot(y_hist[j], r)
                upd = (alphas[j] - beta) * s_hist[j]
                return r + jnp.where(valid, upd, 0.0)

            return lax.fori_loop(0, m, fwd, r)

        def search(x, fx, g, d):
            """Line search dispatch: strong-Wolfe (lswolfe), Armijo
            backtracking, or fixed step. Returns (t, f, g, evals)."""
            gtd = jnp.dot(g, d)
            t0 = jnp.asarray(self.learningrate, flat0.dtype)
            if not self.line_search:
                fx2, g2 = vg(x + t0 * d)
                return t0, fx2, g2, jnp.asarray(1)
            if self.line_search == "wolfe":
                return _strong_wolfe(vg, x, t0, d, fx, g, gtd,
                                     self.armijo_c, self.wolfe_c2,
                                     self.ls_max_steps)

            def cond(carry):
                t, k, fx2, _ = carry
                return (k < self.ls_max_steps) & \
                    (fx2 > fx + self.armijo_c * t * gtd)

            def body(carry):
                t, k, _, _ = carry
                t = t * self.ls_backtrack
                fx2, g2 = vg(x + t * d)
                return t, k + 1, fx2, g2

            fx_first, g_first = vg(x + t0 * d)
            t, k, fx2, g2 = lax.while_loop(
                cond, body, (t0, jnp.asarray(0), fx_first, g_first))
            return t, fx2, g2, k + 1

        def step(carry):
            x, fx, g, s_hist, y_hist, rho, count, head, it, nev, _ = carry
            d = direction(g, s_hist, y_hist, rho, count, head)
            # fall back to steepest descent if d is not a descent dir
            gtd = jnp.dot(g, d)
            d = jnp.where(gtd < 0, d, -g)
            t, fx2, g2, k = search(x, fx, g, d)
            nev = nev + k
            s = t * d
            y = g2 - g
            sy = jnp.dot(s, y)
            # curvature check before admitting the pair to history
            ok = sy > 1e-10
            s_hist = jnp.where(ok, s_hist.at[head].set(s), s_hist)
            y_hist = jnp.where(ok, y_hist.at[head].set(y), y_hist)
            rho = jnp.where(ok, rho.at[head].set(1.0 / jnp.maximum(sy, 1e-10)),
                            rho)
            head = jnp.where(ok, (head + 1) % m, head)
            count = jnp.where(ok, jnp.minimum(count + 1, m), count)
            converged = (jnp.abs(fx2 - fx) < self.tolfun) | \
                (jnp.max(jnp.abs(s)) < self.tolx) | \
                (jnp.max(jnp.abs(g2)) < self.tolfun)
            return (x + s, fx2, g2, s_hist, y_hist, rho, count, head,
                    it + 1, nev, converged)

        def cond(carry):
            *_, it, nev, converged = carry
            return (it < self.max_iter) & jnp.logical_not(converged)

        fx0, g0 = vg(flat0)
        init = (flat0, fx0, g0, jnp.zeros((m, n)), jnp.zeros((m, n)),
                jnp.zeros((m,)), jnp.asarray(0), jnp.asarray(0),
                jnp.asarray(0), jnp.asarray(1), jnp.asarray(False))
        out = lax.while_loop(cond, step, init)
        self.evals = out[9]
        return unravel(out[0]), out[1], out[8]
