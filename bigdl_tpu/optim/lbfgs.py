"""L-BFGS with line search, fully under jit.

Reference parity: optim/LBFGS.scala (two-loop recursion, history of
(s, y) pairs, tolFun/tolX termination) + optim/LineSearch.scala
(`lswolfe`). The reference's optimize() takes a `feval` closure it can
re-evaluate during the line search — a different contract from the
gradient-based OptimMethod.update used by the training loop — so LBFGS
here exposes `minimize(feval, x0)` directly, mirroring
`LBFGS.optimize(feval, x)`.

TPU-first redesign: the reference's Scala loop with mutable ArrayBuffers
becomes a `lax.while_loop` over fixed-shape history buffers
((m, n) ring buffers + ring index), so the WHOLE optimization — history
updates, two-loop recursion, line search — is one XLA computation with
static shapes. Line search is backtracking Armijo under an inner
`lax.while_loop` (the reference defaults to a fixed step unless lswolfe
is passed; strong-Wolfe cubic interpolation is a documented divergence).
Works on any params pytree via ravel_pytree.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree


class LBFGS:
    """minimize(feval, x0) → (x*, final_loss, n_iter).

    feval: params-pytree → scalar loss (differentiated internally).
    """

    def __init__(self, max_iter: int = 100, history_size: int = 10,
                 learningrate: float = 1.0, tolfun: float = 1e-8,
                 tolx: float = 1e-9, line_search: bool = True,
                 ls_max_steps: int = 20, armijo_c: float = 1e-4,
                 ls_backtrack: float = 0.5):
        self.max_iter = max_iter
        self.history_size = history_size
        self.learningrate = learningrate
        self.tolfun = tolfun
        self.tolx = tolx
        self.line_search = line_search
        self.ls_max_steps = ls_max_steps
        self.armijo_c = armijo_c
        self.ls_backtrack = ls_backtrack

    def minimize(self, feval: Callable, x0: Any
                 ) -> Tuple[Any, jax.Array, jax.Array]:
        flat0, unravel = ravel_pytree(x0)
        n = flat0.shape[0]
        m = self.history_size

        def f(flat):
            return feval(unravel(flat))

        vg = jax.value_and_grad(f)

        def direction(g, s_hist, y_hist, rho, count, head):
            """Two-loop recursion (reference: LBFGS.scala twoLoop)."""
            q = -g
            alphas = jnp.zeros((m,))

            def bwd(i, carry):
                q, alphas = carry
                # newest-to-oldest: slot index
                j = (head - 1 - i) % m
                valid = i < count
                a = rho[j] * jnp.dot(s_hist[j], q)
                a = jnp.where(valid, a, 0.0)
                q = q - a * y_hist[j]
                return q, alphas.at[j].set(a)

            q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
            # initial Hessian scaling γ = s·y / y·y of the newest pair
            jn = (head - 1) % m
            gamma = jnp.where(
                count > 0,
                jnp.dot(s_hist[jn], y_hist[jn]) /
                jnp.maximum(jnp.dot(y_hist[jn], y_hist[jn]), 1e-10),
                1.0)
            r = q * gamma

            def fwd(i, r):
                j = (head - count + i) % m      # oldest-to-newest
                valid = i < count
                beta = rho[j] * jnp.dot(y_hist[j], r)
                upd = (alphas[j] - beta) * s_hist[j]
                return r + jnp.where(valid, upd, 0.0)

            return lax.fori_loop(0, m, fwd, r)

        def search(x, fx, g, d):
            """Backtracking Armijo: largest t=lr·β^k with sufficient
            decrease (reference default is fixed-step; lswolfe is the
            stronger variant — documented divergence)."""
            gtd = jnp.dot(g, d)
            t0 = jnp.asarray(self.learningrate)
            if not self.line_search:
                fx2, g2 = vg(x + t0 * d)
                return t0, fx2, g2

            def cond(carry):
                t, k, fx2, _ = carry
                return (k < self.ls_max_steps) & \
                    (fx2 > fx + self.armijo_c * t * gtd)

            def body(carry):
                t, k, _, _ = carry
                t = t * self.ls_backtrack
                fx2, g2 = vg(x + t * d)
                return t, k + 1, fx2, g2

            fx_first, g_first = vg(x + t0 * d)
            t, _, fx2, g2 = lax.while_loop(
                cond, body, (t0, jnp.asarray(0), fx_first, g_first))
            return t, fx2, g2

        def step(carry):
            x, fx, g, s_hist, y_hist, rho, count, head, it, _ = carry
            d = direction(g, s_hist, y_hist, rho, count, head)
            # fall back to steepest descent if d is not a descent dir
            gtd = jnp.dot(g, d)
            d = jnp.where(gtd < 0, d, -g)
            t, fx2, g2 = search(x, fx, g, d)
            s = t * d
            y = g2 - g
            sy = jnp.dot(s, y)
            # curvature check before admitting the pair to history
            ok = sy > 1e-10
            s_hist = jnp.where(ok, s_hist.at[head].set(s), s_hist)
            y_hist = jnp.where(ok, y_hist.at[head].set(y), y_hist)
            rho = jnp.where(ok, rho.at[head].set(1.0 / jnp.maximum(sy, 1e-10)),
                            rho)
            head = jnp.where(ok, (head + 1) % m, head)
            count = jnp.where(ok, jnp.minimum(count + 1, m), count)
            converged = (jnp.abs(fx2 - fx) < self.tolfun) | \
                (jnp.max(jnp.abs(s)) < self.tolx) | \
                (jnp.max(jnp.abs(g2)) < self.tolfun)
            return (x + s, fx2, g2, s_hist, y_hist, rho, count, head,
                    it + 1, converged)

        def cond(carry):
            *_, it, converged = carry
            return (it < self.max_iter) & jnp.logical_not(converged)

        fx0, g0 = vg(flat0)
        init = (flat0, fx0, g0, jnp.zeros((m, n)), jnp.zeros((m, n)),
                jnp.zeros((m,)), jnp.asarray(0), jnp.asarray(0),
                jnp.asarray(0), jnp.asarray(False))
        out = lax.while_loop(cond, step, init)
        return unravel(out[0]), out[1], out[8]
