"""Validation methods and results.

Reference parity: optim/ValidationMethod.scala — `Top1Accuracy`,
`Top5Accuracy`, `Loss`, `TreeNNAccuracy`, `HitRatio`, `NDCG`;
optim/ValidationResult.scala — `AccuracyResult`, `LossResult` with `+`
merge for distributed reduction.

Each method has a jit-friendly core: `stats(output, target) -> (sum, count)`
as device scalars; results merge associatively so partial results from
shards/hosts reduce exactly like the reference's RDD `reduce(_ + _)`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class ValidationResult:
    """Additive (value-sum, count) pair (reference: optim/ValidationResult.scala)."""

    def __init__(self, total: float, count: float, fmt: str = "Accuracy"):
        self.total = float(total)
        self.count = float(count)
        self.fmt = fmt

    def result(self) -> Tuple[float, int]:
        return (self.total / max(self.count, 1.0), int(self.count))

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.total + other.total,
                                self.count + other.count, self.fmt)

    def __repr__(self):
        v, n = self.result()
        return f"{self.fmt}: {v:.6f} (count {n})"


class ValidationMethod:
    name = "ValidationMethod"

    def stats(self, output, target, real_size: Optional[int] = None):
        """Return (metric_sum, count) as scalars. `real_size` masks padded
        tail rows in the final partial batch."""
        raise NotImplementedError

    def apply(self, output, target, real_size: Optional[int] = None) -> ValidationResult:
        s, c = self.stats(output, target, real_size)
        return ValidationResult(float(s), float(c), self.name)

    def __repr__(self):
        return self.name


def _row_mask(n_rows: int, real_size):
    """real_size: None (no padding), an int prefix length, or an explicit
    per-row 0/1 mask array (needed when the batch is sharded over a mesh
    and padded rows are not a prefix of each shard)."""
    if real_size is None:
        return jnp.ones((n_rows,), jnp.float32)
    if isinstance(real_size, (int, np.integer)):
        return (jnp.arange(n_rows) < real_size).astype(jnp.float32)
    return jnp.asarray(real_size, jnp.float32)


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def stats(self, output, target, real_size=None):
        pred = jnp.argmax(output, axis=-1)
        correct = (pred == target.astype(pred.dtype)).astype(jnp.float32)
        mask = _row_mask(correct.shape[0], real_size)
        return jnp.sum(correct * mask), jnp.sum(mask)


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def stats(self, output, target, real_size=None):
        top5 = jnp.argsort(output, axis=-1)[..., -5:]
        hit = jnp.any(top5 == target[..., None].astype(top5.dtype), axis=-1)
        hit = hit.astype(jnp.float32)
        mask = _row_mask(hit.shape[0], real_size)
        return jnp.sum(hit * mask), jnp.sum(mask)


class Loss(ValidationMethod):
    """Criterion value as a validation metric (reference: ValidationMethod.Loss)."""

    name = "Loss"

    def __init__(self, criterion):
        self.criterion = criterion

    def stats(self, output, target, real_size=None):
        n = output.shape[0]
        if real_size is None:
            return self.criterion(output, target) * n, jnp.asarray(float(n))
        if isinstance(real_size, (int, np.integer)):
            if real_size != n:
                output = output[:real_size]
                target = target[:real_size]
            return (self.criterion(output, target) * real_size,
                    jnp.asarray(float(real_size)))
        # Mask-array case (sharded eval). Padded rows REPEAT THE LAST REAL
        # ROW (both padding layers guarantee it: MiniBatch.from_samples
        # `pad_to` repeats samples[-1]; Evaluator._test_mesh pads
        # mode="edge"), so the batch mean decomposes exactly:
        #   sum_real = n * mean_all - (n - real) * loss(last_row)
        # — the final row of any shard is either a real row or a copy of
        # the last real one, so the correction is exact per shard, even
        # for an all-padding shard (mean_all == l_last -> total == 0).
        # Holds for any criterion whose batch value is the per-row mean;
        # weighted criterions normalizing by sum-of-weights remain an
        # approximation, as in the reference's batch-weighted Loss.
        cnt = jnp.sum(jnp.asarray(real_size, jnp.float32))
        mean_all = self.criterion(output, target)
        take_last = (lambda x: tuple(e[-1:] for e in x)
                     if isinstance(x, tuple) else x[-1:])
        l_last = self.criterion(take_last(output), take_last(target))
        return n * mean_all - (n - cnt) * l_last, cnt


class TreeNNAccuracy(ValidationMethod):
    """Accuracy on the root prediction of tree outputs
    (reference: optim/ValidationMethod.scala#TreeNNAccuracy).
    Output (N, T, C): scores per node, root is node 0."""

    name = "TreeNNAccuracy"

    def stats(self, output, target, real_size=None):
        root_out = output[:, 0, :] if output.ndim == 3 else output
        root_tgt = target[:, 0] if target.ndim == 2 else target
        pred = jnp.argmax(root_out, axis=-1)
        correct = (pred == root_tgt.astype(pred.dtype)).astype(jnp.float32)
        mask = _row_mask(correct.shape[0], real_size)
        return jnp.sum(correct * mask), jnp.sum(mask)


class HitRatio(ValidationMethod):
    """HR@k for recommendation (reference: optim/ValidationMethod.scala#HitRatio).
    output: (N, C) scores; target: (N,) index of the positive item."""

    name = "HitRatio"

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.name = f"HitRatio@{k}"

    def stats(self, output, target, real_size=None):
        topk = jnp.argsort(output, axis=-1)[..., -self.k:]
        hit = jnp.any(topk == target[..., None].astype(topk.dtype), axis=-1)
        hit = hit.astype(jnp.float32)
        mask = _row_mask(hit.shape[0], real_size)
        return jnp.sum(hit * mask), jnp.sum(mask)


class NDCG(ValidationMethod):
    """NDCG@k with a single positive item (reference: ValidationMethod.scala#NDCG)."""

    name = "NDCG"

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.name = f"NDCG@{k}"

    def stats(self, output, target, real_size=None):
        order = jnp.argsort(output, axis=-1)[..., ::-1][..., :self.k]
        pos = order == target[..., None].astype(order.dtype)
        ranks = jnp.argmax(pos, axis=-1)  # rank of hit if any
        has_hit = jnp.any(pos, axis=-1)
        gain = jnp.where(has_hit, 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0), 0.0)
        mask = _row_mask(gain.shape[0], real_size)
        return jnp.sum(gain * mask), jnp.sum(mask)


class MAE(ValidationMethod):
    """Mean absolute error for regression outputs
    (reference: optim/ValidationMethod.scala#MAE)."""

    name = "MAE"

    def stats(self, output, target, real_size=None):
        n = output.shape[0]
        err = jnp.mean(jnp.abs(output - target.reshape(output.shape)),
                       axis=tuple(range(1, output.ndim)))
        if real_size is None:
            return jnp.sum(err), jnp.asarray(float(n))
        if isinstance(real_size, (int, np.integer)):
            return jnp.sum(err[:real_size]), jnp.asarray(float(real_size))
        mask = jnp.asarray(real_size, jnp.float32)
        return jnp.sum(err * mask), jnp.sum(mask)
