"""bigdl_tpu.optim — training orchestration (reference: bigdl/optim/)."""

from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adam, Adagrad, Adamax, RMSprop, AdaDelta, Ftrl,
)
from bigdl_tpu.optim.lr_schedule import (
    LearningRateSchedule, Default, Step, MultiStep, EpochStep, EpochDecay,
    Poly, Exponential, NaturalExp, Warmup, Plateau, SequentialSchedule,
)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    MAE,
    ValidationMethod, ValidationResult, Top1Accuracy, Top5Accuracy, Loss,
    TreeNNAccuracy, HitRatio, NDCG,
)
from bigdl_tpu.optim.lbfgs import LBFGS
from bigdl_tpu.optim.metrics import Metrics, Timer
from bigdl_tpu.optim.optimizer import Optimizer, LocalOptimizer
from bigdl_tpu.optim.evaluator import Evaluator, Predictor, LocalPredictor
