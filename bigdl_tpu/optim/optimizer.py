"""Optimizer front-end and single-host training loop.

Reference parity: optim/Optimizer.scala (builder surface: `setOptimMethod`,
`setEndWhen`, `setValidation`, `setCheckpoint`, `setTrainSummary`,
`optimize`, dispatch Local vs Distri) and optim/LocalOptimizer.scala.

TPU-first redesign: the reference's LocalOptimizer clones the model across
cores and hand-splits each MiniBatch; here intra-chip parallelism belongs
to XLA — ONE jitted train step owns the whole batch. The step is pure:

    (params, mod_state, slots, batch, lr, step#, rng)
        -> (params', mod_state', slots', loss)

Distributed training subclasses this loop and swaps the step function for
the mesh-sharded one (bigdl_tpu/parallel/distri_optimizer.py), exactly
the Local/Distri split the reference has.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import obs
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.metrics import Metrics, Timer
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.serialization.checkpoint import Checkpoint

logger = logging.getLogger("bigdl_tpu.optim")


def _batch_iterator(dataset: AbstractDataSet, train: bool,
                    batch_size: Optional[int], skip: int = 0):
    """Yield MiniBatch from a dataset that may produce Samples or
    MiniBatches.

    `skip`: fast-forward past the first `skip` batches — resume support.
    Training datasets replay deterministic epoch permutations from their
    seed, so skipping the batches a checkpointed run already consumed
    re-aligns the stream and makes resumed training bit-for-bit equal to
    the uninterrupted run. Samples are skipped without stacking (train
    streams are infinite, every batch is full), so the cost is bare
    iteration.

    Training streams pass through the fault-injection point
    `data@<position>` (utils/faults): a data-loader failure fires when
    the batch at that global stream position (skip + local index — the
    step number that will consume it) is fetched, so injected loader
    faults are deterministic across resumes."""
    it = dataset.data(train=train)
    first = next(it, None)
    if first is None:
        return iter(())
    import itertools

    chained = itertools.chain([first], it)
    if isinstance(first, MiniBatch):
        for _ in range(skip):
            next(chained, None)
        return _fault_gate(chained, skip) if train else chained
    if batch_size is None:
        raise ValueError("dataset yields Samples; batch_size is required")
    for _ in range(skip * batch_size):
        next(chained, None)
    batched = SampleToMiniBatch(batch_size)(chained)
    return _fault_gate(batched, skip) if train else batched


def _fault_gate(it, start: int):
    """Wrap a training batch stream with the `data` fault point; the
    skip fast-forward is NOT gated (replays must not re-fire)."""
    from bigdl_tpu.utils import faults

    def gen():
        pos = start
        for mb in it:
            faults.get_plan().maybe_raise("data", pos)
            pos += 1
            yield mb

    return gen()


def _to_device(x):
    if x is None:
        return None
    if isinstance(x, tuple):
        return tuple(jnp.asarray(e) for e in x)
    return jnp.asarray(x)


class Optimizer:
    """Builder facade (reference: optim/Optimizer.scala#Optimizer.apply)."""

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, batch_size: Optional[int] = None,
                 seed: int = 42):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size
        self.seed = seed
        self.optim_method: OptimMethod = SGD(learningrate=1e-2)
        self.end_when: Trigger = Trigger.max_epoch(1)
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: List[ValidationMethod] = []
        self.validation_batch_size: Optional[int] = None
        self.checkpoint: Optional[Checkpoint] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.train_summary = None
        self.validation_summary = None
        self.grad_clip_const: Optional[tuple] = None
        self.grad_clip_norm: Optional[float] = None
        self.log_every = 1
        self._resume = False
        self.mesh = None
        self.mesh_axis = "data"
        self.mesh_zero = 1  # 2 = ZeRO-2 weight sharding (set_mesh)
        self.precision = None  # None → full fp32; Policy → mixed precision
        self.grad_accum = 1
        self.anomaly_guard = None  # utils.anomaly.AnomalyGuard or None

    # ------------------------------------------------------- builder surface
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(methods)
        self.validation_batch_size = batch_size or self.batch_size
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       sharded: bool = False,
                       async_save: bool = False) -> "Optimizer":
        """`sharded=True` saves the ZeRO flat optimizer state as
        per-shard units with a manifest-last publish (mesh runs only —
        ISSUE 9; resume reshards across world sizes); `async_save=True`
        moves checkpoint I/O to a background thread so steps never
        stall on disk (serialization/checkpoint.py)."""
        self.checkpoint = Checkpoint(path, sharded=sharded,
                                     async_save=async_save)
        self.checkpoint_trigger = trigger
        return self

    def resume_from_checkpoint(self) -> "Optimizer":
        """Continue from the latest checkpoint under the checkpoint path
        (reference: Optimizer resume + DistriOptimizer retry recovery)."""
        self._resume = True
        return self

    @staticmethod
    def _coerce_summary(summary, cls):
        if isinstance(summary, str):
            return cls(summary, "bigdl_tpu")
        if not hasattr(summary, "add_scalar"):
            raise TypeError(
                f"expected a {cls.__name__} (or a logdir string), got "
                f"{type(summary).__name__}")
        return summary

    def set_train_summary(self, summary) -> "Optimizer":
        from bigdl_tpu.visualization import TrainSummary

        self.train_summary = self._coerce_summary(summary, TrainSummary)
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        from bigdl_tpu.visualization import ValidationSummary

        self.validation_summary = self._coerce_summary(summary, ValidationSummary)
        return self

    def set_gradient_accumulation(self, n: int) -> "Optimizer":
        """Accumulate gradients over `n` micro-batches before each
        optimizer update (effective batch = n × batch_size). TPU-first
        addition (absent in the reference, which scales batch via Spark
        partitions): lets a single chip train at pod-scale batch sizes
        without holding the activations of the full batch."""
        if n < 1:
            raise ValueError("accumulation steps must be >= 1")
        self.grad_accum = n
        return self

    def set_precision(self, policy) -> "Optimizer":
        """Enable mixed precision. `policy` is a `utils.precision.Policy`,
        or one of "bf16"/"mixed" (bf16 compute, fp32 master weights) /
        "fp32" (TPU-first replacement for the reference's FP16 gradient
        wire compression — see utils/precision.py)."""
        from bigdl_tpu.utils.precision import DEFAULT_MIXED, Policy

        if isinstance(policy, str):
            policy = {"bf16": DEFAULT_MIXED, "mixed": DEFAULT_MIXED,
                      "fp32": None}[policy]
        elif policy is not None and not isinstance(policy, Policy):
            raise TypeError(f"expected Policy or str, got {type(policy)}")
        self.precision = policy
        return self

    def set_anomaly_guard(self, guard="skip_step", **kwargs) -> "Optimizer":
        """Arm the numeric-anomaly guard (utils/anomaly.py): every train
        step checks loss + global grad-norm finiteness (and, with
        `spike_factor`, a norm-spike threshold) inside the jitted step
        and discards anomalous updates on device. `guard` is an
        AnomalyGuard, a policy string ('skip_step' | 'rollback' |
        'halt'; kwargs forward to AnomalyGuard), or None to disarm.
        The reference has no such monitoring — a NaN loss silently
        poisons the weights; TensorFlow's health-monitoring contract
        (arXiv 1605.08695 §4.3) is the model here."""
        from bigdl_tpu.utils.anomaly import AnomalyGuard

        if isinstance(guard, str):
            guard = AnomalyGuard(policy=guard, **kwargs)
        elif guard is not None and not isinstance(guard, AnomalyGuard):
            raise TypeError(
                f"expected AnomalyGuard, policy str or None, got "
                f"{type(guard).__name__}")
        elif kwargs:
            raise ValueError("kwargs only apply when guard is a policy str")
        self.anomaly_guard = guard
        return self

    def set_constant_gradient_clipping(self, min_v: float, max_v: float) -> "Optimizer":
        self.grad_clip_const = (min_v, max_v)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> "Optimizer":
        self.grad_clip_norm = clip_norm
        return self

    def set_mesh(self, mesh, axis: str = "data",
                 zero: int = 1) -> "Optimizer":
        """Train data-parallel over a device mesh — switches dispatch to
        DistriOptimizer (the reference dispatches Local vs Distri on the
        dataset type; here the mesh is the explicit signal). `zero=2`
        shards the master fp32 weights across the axis too (ZeRO-2,
        arXiv 2004.13336): 1/n weight residency per device, bit-
        identical fp32 results (parallel/data_parallel.py)."""
        if zero not in (1, 2):
            raise ValueError(f"zero must be 1 or 2, got {zero!r}")
        self.mesh = mesh
        self.mesh_axis = axis
        self.mesh_zero = zero
        return self

    # ------------------------------------------------------------- dispatch
    def optimize(self) -> Module:
        try:
            if self.mesh is not None:
                from bigdl_tpu.parallel.distri_optimizer import \
                    DistriOptimizer

                return DistriOptimizer(
                    self, self.mesh, self.mesh_axis,
                    zero=getattr(self, "mesh_zero", 1)).run()
            if self.checkpoint is not None and self.checkpoint.sharded:
                raise ValueError(
                    "sharded checkpoints shard the ZeRO flat optimizer "
                    "state — they need a mesh (set_mesh); a local run "
                    "can still RESUME from one (the flat layout "
                    "unflattens)")
            return LocalOptimizer(self).run()
        except BaseException:
            # dying run: drain the background checkpoint writer so a
            # restart never races a still-live write of this process
            # (whatever the writer had PUBLISHED before the death
            # exists; an unpublished save stays torn — no MANIFEST —
            # and is skipped by latest()). A secondary writer error
            # here is swallowed: the primary exception is the story,
            # and writer errors surface on their own save()/wait() path
            if self.checkpoint is not None:
                try:
                    self.checkpoint.wait()
                except Exception:
                    pass
            raise


class LocalOptimizer:
    """Single-host jitted training loop (reference: optim/LocalOptimizer.scala).

    Also the base for DistriOptimizer: subclasses override `_make_step`
    and `_make_eval` to insert mesh sharding/collectives.
    """

    def __init__(self, opt: Optimizer):
        self.o = opt
        self.metrics = Metrics()
        # ONE emission path for step telemetry: registry + event log +
        # TrainSummary sink + log line (obs/training.py; ISSUE 5 — the
        # summary scalars and the log line used to be written by two
        # separate blocks here and in DistriOptimizer)
        from bigdl_tpu.obs.training import StepTelemetry

        self.telemetry = StepTelemetry(summary=opt.train_summary,
                                       log_every=opt.log_every)

    # --------------------------------------------------------- step builders
    def _make_step(self) -> Callable:
        model, criterion, method = self.o.model, self.o.criterion, self.o.optim_method
        clip_const, clip_norm = self.o.grad_clip_const, self.o.grad_clip_norm
        precision = self.o.precision
        accum = self.o.grad_accum
        guarded = self.o.anomaly_guard is not None

        from bigdl_tpu.ops.losses import build_train_loss

        loss_call = build_train_loss(model, criterion, precision)

        def grads_of(params, mod_state, bx, by, rng):
            return jax.value_and_grad(
                lambda p: loss_call(p, mod_state, bx, by, rng),
                has_aux=True)(params)

        def clip_and_update(grads, params, slots, lr, stepno):
            if clip_const is not None:
                lo, hi = clip_const
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.clip(g, lo, hi), grads)
            if clip_norm is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(g * g)
                                     for g in jax.tree_util.tree_leaves(grads)))
                scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            return method.update(grads, params, slots, lr, stepno)

        if accum == 1:
            if guarded:
                from bigdl_tpu.utils.anomaly import (
                    global_norm, health_ok, select_update)

                def gstep(params, mod_state, slots, bx, by, lr, stepno,
                          rng, max_gnorm):
                    (loss, new_state), grads = grads_of(params, mod_state,
                                                        bx, by, rng)
                    gnorm = global_norm(grads)  # pre-clip, like the guard
                    ok = health_ok(loss, gnorm, max_gnorm)
                    new_params, new_slots = clip_and_update(
                        grads, params, slots, lr, stepno)
                    # anomalous step: every output is the bit-identical
                    # input — params, slots AND module state keep their
                    # pre-step values on device
                    return (select_update(ok, new_params, params),
                            select_update(ok, new_state, mod_state),
                            select_update(ok, new_slots, slots),
                            loss, ok, gnorm)

                return jax.jit(gstep, donate_argnums=(0, 2))

            def step(params, mod_state, slots, bx, by, lr, stepno, rng):
                (loss, new_state), grads = grads_of(params, mod_state, bx,
                                                    by, rng)
                new_params, new_slots = clip_and_update(grads, params,
                                                        slots, lr, stepno)
                return new_params, new_state, new_slots, loss

            return jax.jit(step, donate_argnums=(0, 2))

        # gradient accumulation: grads-only micro-steps, update every
        # `accum`-th call (Optimizer.set_gradient_accumulation)
        grad_fn = jax.jit(grads_of)
        add_fn = jax.jit(lambda a, g: jax.tree_util.tree_map(
            jnp.add, a, g), donate_argnums=(0,))
        upd_fn = jax.jit(
            lambda acc, params, slots, lr, stepno, n: clip_and_update(
                jax.tree_util.tree_map(lambda g: g / n, acc),
                params, slots, lr, stepno),
            donate_argnums=(0, 1, 2))
        micro = {"acc": None, "n": 0}
        if guarded:
            from bigdl_tpu.utils.anomaly import global_norm, health_ok

            def _health(loss, grads, thr):
                g = global_norm(grads)
                return health_ok(loss, g, thr), g

            health_fn = jax.jit(_health)

        def step(params, mod_state, slots, bx, by, lr, stepno, rng,
                 max_gnorm=None):
            (loss, new_state), grads = grad_fn(params, mod_state, bx, by,
                                               rng)
            if guarded:
                ok, gnorm = health_fn(loss, grads, max_gnorm)
                if not bool(ok):
                    # anomalous micro-batch: its gradients never touch
                    # the accumulator and the NaN-tainted module state
                    # is dropped; the cycle extends by one batch
                    return params, mod_state, slots, loss, ok, gnorm
            micro["acc"] = grads if micro["acc"] is None \
                else add_fn(micro["acc"], grads)
            micro["n"] += 1
            if micro["n"] == accum:
                params, slots = upd_fn(micro["acc"], params, slots, lr,
                                       stepno,
                                       jnp.asarray(accum, jnp.float32))
                micro["acc"], micro["n"] = None, 0
            if guarded:
                return params, new_state, slots, loss, ok, gnorm
            return params, new_state, slots, loss

        def flush(params, slots, lr, stepno):
            """Apply a pending partial accumulator (end trigger fired
            mid-cycle): mean over the micro-batches actually seen, so no
            gradient work is silently discarded."""
            if micro["n"] == 0:
                return params, slots
            params, slots = upd_fn(micro["acc"], params, slots, lr,
                                   stepno,
                                   jnp.asarray(micro["n"], jnp.float32))
            micro["acc"], micro["n"] = None, 0
            return params, slots

        def restore_micro(acc, n):
            """Reinstall a checkpointed mid-cycle accumulator (resume).
            A checkpoint from a run with a LARGER grad_accum can hold
            n >= this run's accum; the `n == accum` update check would
            then never fire again — refuse and restart the cycle."""
            if int(n) >= accum:
                logger.warning(
                    "checkpointed accumulation cycle (%d micro-batches) "
                    "does not fit grad_accum=%d; discarding the partial "
                    "accumulator and restarting the cycle", int(n), accum)
                return
            micro["acc"], micro["n"] = acc, int(n)

        step.flush = flush
        step.micro_state = lambda: (micro["acc"], micro["n"])
        step.restore_micro = restore_micro
        step.clear_micro = lambda: micro.update(acc=None, n=0)
        return step

    def _make_eval(self) -> Callable:
        model, methods = self.o.model, self.o.validation_methods
        precision = self.o.precision

        def eval_step(params, mod_state, bx, by, real_size):
            if precision is not None:
                params = precision.cast_to_compute(params)
                bx = precision.cast_to_compute(bx)
            out, _ = model.apply({"params": params, "state": mod_state}, bx,
                                 training=False)
            if precision is not None:
                out = precision.cast_to_output(out)
            return [m.stats(out, by, real_size) for m in methods]

        return jax.jit(eval_step, static_argnums=(4,))

    # ------------------------------------------------------------ validation
    def _validate(self, variables) -> Dict[str, ValidationResult]:
        o = self.o
        eval_step = self._eval_step
        results = [ValidationResult(0.0, 0.0, m.name) for m in o.validation_methods]
        for mb in _batch_iterator(o.validation_dataset, False,
                                  o.validation_batch_size):
            real = getattr(mb, "real_size", mb.size)
            stats = eval_step(variables["params"], variables["state"],
                              _to_device(mb.input), _to_device(mb.target), real)
            for i, (s, c) in enumerate(stats):
                results[i] = results[i] + ValidationResult(float(s), float(c))
        return {m.name: r for m, r in zip(o.validation_methods, results)}

    def _require_rollback_checkpoint(self) -> None:
        """The anomaly guard's 'rollback' policy has nothing to roll
        back to without a saved checkpoint — shared precondition of the
        local and distributed run loops."""
        from bigdl_tpu.utils.anomaly import AnomalyError

        o = self.o
        if o.checkpoint is None or not o.checkpoint.latest():
            raise AnomalyError(
                "anomaly policy 'rollback' needs a checkpoint "
                "(set_checkpoint) with at least one save; none found")

    # ------------------------------------------------------------------ run
    def run(self) -> Module:
        o = self.o
        rng = jax.random.PRNGKey(o.seed)
        variables = dict(o.model.variables)  # uses existing build or default init
        slots = o.optim_method.init_slots(variables["params"])
        # "nupdates" counts optimizer updates actually APPLIED — it is
        # the stepno/schedule clock. Without the anomaly guard it always
        # equals neval // grad_accum; with the guard, a discarded update
        # (skip_step) or uncounted micro-batch does NOT advance it, so
        # Adam bias correction and LR schedules never skip a step index
        # over an anomaly.
        train_state: Dict[str, Any] = {"epoch": 1, "neval": 0,
                                       "nupdates": 0, "records": 0,
                                       "loss": None, "score": None}
        guard = o.anomaly_guard

        from bigdl_tpu.utils import faults

        plan = faults.get_plan()
        batches = None  # built below; restore() rebuilds it on rollback

        def restore_from_checkpoint(rebuild_stream=True):
            """Reload model/optim/train_state from the newest VALID
            checkpoint (Checkpoint.load falls back past corrupt dirs);
            returns the saved mid-cycle accumulator (or None). Used at
            startup resume and by the anomaly guard's rollback policy."""
            nonlocal variables, slots, batches
            o.checkpoint.wait()  # surface any pending async-save error
            variables, slots, saved, optim_meta = o.checkpoint.load(
                with_optim_meta=True)
            flat_layout = (optim_meta or {}).get("layout") in (
                "zero1_flat", "zero2_flat")
            spec = None
            if flat_layout:
                # checkpoint written by DistriOptimizer: each slot is a flat
                # (padded,) vector over the whole parameter set — unflatten
                # back to the params-pytree layout this loop uses
                from bigdl_tpu.parallel.data_parallel import FlatParamSpec

                spec = FlatParamSpec(variables["params"],
                                     optim_meta["num_shards"])
                slots = jax.tree_util.tree_map(spec.unflatten, slots)
            saved_accum = o.checkpoint.load_accum()
            if saved_accum is not None and flat_layout:
                saved_accum = {"g_acc": spec.unflatten(saved_accum["g_acc"]),
                               "micro_n": saved_accum["micro_n"]}
            train_state.update(saved)
            if "nupdates" not in saved:  # pre-counter checkpoint
                train_state["nupdates"] = \
                    train_state["neval"] // o.grad_accum
            if rebuild_stream:
                batches = _batch_iterator(o.dataset, True, o.batch_size,
                                          skip=train_state["neval"])
            return saved_accum

        # host mirror of the step closure's micro-batch count — drives
        # the nupdates increment at each completed accumulation cycle
        micro_seen = [0]

        def install_accum(saved_accum):
            micro_seen[0] = 0
            if saved_accum is None:
                return
            if hasattr(self._step, "restore_micro"):
                self._step.restore_micro(saved_accum["g_acc"],
                                         int(saved_accum["micro_n"]))
                # mirror what restore_micro actually installed — it
                # refuses (leaves 0) a cycle that doesn't fit this
                # run's grad_accum
                micro_seen[0] = int(self._step.micro_state()[1])
            else:
                logger.warning(
                    "checkpoint holds a mid-cycle accumulator (%d "
                    "micro-batches) but this run has grad_accum=1; the "
                    "partial gradients are discarded",
                    int(saved_accum["micro_n"]))

        saved_accum = None
        if o._resume and o.checkpoint is not None and o.checkpoint.latest():
            saved_accum = restore_from_checkpoint(rebuild_stream=False)
            logger.info("resumed from %s at %s",
                        o.checkpoint._last_loaded, train_state)

        self._step = self._make_step()
        install_accum(saved_accum)
        if o.validation_methods:
            self._eval_step = self._make_eval()

        dataset_size = o.dataset.size()
        # fast-forward the deterministic batch stream to where the
        # checkpointed run stopped: resumed training sees the same
        # batches the uninterrupted run would have
        batches = _batch_iterator(o.dataset, True, o.batch_size,
                                  skip=train_state["neval"])
        pending = None  # deferred (epoch, neval, loss, lr, thr, vars)
        epoch_start = time.perf_counter()
        iter_start = time.perf_counter()

        while not o.end_when(train_state):
            try:
                plan.maybe_preempt(train_state["neval"])
            except faults.Preempted:
                # the worker is dead, not retryable — record the
                # incident (the flight recorder's training-plane
                # trigger, ISSUE 11) and let it propagate
                obs.emit_event("preempted", plane="training",
                               step=train_state["neval"])
                raise
            plan.maybe_raise("step", train_state["neval"])
            with Timer(self.metrics, "data_fetch_s"):
                mb = next(batches)
            if plan.fires("nan", train_state["neval"]):
                mb = faults.poison_minibatch(mb)
            step_rng = jax.random.fold_in(rng, train_state["neval"])
            # schedules and the optimizer's step counter advance per
            # APPLIED update, not per (micro-)batch: a guard-discarded
            # update re-uses its step index, so the schedule clock
            # never skips over an anomaly
            eff_step = train_state["nupdates"]
            lr_state = train_state if o.grad_accum == 1 and guard is None \
                else {**train_state, "neval": eff_step}
            lr = o.optim_method.current_rate(lr_state)
            with Timer(self.metrics, "dispatch_s"):
                step_args = (
                    variables["params"], variables["state"], slots,
                    _to_device(mb.input), _to_device(mb.target),
                    jnp.asarray(lr, jnp.float32),
                    jnp.asarray(eff_step, jnp.int32),
                    step_rng)
                if guard is None:
                    (variables["params"], variables["state"], slots,
                     loss) = self._step(*step_args)
                else:
                    (variables["params"], variables["state"], slots, loss,
                     ok_d, gnorm_d) = self._step(
                        *step_args,
                        jnp.asarray(guard.threshold(), jnp.float32))
            ok_host, gnorm_host = True, None
            if guard is not None:
                # scalar fetch syncs the step — the documented cost of
                # arming the guard (utils/anomaly.py); an anomalous
                # update was already discarded on device either way
                ok_host, gnorm_host = bool(ok_d), float(gnorm_d)
                action = guard.observe(ok_host, gnorm_host,
                                       train_state["neval"])
                if action == "rollback":
                    self._require_rollback_checkpoint()
                    saved_accum = restore_from_checkpoint()
                    if hasattr(self._step, "clear_micro"):
                        self._step.clear_micro()
                    install_accum(saved_accum)
                    continue
            # NOTE: `loss` stays a device array — converting here would
            # block the host on every step and kill async dispatch
            # pipelining. Log/summary emission for step N happens after
            # step N+1 is dispatched (see _emit below), so the loss fetch
            # overlaps the next step's device compute instead of stalling.
            real = getattr(mb, "real_size", mb.size)
            train_state["neval"] += 1
            # advance the update clock only when an update was (or, for
            # a mid-cycle micro-batch, will be) applied: anomalous
            # steps/micro-batches were discarded on device
            if o.grad_accum == 1:
                train_state["nupdates"] += 1 if guard is None \
                    else int(ok_host)
            elif guard is None or ok_host:
                micro_seen[0] += 1
                if micro_seen[0] == o.grad_accum:
                    train_state["nupdates"] += 1
                    micro_seen[0] = 0
            train_state["records"] += real
            train_state["loss"] = loss
            now = time.perf_counter()
            iter_wall = now - iter_start
            iter_start = now
            self.metrics.add("iter_s", iter_wall)
            throughput = real / max(iter_wall, 1e-9)

            if pending is not None:
                self._emit(pending)
            # snapshot the dicts: the loop reassigns variables["params"]
            # next iteration, and _emit must see step-N state, not N+1.
            # Histograms are materialized HERE (np.asarray = host fetch):
            # step-N's param buffers are donated to step N+1's dispatch,
            # so by _emit time the arrays would already be deleted. The
            # fetch blocks until step N finishes — acceptable for a
            # histogram trigger that fires rarely.
            hists = None
            if o.train_summary is not None:
                pt = o.train_summary.get_summary_trigger("Parameters")
                if pt is not None and pt(train_state):
                    hists = [(name, np.asarray(leaf)) for name, leaf
                             in o.model.parameters(variables)]
            pending = (dict(train_state), loss, lr, throughput, real,
                       hists, gnorm_host, ok_host)

            # ---- epoch rollover (the reference counts records vs dataset size)
            if train_state["records"] >= dataset_size:
                train_state["epoch"] += 1
                train_state["records"] = 0
                logger.info("epoch %d done in %.1fs",
                            train_state["epoch"] - 1,
                            time.perf_counter() - epoch_start)
                epoch_start = time.perf_counter()

            # ---- validation
            if (o.validation_trigger is not None
                    and o.validation_trigger(train_state)):
                res = self._validate(variables)
                for name, r in res.items():
                    v, n = r.result()
                    logger.info("validation %s = %.6f (%d)", name, v, n)
                    if o.validation_summary is not None:
                        o.validation_summary.add_scalar(name, v, train_state["neval"])
                first = next(iter(res.values()), None)
                if first is not None:
                    train_state["score"] = first.result()[0]
                    sched = o.optim_method.schedule
                    if hasattr(sched, "on_metric"):
                        sched.on_metric(train_state["score"])

            # ---- checkpoint
            if (o.checkpoint is not None and o.checkpoint_trigger is not None
                    and o.checkpoint_trigger(train_state)):
                accum_state = None
                micro_state = getattr(self._step, "micro_state", None)
                if micro_state is not None:
                    acc, mn = micro_state()
                    if mn:  # mid-cycle: persist the partial accumulator
                        accum_state = {"g_acc": jax.device_get(acc),
                                       "micro_n": mn}
                with Timer(self.metrics, "checkpoint_s"):
                    path = o.checkpoint.save(
                        train_state["neval"], variables, slots,
                        {k: train_state[k] for k in
                         ("epoch", "neval", "nupdates", "records")},
                        accum_state=accum_state)
                logger.info("checkpoint -> %s", path)

        # end trigger may fire mid-accumulation-cycle: flush the partial
        # accumulator so those micro-batches' gradients aren't discarded
        flush = getattr(self._step, "flush", None)
        if flush is not None:
            eff_step = train_state["nupdates"]
            lr = o.optim_method.current_rate(
                {**train_state, "neval": eff_step})
            variables["params"], slots = flush(
                variables["params"], slots,
                jnp.asarray(lr, jnp.float32),
                jnp.asarray(eff_step, jnp.int32))

        if pending is not None:
            self._emit(pending)
        if o.checkpoint is not None:
            # drain the background writer: a failed async save (incl.
            # an injected ckpt_async_torn kill) must fail the run, not
            # vanish with the daemon thread
            o.checkpoint.wait()
        for summary in (o.train_summary, o.validation_summary):
            if summary is not None:
                summary.writer.flush()
        o.model.variables = variables
        return o.model

    def _emit(self, pending) -> None:
        """Telemetry for an already-dispatched step — registry + event
        + TrainSummary sink + log line, all through StepTelemetry;
        called one step late so the loss fetch overlaps device compute.
        The float(loss) here IS the fence for step N (timed as the
        `fence_s` phase). Histogram data arrives pre-materialized (see
        run()): the live param buffers are donated to the next step
        before _emit runs."""
        state, loss, lr, throughput, real, hists, gnorm, ok = pending
        o = self.o
        # the loss fetch piggybacks on the sinks that always needed it
        # (summary scalars, the log line); telemetry alone NEVER adds
        # a device→host sync — on a non-fence step the event simply
        # omits the loss field (StepTelemetry contract)
        fence = (o.train_summary is not None
                 or state["neval"] % o.log_every == 0)
        if not (fence or obs.enabled()):
            return
        if fence:
            with Timer(self.metrics, "fence_s"):
                loss = float(loss)
        else:
            loss = None
        self.telemetry.emit_step(
            epoch=state["epoch"], step=state["neval"], loss=loss,
            lr=lr, throughput=throughput, records=real,
            update_applied=ok, gnorm=gnorm, hists=hists,
            metrics_summary=self.metrics.summary())
