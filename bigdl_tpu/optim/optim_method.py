"""Optimization methods.

Reference parity: optim/SGD.scala, optim/Adam.scala, optim/Adagrad.scala,
optim/Adamax.scala, optim/RMSprop.scala, optim/Ftrl.scala,
optim/AdaDelta.scala, optim/LBFGS.scala (LBFGS lives in lbfgs.py).

TPU-first design: each method is a pure pytree transform

    slots = method.init_slots(params)
    new_params, new_slots = method.update(grads, params, slots, lr, step)

fully jit-traceable; `lr` and `step` arrive as traced scalars from the
host-side schedule (see lr_schedule.py). Because update is leaf-wise over
an arbitrary pytree, the SAME code updates a full replica or a ZeRO-1
shard of the flat parameter vector (bigdl_tpu/parallel/data_parallel.py)
— mirroring how the reference runs its optim method per parameter slice
(optim/DistriOptimizer.scala aggregate step).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.lr_schedule import Default, LearningRateSchedule


def _tree_map(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


class OptimMethod:
    """Base optimizer (reference: optim/OptimMethod.scala)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_schedule: Optional[LearningRateSchedule] = None,
                 weightdecay: float = 0.0):
        self.learningrate = learningrate
        self.schedule = learningrate_schedule or Default()
        self.schedule.base_lr = learningrate
        self.weightdecay = weightdecay

    # -------- host side
    def current_rate(self, state: Dict) -> float:
        """Host-side schedule evaluation (reference: updateHyperParameter)."""
        self.schedule.base_lr = self.learningrate
        return float(self.schedule.rate(state))

    # -------- device side (pure)
    def init_slots(self, params) -> Any:
        return {}

    def update(self, grads, params, slots, lr, step):
        raise NotImplementedError

    def _decay(self, grads, params):
        if self.weightdecay:
            wd = self.weightdecay
            return _tree_map(lambda g, p: g + wd * p, grads, params)
        return grads


class SGD(OptimMethod):
    """SGD with momentum/dampening/nesterov (reference: optim/SGD.scala)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 weightdecay: float = 0.0,
                 momentum: float = 0.0,
                 dampening: Optional[float] = None,
                 nesterov: bool = False,
                 learningrate_schedule: Optional[LearningRateSchedule] = None):
        sched = learningrate_schedule or Default(learningrate_decay)
        super().__init__(learningrate, sched, weightdecay)
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError("nesterov requires momentum > 0 and dampening = 0")

    def init_slots(self, params):
        if self.momentum:
            return {"velocity": _tree_map(jnp.zeros_like, params)}
        return {}

    def update(self, grads, params, slots, lr, step):
        grads = self._decay(grads, params)
        if self.momentum:
            mu, damp = self.momentum, self.dampening
            vel = _tree_map(lambda v, g: mu * v + (1 - damp) * g,
                            slots["velocity"], grads)
            if self.nesterov:
                eff = _tree_map(lambda g, v: g + mu * v, grads, vel)
            else:
                eff = vel
            new_params = _tree_map(lambda p, d: p - lr * d, params, eff)
            return new_params, {"velocity": vel}
        new_params = _tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, slots


class Adam(OptimMethod):
    """Adam (reference: optim/Adam.scala)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
                 weightdecay: float = 0.0,
                 learningrate_schedule: Optional[LearningRateSchedule] = None):
        super().__init__(learningrate,
                         learningrate_schedule or Default(learningrate_decay),
                         weightdecay)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, params):
        return {"m": _tree_map(jnp.zeros_like, params),
                "v": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, lr, step):
        grads = self._decay(grads, params)
        t = step + 1
        b1, b2 = self.beta1, self.beta2
        m = _tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, slots["m"], grads)
        v = _tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, slots["v"], grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        new_params = _tree_map(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.epsilon),
            params, m, v)
        return new_params, {"m": m, "v": v}


class Adagrad(OptimMethod):
    """Adagrad (reference: optim/Adagrad.scala)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0,
                 weightdecay: float = 0.0):
        super().__init__(learningrate, Default(learningrate_decay), weightdecay)

    def init_slots(self, params):
        return {"accum": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, lr, step):
        grads = self._decay(grads, params)
        accum = _tree_map(lambda a, g: a + g * g, slots["accum"], grads)
        new_params = _tree_map(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10), params, grads, accum)
        return new_params, {"accum": accum}


class Adamax(OptimMethod):
    """Adamax (reference: optim/Adamax.scala)."""

    def __init__(self, learningrate: float = 2e-3,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-38):
        super().__init__(learningrate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, params):
        return {"m": _tree_map(jnp.zeros_like, params),
                "u": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, lr, step):
        t = step + 1
        b1 = self.beta1
        m = _tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, slots["m"], grads)
        u = _tree_map(lambda u_, g: jnp.maximum(self.beta2 * u_, jnp.abs(g) + self.epsilon),
                      slots["u"], grads)
        bc = 1 - b1 ** t
        new_params = _tree_map(lambda p, m_, u_: p - (lr / bc) * m_ / u_, params, m, u)
        return new_params, {"m": m, "u": u}


class RMSprop(OptimMethod):
    """RMSprop (reference: optim/RMSprop.scala)."""

    def __init__(self, learningrate: float = 1e-2,
                 learningrate_decay: float = 0.0,
                 decayrate: float = 0.99, epsilon: float = 1e-8):
        super().__init__(learningrate, Default(learningrate_decay))
        self.decayrate = decayrate
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"ms": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, lr, step):
        dr = self.decayrate
        ms = _tree_map(lambda s, g: dr * s + (1 - dr) * g * g, slots["ms"], grads)
        new_params = _tree_map(
            lambda p, g, s: p - lr * g / (jnp.sqrt(s) + self.epsilon), params, grads, ms)
        return new_params, {"ms": ms}


class AdaDelta(OptimMethod):
    """AdaDelta (reference: optim/Adadelta.scala)."""

    def __init__(self, decayrate: float = 0.9, epsilon: float = 1e-6):
        super().__init__(learningrate=1.0)
        self.rho = decayrate
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"accum": _tree_map(jnp.zeros_like, params),
                "accum_update": _tree_map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, lr, step):
        rho, eps = self.rho, self.epsilon
        accum = _tree_map(lambda a, g: rho * a + (1 - rho) * g * g,
                          slots["accum"], grads)
        delta = _tree_map(
            lambda au, a, g: jnp.sqrt(au + eps) / jnp.sqrt(a + eps) * g,
            slots["accum_update"], accum, grads)
        accum_update = _tree_map(lambda au, d: rho * au + (1 - rho) * d * d,
                                 slots["accum_update"], delta)
        new_params = _tree_map(lambda p, d: p - lr * d, params, delta)
        return new_params, {"accum": accum, "accum_update": accum_update}


class Ftrl(OptimMethod):
    """FTRL-proximal (reference: optim/Ftrl.scala)."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0):
        super().__init__(learningrate)
        self.lr_power = learningrate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength

    def init_slots(self, params):
        return {
            "accum": _tree_map(
                lambda p: jnp.full_like(p, self.init_accum), params),
            "linear": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, params, slots, lr, step):
        lp = self.lr_power

        def upd(p, g, a, l):
            new_a = a + g * g
            sigma = (new_a ** -lp - a ** -lp) / lr
            new_l = l + g - sigma * p
            quad = new_a ** -lp / lr + 2 * self.l2
            pre = jnp.clip(new_l, -self.l1, self.l1) - new_l
            new_p = pre / quad
            return new_p, new_a, new_l

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_a = jax.tree_util.tree_leaves(slots["accum"])
        flat_l = jax.tree_util.tree_leaves(slots["linear"])
        out_p, out_a, out_l = [], [], []
        for p, g, a, l in zip(flat_p, flat_g, flat_a, flat_l):
            np_, na, nl = upd(p, g, a, l)
            out_p.append(np_)
            out_a.append(na)
            out_l.append(nl)
        unf = jax.tree_util.tree_unflatten
        return unf(treedef, out_p), {"accum": unf(treedef, out_a),
                                     "linear": unf(treedef, out_l)}
