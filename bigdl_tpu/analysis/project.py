"""ProjectContext — the shared pass-1 index behind graftlint's
cross-module rules (ISSUE 13 tentpole).

Per-file rules see one `FileContext` at a time; the contracts added
since PR 6 span modules: event kinds produced in `serving/engine.py`
are consumed by `obs/journey.py` and the flight-recorder trigger set,
metric families registered in one module are bumped from others,
background threads share attributes with hot paths, and
`donate_argnums` sites donate buffers that callers elsewhere must not
read again. `ProjectContext` is built ONCE per lint run (pass 1) from
the already-parsed `FileContext`s — no file is ever parsed twice — and
pass 2 hands it to every `ProjectRule`.

Indexes collected in one walk per file:

* `files`            — repo-relative path → FileContext (module index)
* `trace_roots`      — jit/shard_map-traced function defs per file
* `event_registry`   — the machine-readable `EVENT_KINDS` dict
                       (obs/events.py, or a fixture tree's own copy)
* `event_producers`  — `emit_event("kind", ...)` / `<log>.emit("kind",
                       ...)` call sites with their visible keyword set
* `event_consumers`  — kind references on the read side: `.events("k")`
                       filters and `<rec>["kind"] == "k"`-shaped
                       comparisons/memberships
* `metric_registrations` / `metric_bumps` / `metric_name_refs`
                     — registry `counter/gauge/histogram` calls with
                       name + labelnames + the binding that holds the
                       family, `.labels/.inc/.set/.observe` bump sites
                       resolved back to their binding, and
                       `registry.get("name")` by-name references
* `donating_defs` / `donating_factories`
                     — functions jitted with `donate_argnums`/
                       `donate_argnames` (decorated defs, and factory
                       functions RETURNING such a jit) with the donated
                       positions
* `thread_classes`   — classes that start a background thread
                       (`threading.Thread(target=self.m)`, an event-log
                       `add_listener(self.m)` subscription, or a
                       local-closure target inside a method) with their
                       lock/synchronized attributes and method table

Everything is pure stdlib AST bookkeeping; the heavy semantic judgement
lives in the rules (`analysis/rules/*_contract.py` etc.).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from bigdl_tpu.analysis.astutil import (call_name, dotted, int_tuple,
                                        jit_decoration, last_segment,
                                        str_tuple)
from bigdl_tpu.analysis.engine import FileContext

# observers called with the ProjectContext each time one is BUILT —
# tests/test_graftlint.py hooks this to pin "built once per run"
BUILD_OBSERVERS: List[Callable[["ProjectContext"], None]] = []

# metric-family snapshots share the "kind" key with event records
# (`fam["kind"] == "histogram"` in obs_report/provenance) — these
# literals are a deliberate carve-out of the event-kind consumer check
METRIC_FAMILY_KINDS = frozenset(
    {"counter", "gauge", "histogram", "untyped"})

_REGISTRY_RECEIVERS = ("reg", "registry")
_BUMP_METHODS = frozenset({"inc", "dec", "set", "observe", "quantile"})
# attribute methods that MUTATE their receiver (shared-state writes for
# the lock-discipline rule)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "remove", "discard", "clear", "update", "add",
    "setdefault", "popitem", "sort", "reverse", "put", "put_nowait"})
# constructors whose instances are themselves synchronization points —
# writes through them need no extra lock
_SYNC_TYPES = frozenset({
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue"})
_LOCK_TYPES = frozenset({"Lock", "RLock"})


@dataclasses.dataclass(frozen=True)
class EventProducer:
    path: str
    node: ast.Call
    kind: str
    fields: Tuple[str, ...]       # visible keyword names
    has_splat: bool               # **kwargs present → fields incomplete


@dataclasses.dataclass(frozen=True)
class EventConsumer:
    path: str
    node: ast.AST
    kind: str
    form: str                     # "events-call" | "kind-compare"


@dataclasses.dataclass(frozen=True)
class EventRegistry:
    path: str
    line: int
    # kind → (required, optional) — None tuples mean the entry was not
    # a literal dict, so field checks are waived for that kind
    kinds: Dict[str, Tuple[Optional[Tuple[str, ...]],
                           Optional[Tuple[str, ...]]]]


@dataclasses.dataclass(frozen=True)
class MetricRegistration:
    path: str
    node: ast.Call
    name: Optional[str]           # literal family name, None if dynamic
    pattern: Optional[str]        # f-string name with '*' placeholders
    kind: str                     # counter | gauge | histogram
    labelnames: Optional[Tuple[str, ...]]  # None = unresolvable
    binding: Optional[str]        # "ClassName.attr" / "module:name"
    chained_labels: Optional[ast.Call]  # .labels(...) chained on reg
    inline_bumped: bool           # chain ends in .inc/.observe/...

    def matches(self, name: str) -> bool:
        if self.name is not None:
            return self.name == name
        if self.pattern is None:
            return False
        parts = self.pattern.split("*")
        if not name.startswith(parts[0]) or not name.endswith(parts[-1]):
            return False
        return len(name) >= sum(len(p) for p in parts)


@dataclasses.dataclass(frozen=True)
class MetricBump:
    path: str
    node: ast.Call
    binding: Optional[str]
    base_name: str                # receiver attr/name for diagnostics
    method: str
    label_names: Optional[Tuple[str, ...]]  # when a .labels() in chain


@dataclasses.dataclass(frozen=True)
class MetricNameRef:
    path: str
    node: ast.Call
    name: str


@dataclasses.dataclass
class ThreadClass:
    path: str
    node: ast.ClassDef
    methods: Dict[str, ast.FunctionDef]
    # entrypoint method names (Thread target / add_listener callback)
    entry_methods: List[str]
    # (enclosing method name, local thread-fn defs incl. helpers)
    closure_entries: List[Tuple[str, List[ast.FunctionDef]]]
    lock_attrs: Set[str]          # self.X = threading.(R)Lock()
    sync_attrs: Set[str]          # self.X = Queue()/Event()/... (+locks)


class ProjectContext:
    """One parse of the tree, shared by every cross-module rule."""

    def __init__(self, root: str, files: Dict[str, FileContext]):
        self.root = root
        self.files = dict(sorted(files.items()))
        self.trace_roots: Dict[str, List[ast.FunctionDef]] = {}
        self.event_registries: List[EventRegistry] = []
        self.event_producers: List[EventProducer] = []
        self.event_consumers: List[EventConsumer] = []
        self.metric_registrations: List[MetricRegistration] = []
        self.metric_bumps: List[MetricBump] = []
        self.metric_name_refs: List[MetricNameRef] = []
        self.donating_defs: Dict[str, Tuple[int, ...]] = {}
        self.donating_factories: Dict[str, Tuple[int, ...]] = {}
        # project-wide def-name counts: call-site resolution is by
        # bare last segment, so a name is only trustworthy when
        # exactly one def in the project carries it
        self.def_counts: Dict[str, int] = {}
        self.thread_classes: List[ThreadClass] = []
        for path, ctx in self.files.items():
            self._index_file(path, ctx)
        for fn in BUILD_OBSERVERS:
            fn(self)

    @property
    def event_registry(self) -> Optional[EventRegistry]:
        """The authoritative EVENT_KINDS registry (first by path)."""
        return self.event_registries[0] if self.event_registries else None

    # ----------------------------------------------------------- indexing
    def _index_file(self, path: str, ctx: FileContext) -> None:
        roots: List[ast.FunctionDef] = []
        shard_bodies: Set[str] = set()
        defs_by_name: Dict[str, ast.FunctionDef] = {}
        kind_compares: List[ast.Compare] = []
        # scopes that alias an event record's kind into a local
        # (`kind = e.get("kind")`): only inside those do comparisons
        # on a bare `kind` name count as event-kind consumers — scopes
        # with their own `kind` locals (serializer "__kind__" specs,
        # lint internals) stay out
        alias_scopes: Set[ast.AST] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._index_event_registry(path, node)
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "kind" \
                        and _is_kind_expr(node.value):
                    _link_parents(ctx)
                    alias_scopes.add(_enclosing_scope(node))
            elif isinstance(node, ast.Call):
                self._index_call(path, ctx, node)
                if last_segment(call_name(node)) == "shard_map" \
                        and node.args \
                        and isinstance(node.args[0], ast.Name):
                    shard_bodies.add(node.args[0].id)
            elif isinstance(node, ast.Compare):
                kind_compares.append(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.def_counts[node.name] = \
                    self.def_counts.get(node.name, 0) + 1
                defs_by_name.setdefault(node.name, node)
                jit = jit_decoration(node)
                if jit is not None:
                    roots.append(node)
                    donated = _decorated_donation(node)
                    if donated:
                        _add_unambiguous(self.donating_defs,
                                         node.name, donated)
                else:
                    donated = _factory_donation(node)
                    if donated:
                        _add_unambiguous(self.donating_factories,
                                         node.name, donated)
            elif isinstance(node, ast.ClassDef):
                tc = _thread_class(path, node)
                if tc is not None:
                    self.thread_classes.append(tc)
        if kind_compares:
            _link_parents(ctx)
        for node in kind_compares:
            self._index_kind_compare(path, node, alias_scopes)
        for fname in sorted(shard_bodies):
            if fname in defs_by_name:
                roots.append(defs_by_name[fname])
        if roots:
            self.trace_roots[path] = roots

    def _index_event_registry(self, path: str, node) -> None:
        target = node.target if isinstance(node, ast.AnnAssign) \
            else (node.targets[0] if len(node.targets) == 1 else None)
        if not isinstance(target, ast.Name) \
                or target.id != "EVENT_KINDS" \
                or not isinstance(node.value, ast.Dict):
            return
        kinds: Dict[str, Tuple[Optional[Tuple[str, ...]],
                               Optional[Tuple[str, ...]]]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            req = opt = None
            if isinstance(v, ast.Dict):
                spec = {kk.value: vv for kk, vv in zip(v.keys, v.values)
                        if isinstance(kk, ast.Constant)}
                req = _str_seq(spec.get("required"))
                opt = _str_seq(spec.get("optional"))
            kinds[k.value] = (req, opt)
        self.event_registries.append(EventRegistry(
            path, node.lineno, kinds))
        self.event_registries.sort(key=lambda r: r.path)

    def _index_call(self, path: str, ctx: FileContext,
                    node: ast.Call) -> None:
        name = call_name(node)
        seg = last_segment(name)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        # --- event producers -------------------------------------------
        if (seg == "emit_event" or (attr == "emit"
                                    and _is_event_log(node.func.value))) \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            self.event_producers.append(EventProducer(
                path, node, node.args[0].value,
                tuple(kw.arg for kw in node.keywords
                      if kw.arg is not None),
                any(kw.arg is None for kw in node.keywords)))
        # --- event consumers: EventLog.events("kind", ...) -------------
        if attr == "events":
            kind_arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "kind":
                    kind_arg = kw.value
            if isinstance(kind_arg, ast.Constant) \
                    and isinstance(kind_arg.value, str):
                self.event_consumers.append(EventConsumer(
                    path, kind_arg, kind_arg.value, "events-call"))
        # --- metric registrations --------------------------------------
        if attr in ("counter", "gauge", "histogram") \
                and _is_registry(node.func.value):
            self.metric_registrations.append(
                _metric_registration(path, ctx, node, attr))
        # --- metric by-name references ---------------------------------
        if attr == "get" and _is_registry(node.func.value) \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            self.metric_name_refs.append(MetricNameRef(
                path, node, node.args[0].value))
        # --- metric bumps ----------------------------------------------
        if attr in _BUMP_METHODS or attr == "labels":
            bump = _metric_bump(path, ctx, node, attr)
            if bump is not None:
                self.metric_bumps.append(bump)

    def _index_kind_compare(self, path: str, node: ast.Compare,
                            alias_scopes) -> None:
        """`<rec>.get("kind") == "x"` / `kind in ("a", "b")`-shaped
        consumer references (both operand orders). The bare-`kind`
        form only counts inside a scope that aliases
        `kind = <rec>["kind"]` (see _index_file)."""
        if len(node.ops) != 1 or not isinstance(
                node.ops[0], (ast.Eq, ast.NotEq, ast.In, ast.NotIn)):
            return
        sides = [node.left, node.comparators[0]]

        def counts(s):
            if isinstance(s, ast.Name):
                return _is_kind_expr(s) \
                    and _enclosing_scope(s) in alias_scopes
            return _is_kind_expr(s)

        if not any(counts(s) for s in sides):
            return
        for side in sides:
            for lit in _str_literals(side):
                self.event_consumers.append(EventConsumer(
                    path, side, lit, "kind-compare"))


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _str_seq(node) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = tuple(e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
        if len(out) == len(node.elts):
            return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return None


def _str_literals(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _is_kind_expr(node) -> bool:
    """An expression reading the "kind" key: `x["kind"]`,
    `x.get("kind")`, or a bare variable literally named `kind`."""
    if isinstance(node, ast.Name) and node.id == "kind":
        return True
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and node.slice.value == "kind":
        return True
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value == "kind":
        return True
    return False


def _is_event_log(node) -> bool:
    """Receiver of an `.emit(...)` that is plausibly an EventLog: the
    `get_event_log()` accessor or a name carrying 'log'."""
    if isinstance(node, ast.Call) \
            and last_segment(call_name(node)) == "get_event_log":
        return True
    name = dotted(node)
    return name is not None and "log" in last_segment(name).lower()


def _is_registry(node) -> bool:
    """Receiver of `.counter/.gauge/.histogram/.get` that is plausibly
    a MetricsRegistry: `get_registry()` or a `reg`/`registry`-named
    binding (the repo convention)."""
    if isinstance(node, ast.Call) \
            and last_segment(call_name(node)) == "get_registry":
        return True
    name = dotted(node)
    if name is None:
        return False
    seg = last_segment(name)
    return seg in _REGISTRY_RECEIVERS or seg.endswith("_reg") \
        or seg.endswith("_registry")


def _binding_of(path: str, node) -> Optional[str]:
    """Key of the assignment target an expression ultimately lands in:
    'path:Class.attr' / 'path::name' — path-qualified so same-named
    classes/attrs in different files never collide. `node` must carry
    ._gl_parent links (set by _link_parents)."""
    cur = node
    while True:
        parent = getattr(cur, "_gl_parent", None)
        if parent is None:
            return None
        if isinstance(parent, ast.Assign):
            if len(parent.targets) == 1:
                return _target_key(path, parent.targets[0], parent)
            return None
        if isinstance(parent, (ast.Call, ast.Attribute, ast.DictComp,
                               ast.ListComp, ast.SetComp, ast.Dict,
                               ast.Tuple, ast.IfExp, ast.keyword)):
            cur = parent
            continue
        return None


def _target_key(path: str, target, node) -> Optional[str]:
    cls = _enclosing_class(node)
    prefix = f"{path}:{cls.name}." if cls is not None else f"{path}::"
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return prefix + target.attr
    if isinstance(target, ast.Name):
        return prefix + target.id
    return None


def _enclosing_scope(node) -> Optional[ast.AST]:
    """Nearest enclosing function def (or None at module level) via
    the _gl_parent links."""
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
        cur = getattr(cur, "_gl_parent", None)
    return None


def _enclosing_class(node) -> Optional[ast.ClassDef]:
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_gl_parent", None)
    return None


def _link_parents(ctx: FileContext) -> None:
    """Stamp child→parent links once per file (idempotent); cheaper to
    navigate than FileContext.parent's dict for the hot chains here."""
    if getattr(ctx.tree, "_gl_linked", False):
        return
    for parent in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(parent):
            child._gl_parent = parent  # type: ignore[attr-defined]
    ctx.tree._gl_linked = True  # type: ignore[attr-defined]


def _metric_registration(path: str, ctx: FileContext, node: ast.Call,
                         kind: str) -> MetricRegistration:
    _link_parents(ctx)
    name = pattern = None
    if node.args:
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            name = a0.value
        elif isinstance(a0, ast.JoinedStr):
            parts = []
            for v in a0.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("*")
            pattern = "".join(parts)
    labelnames: Optional[Tuple[str, ...]] = ()
    ln_node = None
    if len(node.args) >= 3:
        ln_node = node.args[2]
    for kw in node.keywords:
        if kw.arg == "labelnames":
            ln_node = kw.value
    if ln_node is not None:
        labelnames = _str_seq(ln_node)
    # chained `.labels(...)` / terminal bump on the registration chain
    chained_labels = None
    inline_bumped = False
    cur = node
    while True:
        parent = getattr(cur, "_gl_parent", None)
        if isinstance(parent, ast.Attribute):
            gp = getattr(parent, "_gl_parent", None)
            if isinstance(gp, ast.Call) and gp.func is parent:
                if parent.attr == "labels" and chained_labels is None:
                    chained_labels = gp
                elif parent.attr in _BUMP_METHODS:
                    inline_bumped = True
                cur = gp
                continue
        break
    return MetricRegistration(path, node, name, pattern, kind,
                              labelnames, _binding_of(path, node),
                              chained_labels, inline_bumped)


def _receiver_base(node):
    """Walk a bump chain `self._m_x[...].labels(...).inc()` down to its
    base Name / self-attribute; returns (base node, saw_labels_call)."""
    saw_labels = None
    cur = node
    while True:
        if isinstance(cur, ast.Call):
            if isinstance(cur.func, ast.Attribute) \
                    and cur.func.attr == "labels":
                saw_labels = cur
                cur = cur.func.value
                continue
            return None, saw_labels
        if isinstance(cur, ast.Subscript):
            cur = cur.value
            continue
        if isinstance(cur, ast.Attribute):
            if isinstance(cur.value, ast.Name) \
                    and cur.value.id == "self":
                return cur, saw_labels
            cur = cur.value
            continue
        if isinstance(cur, ast.Name):
            return cur, saw_labels
        return None, saw_labels


def _metric_bump(path: str, ctx: FileContext, node: ast.Call,
                 attr: str) -> Optional[MetricBump]:
    _link_parents(ctx)
    if attr == "labels":
        # only terminal .labels(...) starts a bump record; a .labels in
        # the middle of an .inc() chain is folded into that bump below
        parent = getattr(node, "_gl_parent", None)
        if isinstance(parent, ast.Attribute) \
                and parent.attr in _BUMP_METHODS:
            return None
        recv = node.func.value
        labels_call = node
    else:
        recv, labels_call = node.func.value, None
        if isinstance(recv, ast.Call) \
                and isinstance(recv.func, ast.Attribute) \
                and recv.func.attr == "labels":
            labels_call = recv
    base, chain_labels = _receiver_base(
        labels_call if labels_call is not None else recv)
    if labels_call is None and chain_labels is not None:
        labels_call = chain_labels
    if base is None:
        return None
    cls = _enclosing_class(node)
    if isinstance(base, ast.Attribute):
        base_name = base.attr
        binding = (f"{path}:{cls.name}.{base_name}" if cls is not None
                   else None)
    else:
        base_name = base.id
        # a plain name inside a class is a local/loop variable (often a
        # child fetched out of a family dict) — unresolvable by design
        binding = None if cls is not None else f"{path}::{base_name}"
    label_names = None
    if labels_call is not None:
        if any(kw.arg is None for kw in labels_call.keywords):
            label_names = None  # **labels splat — unknowable
        else:
            label_names = tuple(sorted(
                kw.arg for kw in labels_call.keywords))
    return MetricBump(path, node, binding, base_name, attr, label_names)


# --------------------------------------------------------------------------
# donation indexing
# --------------------------------------------------------------------------

def _donation_kw(call: ast.Call,
                 target_fn=None) -> Tuple[int, ...]:
    """Donated positions declared on a jit call: `donate_argnums`
    directly, plus `donate_argnames` resolved to positions when the
    jitted function's def is visible (`target_fn`)."""
    out: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            out.extend(int_tuple(kw.value))
        elif kw.arg == "donate_argnames":
            names.extend(str_tuple(kw.value))
    if names and target_fn is not None:
        params = [a.arg for a in target_fn.args.posonlyargs] \
            + [a.arg for a in target_fn.args.args]
        for n in names:
            if n in params:
                out.append(params.index(n))
    return tuple(sorted(set(out)))


_JIT_NAMES = {"jit", "pjit"}


def _add_unambiguous(index: Dict[str, Tuple[int, ...]], name: str,
                     donated: Tuple[int, ...]) -> None:
    """Record a donating callable under its bare name; two same-named
    defs with DIFFERENT donated positions make the name ambiguous and
    it is dropped (conservative — call-site resolution is by last
    segment only)."""
    prior = index.get(name)
    if prior is not None and prior != donated:
        index[name] = ()
    elif prior is None:
        index[name] = donated


def is_donating_jit_call(call: ast.Call) -> Tuple[int, ...]:
    """Donated positions of a `jax.jit(f, donate_argnums=...)` call
    expression (empty when it is not one). `donate_argnames` on a
    bare jit expression cannot be resolved to positions without the
    target def — decorated defs and factory returns (where the def is
    visible) handle argnames via _decorated/_factory_donation."""
    if last_segment(call_name(call)) in _JIT_NAMES:
        return _donation_kw(call)
    return ()


def _decorated_donation(fn) -> Tuple[int, ...]:
    """Donated positions declared by a @jit/@partial(jit, ...)
    decorator on `fn` (donate_argnames resolve against `fn`'s own
    signature)."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = last_segment(call_name(dec))
            if name in _JIT_NAMES:
                return _donation_kw(dec, fn)
            if name == "partial" and dec.args \
                    and last_segment(dotted(dec.args[0])) in _JIT_NAMES:
                return _donation_kw(dec, fn)
    return ()


def walk_skipping_nested_defs(fn) -> Iterator[ast.AST]:
    """Yield `fn`'s body nodes, pruning nested function/lambda
    subtrees — an inner helper's statements must not be attributed to
    the outer function."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _factory_donation(fn) -> Tuple[int, ...]:
    """Donated positions when `fn` itself RETURNS a donating jit
    callable (the make_*_step factory pattern) — nested defs pruned
    from the traversal so an inner helper's `return jax.jit(...)`
    never makes the OUTER function claim to donate; donate_argnames
    resolve against the jitted local def when it is a sibling."""
    local_defs = {n.name: n for n in ast.walk(fn)
                  if isinstance(n, ast.FunctionDef) and n is not fn}
    for node in walk_skipping_nested_defs(fn):
        if isinstance(node, ast.Return) \
                and isinstance(node.value, ast.Call):
            call = node.value
            if last_segment(call_name(call)) not in _JIT_NAMES:
                continue
            target = None
            if call.args and isinstance(call.args[0], ast.Name):
                target = local_defs.get(call.args[0].id)
            donated = _donation_kw(call, target)
            if donated:
                return donated
    return ()


# --------------------------------------------------------------------------
# thread / lock indexing
# --------------------------------------------------------------------------

def _thread_class(path: str, node: ast.ClassDef
                  ) -> Optional[ThreadClass]:
    methods = {n.name: n for n in node.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    lock_attrs: Set[str] = set()
    sync_attrs: Set[str] = set()
    entry_methods: List[str] = []
    closure_entries: List[Tuple[str, List[ast.FunctionDef]]] = []
    for mname, m in methods.items():
        local_defs = {n.name: n for n in ast.walk(m)
                      if isinstance(n, ast.FunctionDef) and n is not m}
        for sub in ast.walk(m):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call):
                ctor = last_segment(call_name(sub.value))
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        if ctor in _LOCK_TYPES:
                            lock_attrs.add(t.attr)
                        if ctor in _SYNC_TYPES:
                            sync_attrs.add(t.attr)
            if not isinstance(sub, ast.Call):
                continue
            target = _thread_target(sub)
            if target is None:
                continue
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" \
                    and target.attr in methods:
                entry_methods.append(target.attr)
            elif isinstance(target, ast.Name) \
                    and target.id in local_defs:
                closure_entries.append((mname, _closure_group(
                    local_defs, target.id)))
    if not entry_methods and not closure_entries:
        return None
    return ThreadClass(path, node, methods, sorted(set(entry_methods)),
                       closure_entries, lock_attrs, sync_attrs)


def _thread_target(call: ast.Call):
    """The callable handed to a background execution point:
    `Thread(target=X)` or `<log>.add_listener(X)`."""
    seg = last_segment(call_name(call))
    if seg == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr == "add_listener" and call.args:
        return call.args[0]
    return None


def _closure_group(local_defs: Dict[str, ast.FunctionDef],
                   entry: str) -> List[ast.FunctionDef]:
    """`entry` plus every sibling local function it (transitively)
    calls — the watchdog's boxed()→work() pattern."""
    seen = [entry]
    frontier = [entry]
    while frontier:
        fn = local_defs[frontier.pop()]
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id in local_defs \
                    and sub.func.id not in seen:
                seen.append(sub.func.id)
                frontier.append(sub.func.id)
    return [local_defs[n] for n in seen]
