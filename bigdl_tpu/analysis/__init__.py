"""graftlint — JAX-aware static analysis for this repo's contracts.

`engine` holds the machinery (Rule/ProjectRule registry, suppressions,
baseline, the two-pass driver); `project` the shared single-parse
ProjectContext behind the cross-module rules (ISSUE 13); `astutil`
the generic AST helpers; `rules/` the repo-specific checks;
`scripts/graftlint.py` the CLI; `tests/test_graftlint.py` the tier-1
gate (full tree clean modulo a shrink-only baseline).
"""

from bigdl_tpu.analysis.engine import (  # noqa: F401
    BaselineEntry, Finding, ProjectRule, Rule, RULES, apply_baseline,
    format_baseline, iter_python_files, lint_file, lint_source,
    load_baseline, parse_baseline, register, run_lint,
)

BASELINE_PATH = "bigdl_tpu/analysis/baseline.toml"
