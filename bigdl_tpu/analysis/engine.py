"""graftlint engine — AST lint infrastructure for the repo's own
contracts.

Stock linters can't see the invariants this codebase lives by: the
#buckets+1 compile contract, "telemetry consumes already-fetched host
values", trace-time env reads baking stale knob values into compiled
executables, timing that must be fenced by a real device→host fetch
because `block_until_ready` lies through the axon tunnel. Each of
those is a *mechanically checkable* pattern; this module is the
machinery, `bigdl_tpu/analysis/rules/` holds the checks.

Pieces:

* `Rule` — one named check over a parsed file (`check(ctx)` yields
  `Finding`s); registered via the `@register` decorator, carries a
  severity and a path scope so e.g. the nn-docstring rule never runs
  over `serving/`.
* `ProjectRule` — a cross-module check (ISSUE 13) run once per lint
  over the shared `ProjectContext` (`analysis/project.py`) that pass 1
  builds from the SAME parsed FileContexts — the two-pass engine
  parses every file exactly once (PARSE_OBSERVERS lets the tier-1
  gate pin that).
* `FileContext` — one file parsed once (AST + source lines + the
  per-line suppression table), shared by every rule.
* suppressions — `# graftlint: disable=rule-a,rule-b` on the offending
  line (or on a comment line directly above it) waives those rules for
  that line; `# graftlint: disable-file=rule-a` anywhere in the file
  waives the whole file. A bare `disable` (no `=`) waives every rule
  for the line. Suppressions are for *intentional* violations (e.g.
  the one deliberate per-step device fetch in the serving engine) —
  write the why next to the directive.
* baseline — `analysis/baseline.toml` grandfathers pre-existing
  findings as (rule, path, count) entries so the gate can land before
  the tree is fully clean. Policy (enforced by tests/test_graftlint.py):
  the baseline may only SHRINK — stale entries that no longer match a
  real finding must be deleted, and new code never gets baselined.

The engine is pure stdlib (ast + re); the tier-1 gate budget is a
full-tree run in well under 10 s on the 1-core host
(tests/test_graftlint.py pins it).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterable, Iterator, List, \
    Optional, Sequence, Tuple, Union

SEVERITIES = ("error", "warning")

# files never worth linting: generated protobuf bindings and bundled
# wire-format shims
DEFAULT_EXCLUDES = (
    "bigdl_tpu/utils/caffe/bigdl_caffe_pb2.py",
    "bigdl_tpu/utils/tf/",
    "tests/fixtures/",
)

# what `scripts/graftlint.py` (and the tier-1 gate) lint when given a
# repo root with no explicit paths
DEFAULT_ROOTS = ("bigdl_tpu", "scripts", "examples", "bench.py",
                 "__graft_entry__.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit. `path` is repo-relative posix; `line` is 1-based."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str

    def key(self) -> Tuple[str, str]:
        return (self.rule, self.path)

    def text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity}: {self.message} [{self.rule}]")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable-file|disable)\s*(?:=\s*([\w,\- ]+))?")


class _Suppressions:
    """Per-file suppression table parsed from comments."""

    def __init__(self, lines: Sequence[str]):
        self.file_rules: set = set()
        self.file_all = False
        # line number -> set of rule names ('*' = all)
        self.by_line: Dict[int, set] = {}
        for i, raw in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            kind, arg = m.group(1), m.group(2)
            rules = {r.strip() for r in arg.split(",")} if arg else {"*"}
            rules.discard("")
            if kind == "disable-file":
                if "*" in rules:
                    self.file_all = True
                self.file_rules |= rules
                continue
            targets = {i}
            # a comment-only directive line applies to the next line
            if raw.lstrip().startswith("#"):
                targets.add(i + 1)
            for t in targets:
                self.by_line.setdefault(t, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if self.file_all or rule in self.file_rules:
            return True
        here = self.by_line.get(line, ())
        return "*" in here or rule in here


# observers called with the repo-relative path each time a file is
# PARSED into a FileContext — tests/test_graftlint.py hooks this to pin
# the "every file parsed exactly once per run" contract of the shared
# two-pass engine (ISSUE 13)
PARSE_OBSERVERS: List[Callable[[str], None]] = []


class FileContext:
    """One source file, parsed once and handed to every rule."""

    def __init__(self, path: str, source: str):
        self.path = path          # repo-relative, posix separators
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for _obs in PARSE_OBSERVERS:
            _obs(path)
        self.suppressions = _Suppressions(self.lines)
        # lazily-built parent map for rules that need upward navigation
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        if self._parents is None:
            self._parents = {}
            for p in ast.walk(self.tree):
                for c in ast.iter_child_nodes(p):
                    self._parents[c] = p
        return self._parents.get(node)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of FunctionDef/AsyncFunctionDef
        containing `node`."""
        out = []
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parent(cur)
        return out


class Rule:
    """Base class. Subclasses set `name`, `severity`, `description`,
    optionally `scope` (path prefixes relative to the repo root; a
    file is checked iff it starts with one of them — empty scope means
    every linted file), and implement `check`."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    scope: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        if not self.scope:
            return True
        return any(path.startswith(s) for s in self.scope)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.name, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, message,
                       self.severity)


class ProjectRule(Rule):
    """A cross-module rule: checked once per run over the shared
    `ProjectContext` (pass 2) instead of per file. Subclasses implement
    `check_project(pctx)`; the per-file `check` is a no-op. Project
    rules run on full-tree lints and wherever an explicit
    `project_scope` is supplied (the fixture trees, `--changed-only`);
    a bare path-subset run skips them — a subset cannot distinguish
    "never bumped" from "bumped in a file outside the subset"."""

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        return ()

    def check_project(self, pctx) -> Iterable[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"{cls.__name__} has no name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"{rule.name}: bad severity {rule.severity!r}")
    if rule.name in RULES:
        raise ValueError(f"duplicate rule {rule.name!r}")
    RULES[rule.name] = rule
    return cls


def _ensure_rules_loaded() -> None:
    # import side effect registers every rule exactly once
    from bigdl_tpu.analysis import rules as _rules  # noqa: F401


# --------------------------------------------------------------------------
# baseline (grandfathered findings)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    count: int = 1
    reason: str = ""


_KV_RE = re.compile(r"^(\w+)\s*=\s*(.+?)\s*$")


def parse_baseline(text: str) -> List[BaselineEntry]:
    """Parse the TOML subset baseline.toml uses: `[[finding]]` tables
    of string/int scalars plus comments. (Python 3.10 image has no
    tomllib; the format stays valid TOML so tooling can read it.)"""
    entries: List[BaselineEntry] = []
    cur: Optional[dict] = None
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[finding]]":
            cur = {}
            entries.append(cur)  # type: ignore[arg-type]
            continue
        m = _KV_RE.match(line)
        if not m or cur is None:
            raise ValueError(f"baseline line {lineno}: cannot parse "
                             f"{raw!r}")
        key, val = m.group(1), m.group(2)
        if val.startswith(('"', "'")):
            # quote-aware: a '#' INSIDE the string is data, and only a
            # comment may follow the closing quote
            q = val[0]
            end = val.find(q, 1)
            if end < 0:
                raise ValueError(f"baseline line {lineno}: "
                                 f"unterminated string {raw!r}")
            rest = val[end + 1:].strip()
            if rest and not rest.startswith("#"):
                raise ValueError(f"baseline line {lineno}: trailing "
                                 f"garbage after string {raw!r}")
            cur[key] = val[1:end]
        else:
            cur[key] = int(val.split("#", 1)[0].strip())
    out = []
    for e in entries:  # type: ignore[assignment]
        if "rule" not in e or "path" not in e:
            raise ValueError(f"baseline entry missing rule/path: {e}")
        out.append(BaselineEntry(e["rule"], e["path"],
                                 int(e.get("count", 1)),
                                 str(e.get("reason", ""))))
    return out


def load_baseline(path: str) -> List[BaselineEntry]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return parse_baseline(f.read())


def format_baseline(entries: Sequence[BaselineEntry]) -> str:
    head = ("# graftlint baseline — grandfathered findings.\n"
            "# POLICY: this file may only shrink. Delete entries as "
            "the findings are\n# fixed; never add entries for new "
            "code (fix or inline-suppress instead).\n")
    chunks = [head]
    for e in entries:
        chunk = (f"\n[[finding]]\nrule = \"{e.rule}\"\n"
                 f"path = \"{e.path}\"\ncount = {e.count}\n")
        if e.reason:
            chunk += f"reason = \"{e.reason}\"\n"
        chunks.append(chunk)
    return "".join(chunks)


def apply_baseline(findings: Sequence[Finding],
                   baseline: Sequence[BaselineEntry]
                   ) -> Tuple[List[Finding], List[BaselineEntry]]:
    """Subtract grandfathered findings. Returns (surviving findings,
    stale entries) — a stale entry matched FEWER current findings than
    its count, i.e. the violation was (partly) fixed and the entry must
    be deleted or shrunk."""
    budget: Dict[Tuple[str, str], int] = {}
    for e in baseline:
        # duplicate (rule, path) entries SUM (hand-edited baselines may
        # split one path across entries with different reasons)
        budget[(e.rule, e.path)] = budget.get((e.rule, e.path), 0) \
            + e.count
    out: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    seen_stale = set()
    stale = []
    for e in baseline:
        k = (e.rule, e.path)
        if budget.get(k, 0) > 0 and k not in seen_stale:
            seen_stale.add(k)
            stale.append(e)
    return out, stale


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def iter_python_files(root: str,
                      roots: Sequence[str] = DEFAULT_ROOTS,
                      excludes: Sequence[str] = DEFAULT_EXCLUDES
                      ) -> Iterator[str]:
    """Repo-relative paths of every lintable .py under `roots`."""
    for r in roots:
        full = os.path.join(root, r)
        if os.path.isfile(full):
            if r.endswith(".py"):
                yield r
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      root).replace(os.sep, "/")
                if any(rel.startswith(x) for x in excludes):
                    continue
                yield rel


def lint_source(rel_path: str, source: str,
                rules: Optional[Sequence[Rule]] = None
                ) -> List[Finding]:
    """Lint source text AS IF it lived at `rel_path` (rule scopes and
    suppressions apply). Backs the fixture tests, where known-bad
    snippets live under tests/fixtures/ but must be judged under a
    scoped path like bigdl_tpu/ops/x.py."""
    _ensure_rules_loaded()
    if rules is None:
        rules = list(RULES.values())
    ctx = FileContext(rel_path, source)
    out: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(rel_path):
            continue
        for f in rule.check(ctx):
            if not ctx.suppressions.suppressed(f.rule, f.line):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _parse_file(root: str, rel_path: str
                ) -> Union[FileContext, Finding]:
    with open(os.path.join(root, rel_path)) as f:
        source = f.read()
    try:
        return FileContext(rel_path, source)
    except SyntaxError as e:
        return Finding("parse-error", rel_path, e.lineno or 1, 1,
                       f"cannot parse: {e.msg}", "error")


def _check_file(ctx: FileContext, rules: Sequence[Rule]
                ) -> List[Finding]:
    out: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(ctx.path):
            continue
        for f in rule.check(ctx):
            if not ctx.suppressions.suppressed(f.rule, f.line):
                out.append(f)
    return out


def lint_file(root: str, rel_path: str,
              rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    _ensure_rules_loaded()
    ctx = _parse_file(root, rel_path)
    if isinstance(ctx, Finding):
        return [ctx]
    if rules is None:
        rules = list(RULES.values())
    return sorted(_check_file(ctx, rules),
                  key=lambda f: (f.path, f.line, f.col, f.rule))


def run_lint(root: str,
             paths: Optional[Sequence[str]] = None,
             rule_names: Optional[Sequence[str]] = None,
             project_scope: Optional[Sequence[str]] = None
             ) -> List[Finding]:
    """Two-pass lint under repo `root` (ISSUE 13).

    Pass 1 parses every target file exactly once into a `FileContext`
    and runs the per-file rules over `paths` (repo-relative; default:
    the whole DEFAULT_ROOTS tree). Pass 2 folds the SAME parsed
    contexts into one `ProjectContext` and runs the cross-module
    `ProjectRule`s over it.

    `project_scope` controls pass 2's view of the project:
      * None + full-tree run → the project is the full tree (the tier-1
        gate's mode); None + explicit `paths` → pass 2 is SKIPPED (a
        bare subset cannot answer cross-module questions);
      * "full" → the ProjectContext is built from the full tree even
        when `paths` is a subset, and project findings are reported
        WHEREVER they anchor — a changed file can break a contract
        whose finding lands in an unchanged file (delete a kind from
        EVENT_KINDS and the orphaned emit sites elsewhere fire), and
        against a gate-clean baseline any project finding is caused by
        the subset (the `--changed-only` mode);
      * an explicit path list → the project is exactly those files
        (the fixture mini-package trees).

    Baseline is NOT applied here — callers subtract it explicitly via
    `apply_baseline` so the stale-entry check stays visible."""
    _ensure_rules_loaded()
    if rule_names is None:
        rules = list(RULES.values())
    else:
        unknown = [n for n in rule_names if n not in RULES]
        if unknown:
            raise ValueError(f"unknown rule(s): {unknown}; known: "
                             f"{sorted(RULES)}")
        rules = [RULES[n] for n in rule_names]
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]

    full_tree = paths is None
    if paths is None:
        paths = list(iter_python_files(root))
    contexts: Dict[str, FileContext] = {}
    findings: List[Finding] = []
    for rel in paths:
        ctx = _parse_file(root, rel)
        if isinstance(ctx, Finding):
            findings.append(ctx)
            continue
        contexts[rel] = ctx
        findings.extend(_check_file(ctx, file_rules))

    run_project = project_rules and (
        full_tree or project_scope is not None)
    if run_project:
        if project_scope is not None and project_scope != "full":
            project_paths = list(project_scope)  # explicit list wins
        elif full_tree:
            project_paths = paths       # one filesystem walk, not two
        else:                           # project_scope == "full"
            project_paths = list(iter_python_files(root))
        for rel in project_paths:
            if rel not in contexts:
                ctx = _parse_file(root, rel)
                if not isinstance(ctx, Finding):
                    contexts[rel] = ctx
        from bigdl_tpu.analysis.project import ProjectContext
        pctx = ProjectContext(
            root, {p: contexts[p] for p in project_paths
                   if p in contexts})
        # project findings are never filtered to the `paths` subset:
        # in "full" mode a changed file's breakage may anchor in an
        # unchanged one, and the gate keeps HEAD clean — so whatever
        # pass 2 finds was caused by the subset
        for rule in project_rules:
            for f in rule.check_project(pctx):
                ctx = contexts.get(f.path)
                if ctx is not None and ctx.suppressions.suppressed(
                        f.rule, f.line):
                    continue
                findings.append(f)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
