"""Shared AST helpers for graftlint rules and the ProjectContext.

Lives outside `analysis/rules/` so `analysis/project.py` can use the
helpers without importing the rules package (whose __init__ imports
every rule module, several of which import project — a cycle).
`rules/_common.py` re-exports everything for the rule modules."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for a Name/Attribute chain, None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def last_segment(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def jit_decoration(fn: ast.FunctionDef
                   ) -> Optional[Tuple[Set[int], Set[str]]]:
    """If `fn` is decorated as a jit root, return (static_argnums,
    static_argnames); else None. Handles `@jax.jit`,
    `@functools.partial(jax.jit, static_argnums=..., ...)` and
    `@partial(jax.jit, ...)`."""
    for dec in fn.decorator_list:
        if dotted(dec) in _JIT_NAMES:
            return set(), set()
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in _JIT_NAMES:
                return _static_args(dec)
            if last_segment(name) == "partial" and dec.args \
                    and dotted(dec.args[0]) in _JIT_NAMES:
                return _static_args(dec)
    return None


def _static_args(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= {int(v) for v in int_tuple(kw.value)}
        elif kw.arg == "static_argnames":
            names |= set(str_tuple(kw.value))
    return nums, names


def int_tuple(node: ast.AST) -> Sequence[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def str_tuple(node: ast.AST) -> Sequence[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])
