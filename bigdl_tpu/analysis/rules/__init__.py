"""graftlint rule set — importing this package registers every rule
with `bigdl_tpu.analysis.engine.RULES`."""

from bigdl_tpu.analysis.rules import (  # noqa: F401
    donation_flow,
    event_kind_contract,
    hidden_device_sync,
    lock_discipline,
    metric_family_contract,
    missing_reference_docstring,
    nondeterministic_drill,
    retrace_hazard,
    telemetry_bypass,
    tf_import_in_core,
    trace_env_read,
    unfenced_timing,
)
