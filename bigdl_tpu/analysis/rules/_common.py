"""Shared AST helpers for graftlint rules — re-exported from
`bigdl_tpu.analysis.astutil` (which lives outside this package so the
ProjectContext can import the helpers without triggering the rules
package __init__, a cycle)."""

from bigdl_tpu.analysis.astutil import (  # noqa: F401
    call_name, dotted, functions, jit_decoration, last_segment,
    param_names, walk_calls,
)
