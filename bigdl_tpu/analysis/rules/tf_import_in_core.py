"""tf-import-in-core — TensorFlow is a test oracle, never a core dep.

The image ships TensorFlow for oracle comparisons
(tests/test_tf_interop.py) only; `bigdl_tpu/` interop uses the bundled
wire-compatible protos (`bigdl_tpu/utils/tf/`). A TF import in core
would drag a second ML runtime into every user process.
"""

from __future__ import annotations

import ast

from bigdl_tpu.analysis.engine import Rule, register


@register
class TfImportInCore(Rule):
    name = "tf-import-in-core"
    severity = "error"
    description = "core must not import TensorFlow (test oracle only)"
    scope = ("bigdl_tpu/",)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for m in mods:
                if m == "tensorflow" or m.startswith("tensorflow."):
                    yield self.finding(
                        ctx, node,
                        f"import of {m!r} in core — TensorFlow is a "
                        f"test oracle only; interop goes through the "
                        f"bundled protos (bigdl_tpu/utils/tf)")
