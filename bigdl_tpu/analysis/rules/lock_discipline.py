"""lock-discipline — state shared between a background thread and the
main path is accessed under a lock on both sides.

Four background threads share attributes with hot paths (the ISSUE 13
seed set): the serving engine's step watchdog, the async checkpoint
writer (`_AsyncSaver`), the dataset prefetch workers, and the flight
recorder's event-log listener (called from whatever thread emits). A
`self.x` written from any of those and also touched by a main-path
method is a race unless both sides hold a lock — and nothing at
runtime tells you; the drill just goes nondeterministic one day.

Detection (lightweight, class-scoped):

* thread entrypoints: a method handed to `threading.Thread(target=
  self.m)`, subscribed via `add_listener(self.m)`, or a local closure
  passed as a Thread target inside a method (the watchdog's
  `boxed()`/`work()` pattern) — plus every class method transitively
  called from one;
* thread-side WRITES: `self.x = ...` / `self.x += ...` or a mutating
  method call (`self.x.append(...)`, `.update`, `.put`, ...) inside a
  thread-side function, excluding attributes that are themselves
  synchronization objects (`Lock`/`Event`/`Queue`/...);
* both the thread-side write and every main-path access (any method
  except `__init__`, which runs before the thread exists) of such an
  attribute must sit inside a `with self.<lock>:` region — directly,
  or in a helper whose every call site is inside one (the flight
  recorder's `_dump` pattern).

A racy-by-design access carries an inline suppression naming why it is
safe (GIL-atomic single read, monotonic flag, ...) — the standard
graftlint `# graftlint: disable=lock-discipline` + a why.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from bigdl_tpu.analysis.engine import ProjectRule, register
from bigdl_tpu.analysis.project import _MUTATORS, ThreadClass


@register
class LockDiscipline(ProjectRule):
    name = "lock-discipline"
    severity = "error"
    description = ("thread-shared attribute accessed outside a lock "
                   "region on the thread or main path")

    def check_project(self, pctx):
        for tc in pctx.thread_classes:
            yield from self._check_class(pctx, tc)

    def _check_class(self, pctx, tc: ThreadClass):
        ctx = pctx.files[tc.path]
        thread_fns: List[Tuple[str, ast.FunctionDef]] = []
        for m in tc.entry_methods:
            thread_fns.append((m, tc.methods[m]))
        # closure entries: only the closure defs run on the thread —
        # the HOST method stays main-path (it starts/joins the thread)
        # with the closure subtrees carved out of its scan
        closure_nodes: Dict[str, set] = {}
        for host, closures in tc.closure_entries:
            for c in closures:
                thread_fns.append((f"{host}.{c.name}", c))
                closure_nodes.setdefault(host, set()).update(
                    ast.walk(c))
        # expand through self-calls: a method only the thread reaches
        # runs on the thread
        reachable = {n for n, _ in thread_fns}
        frontier = [fn for _, fn in thread_fns]
        while frontier:
            fn = frontier.pop()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self" \
                        and sub.func.attr in tc.methods \
                        and sub.func.attr not in reachable:
                    reachable.add(sub.func.attr)
                    thread_fns.append((sub.func.attr,
                                       tc.methods[sub.func.attr]))
                    frontier.append(tc.methods[sub.func.attr])
        locked_methods = self._effectively_locked(ctx, tc)

        def is_locked(node, fn_name: str, fn) -> bool:
            if fn_name.split(".")[-1] in locked_methods:
                return True
            return self._under_lock(ctx, node, fn, tc)

        # ---- thread-side writes ---------------------------------------
        writes: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        for fname, fn in thread_fns:
            for attr, node in self._attr_writes(fn):
                if attr in tc.sync_attrs:
                    continue
                writes.setdefault(attr, []).append(
                    (fname, node, is_locked(node, fname, fn)))
        if not writes:
            return
        for attr, sites in sorted(writes.items()):
            for fname, node, locked in sites:
                if not locked:
                    yield self.finding(
                        ctx, node,
                        f"`self.{attr}` is written on the "
                        f"thread side ({fname}) outside a lock region "
                        f"— wrap in `with self.<lock>:` or suppress "
                        f"with the reason it is safe")
        # ---- main-path accesses of thread-written attrs ----------------
        # thread-side methods are the entrypoints + everything
        # reachable from them via self-calls; closure HOSTS are not in
        # this set (their dotted "host.closure" names drop out here)
        thread_methods = {n for n, _ in thread_fns if "." not in n}
        for mname, m in sorted(tc.methods.items()):
            if mname == "__init__" or mname in thread_methods:
                continue
            excluded = closure_nodes.get(mname, set())
            for attr, node in self._attr_accesses(m):
                if node in excluded:
                    continue    # closure body: already thread-scanned
                if attr not in writes:
                    continue
                if is_locked(node, mname, m):
                    continue
                wname, wnode, _ = writes[attr][0]
                yield self.finding(
                    ctx, node,
                    f"`self.{attr}` is written from thread entrypoint "
                    f"{wname} (line {wnode.lineno}) and accessed here "
                    f"on the main path outside a lock region — take "
                    f"the same lock on both sides or suppress with the "
                    f"reason it is safe")

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _self_attr(node):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _attr_writes(self, fn):
        """(attr, node) for self.<attr> stores / augmented stores /
        mutating method calls inside `fn` (nested defs included — they
        run on the same thread)."""
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = self._self_attr(t)
                    if attr is not None:
                        yield attr, t
                    elif isinstance(t, ast.Subscript):
                        attr = self._self_attr(t.value)
                        if attr is not None:
                            yield attr, t
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = self._self_attr(node.func.value)
                if attr is not None:
                    yield attr, node

    def _attr_accesses(self, fn):
        for node in ast.walk(fn):
            attr = self._self_attr(node)
            if attr is not None:
                yield attr, node

    def _under_lock(self, ctx, node, fn, tc: ThreadClass) -> bool:
        """Ancestor `with self.<lock>:` between `node` and `fn`."""
        cur = ctx.parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    expr = item.context_expr
                    attr = self._self_attr(expr)
                    if attr is not None and (
                            attr in tc.lock_attrs
                            or "lock" in attr.lower()):
                        return True
            cur = ctx.parent(cur)
        return False

    def _effectively_locked(self, ctx, tc: ThreadClass) -> Set[str]:
        """Methods whose EVERY in-class call site is inside a lock
        region (directly or in another effectively-locked method) —
        their bodies inherit the lock (FlightRecorder._dump)."""
        # call sites: method -> [(caller, call node)]
        sites: Dict[str, List[Tuple[str, ast.Call]]] = {}
        for caller, m in tc.methods.items():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in tc.methods:
                    sites.setdefault(node.func.attr, []).append(
                        (caller, node))
        locked: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for m, calls in sites.items():
                if m in locked or not calls:
                    continue
                if all(caller in locked
                       or self._under_lock(ctx, node,
                                           tc.methods[caller], tc)
                       for caller, node in calls):
                    locked.add(m)
                    changed = True
        return locked
