"""donation-flow — a buffer donated to a jitted call must not be read
again by the caller.

`donate_argnums` aliases the argument's device buffer into the
executable's outputs: after the call returns, the donated array is
DELETED on accelerator backends — touching it raises (or, worse,
silently recomputes through a stale reference on backends that ignore
donation, so the bug only fires on TPU). PR 4's donation-aware retry
fixed exactly this class by hand; this rule pins it mechanically.

Cross-module resolution via the ProjectContext:

* functions **decorated** `@partial(jax.jit, donate_argnums=...)` are
  donating callables under their own name (`_decode_step` style);
* a function **returning** `jax.jit(f, donate_argnums=...)` is a
  donating *factory*: any binding assigned from a call to it
  (`step = make_dp_train_step(...)`, `self._step = self._make_step()`)
  donates at the same positions;
* a binding assigned `jax.jit(f, donate_argnums=...)` directly
  donates too.

At each call of a donating callable, every donated positional argument
that is a bare name or `self.<attr>` is tracked through the REST of
the enclosing function (linear statement order, nested defs excluded):
if the next mention is a read — not a rebind — it fires. Rebinding via
the call's own assignment targets (`state = step(state, batch)`, the
sanctioned pattern) is safe; `*args` splats and non-name arguments are
out of static reach and skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.analysis.engine import ProjectRule, register
from bigdl_tpu.analysis.project import is_donating_jit_call
from bigdl_tpu.analysis.rules._common import call_name, functions, \
    last_segment


def _expr_key(node) -> Optional[str]:
    """'x' for Name, 'self.x' for a self attribute — the trackable
    donated-argument shapes."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return "self." + node.attr
    return None


@register
class DonationFlow(ProjectRule):
    name = "donation-flow"
    severity = "error"
    description = ("argument donated via donate_argnums read again "
                   "after the jitted call")

    def check_project(self, pctx):
        for path, ctx in pctx.files.items():
            yield from self._check_file(pctx, path, ctx)

    def _check_file(self, pctx, path, ctx):
        class_bindings = self._class_donating_bindings(pctx, ctx)
        for fn in functions(ctx.tree):
            bindings = dict(self._enclosing_class_bindings(
                ctx, fn, class_bindings))
            bindings.update(self._local_donating_bindings(pctx, fn))
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                donated = self._donated_positions(pctx, bindings, call)
                if not donated \
                        or any(isinstance(a, ast.Starred)
                               for a in call.args):
                    continue
                stmt = self._enclosing_stmt(ctx, fn, call)
                if stmt is None:
                    continue
                rebound = self._assign_targets(stmt)
                for pos in donated:
                    if pos >= len(call.args):
                        continue
                    key = _expr_key(call.args[pos])
                    if key is None or key in rebound:
                        continue
                    hit = self._first_use_after(fn, stmt, key)
                    if hit is not None:
                        yield self.finding(
                            ctx, hit,
                            f"`{key}` was donated to the jitted call "
                            f"at line {call.lineno} (donate_argnums "
                            f"position {pos}) and is read again here — "
                            f"its device buffer is deleted after the "
                            f"call; use the call's result or copy "
                            f"before dispatch (the donation-aware "
                            f"retry pattern)")

    # ------------------------------------------------------------ helpers
    @classmethod
    def _class_donating_bindings(cls, pctx, ctx
                                 ) -> Dict[ast.ClassDef,
                                           Dict[str, Tuple[int, ...]]]:
        """Per class: 'self.X' → donated positions for attributes
        assigned a donating jit/factory anywhere in the class — the
        `self._step = self._make_step()` setup-in-__init__,
        call-elsewhere pattern."""
        out: Dict[ast.ClassDef, Dict[str, Tuple[int, ...]]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                b = cls._donating_assigns(pctx, node, self_only=True)
                if b:
                    out[node] = b
        return out

    @staticmethod
    def _enclosing_class_bindings(ctx, fn, class_bindings):
        cur = ctx.parent(fn)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return class_bindings.get(cur, {})
            cur = ctx.parent(cur)
        return {}

    @staticmethod
    def _donating_assigns(pctx, scope,
                          self_only: bool = False
                          ) -> Dict[str, Tuple[int, ...]]:
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            key = _expr_key(node.targets[0])
            if key is None or (self_only
                               and not key.startswith("self.")):
                continue
            donated = is_donating_jit_call(node.value)
            if not donated:
                seg = last_segment(call_name(node.value))
                # a factory name two defs share is ambiguous — skip
                if pctx.def_counts.get(seg) == 1:
                    donated = pctx.donating_factories.get(seg, ())
            if donated:
                out[key] = donated
        return out

    @classmethod
    def _local_donating_bindings(cls, pctx, fn
                                 ) -> Dict[str, Tuple[int, ...]]:
        """name/'self.x' → donated positions, for bindings assigned in
        `fn` from a donating jit expression or factory call."""
        return cls._donating_assigns(pctx, fn)

    @staticmethod
    def _donated_positions(pctx, bindings, call) -> Tuple[int, ...]:
        key = _expr_key(call.func)
        if key is not None and key in bindings:
            return bindings[key]
        # name-based fallback to project-wide donating defs: only for
        # plain-Name calls of a name that exactly ONE def in the
        # project carries — attribute chains and shadowed/ambiguous
        # names are out of static reach
        if isinstance(call.func, ast.Name):
            seg = call.func.id
            if pctx.def_counts.get(seg) == 1 \
                    and seg in pctx.donating_defs:
                return pctx.donating_defs[seg]
        return ()

    @staticmethod
    def _enclosing_stmt(ctx, fn, call):
        """The statement of `fn`'s body region containing `call`."""
        cur = call
        while cur is not None and cur is not fn:
            parent = ctx.parent(cur)
            if isinstance(cur, ast.stmt):
                return cur
            cur = parent
        return None

    @staticmethod
    def _assign_targets(stmt) -> set:
        out = set()
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    k = _expr_key(e)
                    if k is not None:
                        out.add(k)
            else:
                k = _expr_key(t)
                if k is not None:
                    out.add(k)
        return out

    @staticmethod
    def _first_use_after(fn, stmt, key):
        """First mention of `key` in `fn` strictly after `stmt` (linear
        line order, nested function bodies excluded): the node when it
        is a read, None when it is a rebind (or never mentioned)."""
        end = stmt.end_lineno or stmt.lineno
        events: List[Tuple[int, int, str, ast.AST]] = []
        attr = key.startswith("self.")
        name = key.split(".", 1)[1] if attr else key

        def visit(node, top):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)) \
                        and child is not top:
                    continue
                if attr and isinstance(child, ast.Attribute) \
                        and child.attr == name \
                        and isinstance(child.value, ast.Name) \
                        and child.value.id == "self":
                    kind = "read" if isinstance(child.ctx, ast.Load) \
                        else "bind"
                    events.append((child.lineno, child.col_offset,
                                   kind, child))
                elif not attr and isinstance(child, ast.Name) \
                        and child.id == name:
                    kind = "read" if isinstance(child.ctx, ast.Load) \
                        else "bind"
                    events.append((child.lineno, child.col_offset,
                                   kind, child))
                visit(child, top)

        visit(fn, fn)
        events.sort(key=lambda e: (e[0], e[1]))
        for line, col, kind, node in events:
            if line <= end:
                continue
            return node if kind == "read" else None
        return None
