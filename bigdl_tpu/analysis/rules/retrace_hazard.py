"""retrace-hazard — no Python-value branches on traced arguments
inside jit roots.

A `jit`-decorated function branching on a *traced* argument either
concretization-errors (`if x > 0:`) or, when the value sneaks in as a
Python scalar (a non-static kwarg, a `float()`/`bool()` coercion),
silently retraces per distinct value — the resharding/retrace hazard
class of arXiv 2004.13336, and the reason the serving plane pins the
#buckets+1 compile contract.

The rule inspects functions decorated `@jax.jit` /
`@functools.partial(jax.jit, ...)`: an `if`/`while` test or a
`bool()`/`float()`/`int()` coercion that touches a *bare* non-static
parameter is flagged. Shape metadata (`x.shape`, `x.ndim`, `x.dtype`,
`len(x)`, `isinstance(x, ...)`) is static under trace and allowed, as
are parameters named in `static_argnums`/`static_argnames`.

ISSUE 10: `shard_map`-wrapped bodies are trace roots too — the
sharded serving plane (serving/tp.py) builds its paged trio as local
functions handed to `shard_map(body, mesh=..., ...)`, which traces
`body` exactly like jit traces its function and has NO static-arg
escape hatch: every parameter is a traced operand. A function passed
as the first argument to a `shard_map(...)` call anywhere in the
module is therefore checked with all parameters traced.

ISSUE 17: Pallas KERNEL BODIES are trace roots too — a function
handed to `pl.pallas_call` (directly, wrapped in
`functools.partial(...)`, or via a variable holding such a partial —
the `ops/paged_decode.py` / `ops/flash_attention.py` launch idiom) is
traced with its Ref parameters as traced operands. The partial's
bound arguments are the kernel's static escape hatch (grid constants
like tile sizes and `dup_batch` are Python values by construction);
everything unbound is a Ref, and a Python branch on a Ref would
concretize at trace time exactly like a jit-root branch.
"""

from __future__ import annotations

import ast

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name, functions, \
    jit_decoration, last_segment, param_names

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "itemsize"}
_STATIC_FNS = {"len", "isinstance", "getattr", "hasattr", "type"}
_COERCIONS = {"bool", "float", "int"}


@register
class RetraceHazard(Rule):
    name = "retrace-hazard"
    severity = "warning"
    description = ("Python-value branch/coercion on a traced argument "
                   "inside a jit root")
    scope = ("bigdl_tpu/",)

    def check(self, ctx):
        shard_bodies = self._shard_map_bodies(ctx.tree)
        kernel_bodies = self._pallas_kernel_bodies(ctx.tree)
        for fn in functions(ctx.tree):
            jit = jit_decoration(fn)
            if jit is None:
                if fn.name in shard_bodies:
                    # shard_map body: no static-arg escape —
                    # everything the mesh hands in is a traced operand
                    nums, names = set(), set()
                elif fn.name in kernel_bodies:
                    # pallas kernel body: partial-bound args are the
                    # static escape; unbound params are traced Refs
                    nums, names = kernel_bodies[fn.name]
                else:
                    continue
            else:
                nums, names = jit
            params = param_names(fn)
            traced = {p for i, p in enumerate(params)
                      if i not in nums and p not in names}
            traced.discard("self")
            yield from self._check_fn(ctx, fn, traced)

    @staticmethod
    def _pallas_kernel_bodies(tree):
        """Kernel name -> (static positional indexes, static kwarg
        names) for functions handed to pallas_call — directly, as an
        inline `functools.partial(kernel, ...)`, or via a variable
        assigned such a partial (the ops/ launch idiom). The partial's
        bound leading positionals / kwargs are static; every other
        parameter is a traced Ref (ISSUE 17)."""

        def unpartial(expr):
            if isinstance(expr, ast.Name):
                return expr.id, set(), set()
            if isinstance(expr, ast.Call) \
                    and last_segment(call_name(expr)) == "partial" \
                    and expr.args \
                    and isinstance(expr.args[0], ast.Name):
                return (expr.args[0].id,
                        set(range(1, len(expr.args))) | {0},
                        {kw.arg for kw in expr.keywords if kw.arg})
            return None

        # variables holding a partial: name -> partial info
        partials = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                info = unpartial(node.value)
                if info is not None and (info[1] or info[2]):
                    partials[node.targets[0].id] = info

        out = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and last_segment(call_name(node)) == "pallas_call"
                    and node.args):
                continue
            first = node.args[0]
            info = unpartial(first)
            if isinstance(first, ast.Name) and first.id in partials:
                info = partials[first.id]
            if info is None:
                continue
            name, pos, kws = info
            # partial(fn, a, b) binds fn's FIRST len-1 params; the
            # recorded indexes 1..n map to param slots 0..n-1
            nums = {i - 1 for i in pos if i} if pos else set()
            out[name] = (nums, kws)
        return out

    @staticmethod
    def _shard_map_bodies(tree):
        """Names of local functions handed to shard_map(body, ...) —
        traced exactly like jit roots (serving/tp.py's paged trio)."""
        out = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and last_segment(call_name(node)) == "shard_map" \
                    and node.args \
                    and isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
        return out

    def _bare_traced_names(self, ctx, expr, traced):
        """Name nodes of traced params used by VALUE (not via static
        metadata like .shape/.ndim, len(), or an `is None` pytree-
        structure test — all static under trace)."""
        out = []
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Name) and node.id in traced):
                continue
            parent = ctx.parent(node)
            if isinstance(parent, ast.Attribute) \
                    and parent.value is node \
                    and parent.attr in _STATIC_ATTRS:
                continue
            if isinstance(parent, ast.Call) \
                    and call_name(parent) in _STATIC_FNS \
                    and node in parent.args:
                continue
            if isinstance(parent, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in parent.ops) \
                    and all(isinstance(c, ast.Constant)
                            and c.value is None
                            for c in parent.comparators):
                continue  # `x is (not) None`: argument-structure test
            out.append(node)
        return out

    def _check_fn(self, ctx, fn, traced):
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                for name in self._bare_traced_names(ctx, node.test,
                                                    traced):
                    kind = "while" if isinstance(node, ast.While) \
                        else "if"
                    yield self.finding(
                        ctx, node,
                        f"`{kind}` on traced argument "
                        f"`{name.id}` inside a jit root — "
                        f"concretizes/retraces per value; use "
                        f"lax.cond/jnp.where, or mark the argument "
                        f"static if it is host metadata")
            elif isinstance(node, ast.Call) \
                    and call_name(node) in _COERCIONS and node.args:
                for name in self._bare_traced_names(ctx, node.args[0],
                                                    traced):
                    yield self.finding(
                        ctx, node,
                        f"{call_name(node)}() coerces traced argument "
                        f"`{name.id}` to a Python value inside a jit "
                        f"root — forces a sync or a per-value retrace")
