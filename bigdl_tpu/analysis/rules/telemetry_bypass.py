"""telemetry-bypass — core code reports through `bigdl_tpu.obs`/logging,
never `print()`.

The telemetry convention (CLAUDE.md): metrics/events/spans go through
`bigdl_tpu.obs` ONLY, human-readable diagnostics through the
`bigdl_tpu.*` loggers. A stray `print()` in library code bypasses the
BIGDL_OBS kill switch, the event log, and every consumer parsing
stdout (bench JSON rows, drill output).

Scope is the `bigdl_tpu/` package only — scripts and examples are
CLIs and own their stdout. ISSUE 11 names `obs/journey.py` and
`obs/flightrecorder.py` explicitly (already inside the package
prefix): the flight recorder writes bundle FILES, never stdout — a
print() there would interleave with the bench/drill JSON its own
incident events are meant to index.
"""

from __future__ import annotations

import ast

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name

_WRITES = {"sys.stdout.write", "sys.stderr.write"}


@register
class TelemetryBypass(Rule):
    name = "telemetry-bypass"
    severity = "error"
    description = ("print()/direct stdout writes in core — route "
                   "through logging or bigdl_tpu.obs")
    scope = ("bigdl_tpu/",)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "print":
                yield self.finding(
                    ctx, node,
                    "print() in core bypasses the obs plane and the "
                    "BIGDL_OBS kill switch — use "
                    "logging.getLogger('bigdl_tpu.*') for diagnostics "
                    "or bigdl_tpu.obs for telemetry")
            elif name in _WRITES:
                yield self.finding(
                    ctx, node,
                    f"{name} in core — use logging or bigdl_tpu.obs")
