"""hidden-device-sync — no device→host fetches on hot/emission paths.

Two contracts meet here:

* obs emission consumes ALREADY-FETCHED host values — zero new device
  syncs (tests/test_obs.py pins compile counts; a sync hiding in an
  emission helper would stall the decode loop once per event);
* the serving decode loop performs exactly ONE deliberate fetch per
  step (the watchdog-guarded `np.asarray` in `_dispatch_and_fetch`) —
  any other `.item()`/`np.asarray`/`device_get` on that path is a
  stealth round-trip through the axon tunnel.

The deliberate fetch carries an inline
`# graftlint: disable=hidden-device-sync` with its justification;
everything else is a finding. Scope: all of `bigdl_tpu/obs/`, plus
hot-path functions (decode/prefill/step/dispatch/sample/work/emit/
observe, and the paged-cache lookup/insert/evict/alloc paths —
ISSUE 8: block-table and radix-tree surgery runs between EVERY decode
step, so a sync there stalls the whole batch once per admission) in
`serving/`, `ops/kv_cache.py` and `models/transformer.py`.

ISSUE 10 widens the hot set to the sharded-serving paths: handoff
export/import (`_export_handoff` carries the ONE suppressed
per-request fetch — the disaggregation boundary; anything else on a
handoff path is a stealth sync per package) and pool placement
(`place_pools` runs on the step path after eager pool surgery — it
must re-COMMIT shardings, never fetch). `serving/tp.py` is inside the
`serving/` scope like the rest of the plane; its `gather_serving_
params` (the checkpoint form — a deliberate whole-tree fetch) is
host-side setup by name, not a hot path.

ISSUE 11 extends the scope to the journey/flight-recorder layer
(`obs/journey.py`, `obs/flightrecorder.py` — named explicitly below
even though the `bigdl_tpu/obs/` prefix already covers them: shrinking
the obs/ scope must not silently drop them) and the hot-name set to
journey/record/dump/bundle/flight functions: the flight recorder runs
INSIDE emit (an EventLog listener), so a sync in a dump path would
stall the decode loop once per incident-adjacent event — everything it
records must be an already-emitted host dict.

ISSUE 15 widens the hot-name set to the speculative-decoding paths:
verify/rollback/mirror/spec functions (`serving/speculative.py` —
already inside the `serving/` scope). The verify dispatch carries the
round's ONE suppressed target fetch and each draft chain step its
bounded draft fetch (the chain is sequential by construction); the
acceptance loop, rollback (a pure table/length edit) and mirror
seating run BETWEEN every verify round, so a stealth sync there
stalls the whole batch once per round — same bar as the block-table
surgery paths.

ISSUE 16 widens the hot-name set to the host spill tier:
spill/readmit/migrate functions (prefix_cache.py tree surgery, the
engine's spill cascade and re-admission, the router's warm-state
migration). Spill export carries ONE suppressed batched `device_get`
(host parking is the point — the bytes must come down) and
re-admission/tree import their deliberate eager `device_put`-side
placement; everything else on those paths is host bookkeeping over
block ids and numpy arrays, so any other fetch is a stealth sync per
eviction or per admission.

ISSUE 17 adds `ops/paged_decode.py` to the scope and quant/repack to
the hot-name set: the one-launch paged-attention kernel runs INSIDE
the jitted decode step (its launch wrapper and BlockSpec index maps
are trace roots — a fetch there would sync once per decode step), and
`serving/quant.py`'s repack (already inside the `serving/` prefix)
must stay device-side jnp ops: quantization happens once at engine
construction, but a fetch hiding in `quantize_serving_params` would
pull the whole fp32 tree through the tunnel.

ISSUE 18 adds `parallel/param_layout.py` to the scope and
swap/distill/adapt to the hot-name set (the speculation flywheel).
The param-layout spine's shard/unstack/spec helpers run inside
jitted step traces (zero2 slices) and on the engine-construction /
hot-swap path; `swap_params`/`swap_draft` execute BETWEEN decode
rounds on a LIVE engine — a fetch there stalls serving once per
swap, and the swap is pure re-placement (structure/shape checks on
tree metadata, never values). The adaptive-k ladder (`_evaluate_k`)
and the distiller's corpus walk are host arithmetic over already-
fetched ints; `gather_tree`'s np.asarray is the deliberate,
documented exception (explicit gather API, not a step path).
"""

from __future__ import annotations

import ast
import re

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name, last_segment

_SYNC_CALLS = {"np.asarray", "numpy.asarray", "np.array",
               "numpy.array", "jax.device_get", "device_get",
               "jax.block_until_ready"}
_SYNC_METHODS = {"item", "block_until_ready", "tolist", "__array__"}
_HOT_FN = re.compile(
    r"(decode|prefill|dispatch|step|sample|work|emit|observe"
    r"|lookup|insert|evict|alloc|handoff|place"
    r"|journey|record|dump|bundle|flight"
    r"|verify|rollback|mirror|spec"
    r"|spill|readmit|migrate"
    r"|quant|repack"
    r"|swap|distill|adapt)")


@register
class HiddenDeviceSync(Rule):
    name = "hidden-device-sync"
    severity = "error"
    description = ("device→host fetch on a decode/step hot path or "
                   "obs emission path")
    scope = ("bigdl_tpu/obs/", "bigdl_tpu/obs/journey.py",
             "bigdl_tpu/obs/flightrecorder.py",
             "bigdl_tpu/serving/",
             "bigdl_tpu/ops/kv_cache.py",
             "bigdl_tpu/ops/paged_decode.py",
             "bigdl_tpu/models/transformer.py",
             "bigdl_tpu/parallel/param_layout.py")

    def _in_scope(self, ctx, node) -> bool:
        fns = ctx.enclosing_functions(node)
        if not fns:
            return False
        if ctx.path.startswith("bigdl_tpu/obs/"):
            return True
        return any(_HOT_FN.search(fn.name) for fn in fns)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            hit = None
            if name in _SYNC_CALLS:
                hit = name
            elif isinstance(node.func, ast.Attribute) \
                    and not node.args and not node.keywords \
                    and last_segment(name) in _SYNC_METHODS:
                hit = f".{last_segment(name)}()"
            if hit is None or not self._in_scope(ctx, node):
                continue
            yield self.finding(
                ctx, node,
                f"{hit} forces a device→host sync on a hot/emission "
                f"path — consume already-fetched host values (the one "
                f"deliberate per-step fetch carries an inline "
                f"suppression with its why)")
