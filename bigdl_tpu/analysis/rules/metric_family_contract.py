"""metric-family-contract — one registration per family, label sets
that match it, no orphan series.

The registry merges idempotent re-registrations at runtime, which is
exactly why drift hides: a second registration site with different
help text silently wins or raises depending on call order, a bump site
passing the wrong label set only explodes when that code path finally
runs, and a family nobody bumps (or a bump nobody registered) is dead
weight on every snapshot. This rule checks statically, across modules:

* **single registration** — a literal family name is registered at
  exactly one code site (f-string families like `serving_{k}_total`
  register a *pattern* site and are exempt from the uniqueness check);
* **label-set match** — every `.labels(...)` call resolvable to a
  registration (chained on it, or through the binding that stores the
  family) passes exactly the declared labelnames;
* **registered-never-bumped** — a registration whose binding is never
  referenced again anywhere in the project (and whose name is never
  fetched via `registry.get("name")`) is an orphan;
* **bumped-never-registered** — a `registry.get("name")` naming no
  registration, or a bump through a `_m_*`-conventioned attribute that
  no registration ever assigned.

Binding resolution follows the repo convention: families/children live
in `self._m_*` attributes or module-level names assigned straight from
`reg.counter/gauge/histogram(...)` (optionally `.labels(...)`-chained,
optionally inside a dict comprehension for keyed family maps).
"""

from __future__ import annotations

from typing import Dict, List

import ast

from bigdl_tpu.analysis.engine import ProjectRule, register


@register
class MetricFamilyContract(ProjectRule):
    name = "metric-family-contract"
    severity = "error"
    description = ("metric families: single registration, matching "
                   "bump label sets, no orphan/unregistered series")

    def check_project(self, pctx):
        regs = pctx.metric_registrations
        by_name: Dict[str, List] = {}
        for r in regs:
            if r.name is not None:
                by_name.setdefault(r.name, []).append(r)
        by_binding = {r.binding: r for r in regs
                      if r.binding is not None}
        # ---- label sets on chained .labels(...) ------------------------
        for r in regs:
            if r.chained_labels is None or r.labelnames is None:
                continue
            yield from self._check_labels(
                pctx, r, r.chained_labels, r.path)
        # ---- bumps resolved through bindings ---------------------------
        bumped_bindings = set()
        for b in pctx.metric_bumps:
            if b.binding in by_binding:
                bumped_bindings.add(b.binding)
                r = by_binding[b.binding]
                if b.method == "labels" or b.label_names is not None:
                    if r.chained_labels is not None:
                        # binding holds a CHILD (labels already applied
                        # at registration) — .labels() on it re-labels
                        # a child, which raises at runtime
                        yield self.finding(
                            pctx.files[b.path], b.node,
                            f"binding {b.base_name!r} holds a labeled "
                            f"child of {r.name or r.pattern!r} — "
                            f".labels(...) on a child is a runtime "
                            f"error; call it on the family")
                    elif r.labelnames is not None \
                            and b.label_names is not None \
                            and set(b.label_names) != set(r.labelnames):
                        yield self.finding(
                            pctx.files[b.path], b.node,
                            f"bump labels {sorted(b.label_names)} do "
                            f"not match family "
                            f"{r.name or r.pattern!r} labelnames "
                            f"{sorted(r.labelnames)} (registered at "
                            f"{r.path}:{r.node.lineno})")
            elif b.binding is not None \
                    and b.base_name.startswith("_m_"):
                # the `_m_*` convention marks metric bindings — a bump
                # through one with no registration anywhere is a
                # family nobody ever created
                yield self.finding(
                    pctx.files[b.path], b.node,
                    f"bump through metric binding {b.base_name!r} but "
                    f"no registration assigns it — register the family "
                    f"or drop the bump (bumped-never-registered)")
        # ---- single registration per literal family name --------------
        for name, sites in sorted(by_name.items()):
            # the canonical owner is the site whose binding actually
            # gets bumped, then any bound site — the stray duplicate
            # is the re-register nobody feeds
            sites = sorted(sites, key=lambda r: (
                r.binding not in bumped_bindings,
                r.binding is None, r.path, r.node.lineno))
            for dup in sites[1:]:
                first = sites[0]
                yield self.finding(
                    pctx.files[dup.path], dup.node,
                    f"metric family {name!r} is also registered at "
                    f"{first.path}:{first.node.lineno} — exactly one "
                    f"registration site per family (share the binding "
                    f"or registry.get() it)")
        # ---- registry.get("name") by-name references -------------------
        named_refs = set()
        for ref in pctx.metric_name_refs:
            if any(r.matches(ref.name) for r in regs):
                named_refs.add(ref.name)
                continue
            yield self.finding(
                pctx.files[ref.path], ref.node,
                f"registry.get({ref.name!r}) names a family no call "
                f"site registers (bumped-never-registered)")
        # ---- registered-never-bumped -----------------------------------
        for r in regs:
            if r.inline_bumped:
                continue
            if r.name is not None and r.name in named_refs:
                continue
            if r.binding is not None:
                if r.binding in bumped_bindings:
                    continue
                if self._binding_referenced(pctx, r):
                    continue
            yield self.finding(
                pctx.files[r.path], r.node,
                f"metric family {r.name or r.pattern!r} is registered "
                f"but never bumped or read anywhere in the project — "
                f"wire it or cull it (registered-never-bumped)")

    def _check_labels(self, pctx, r, labels_call, path):
        if any(kw.arg is None for kw in labels_call.keywords):
            return
        passed = {kw.arg for kw in labels_call.keywords}
        if passed != set(r.labelnames):
            yield self.finding(
                pctx.files[path], labels_call,
                f"labels {sorted(passed)} do not match family "
                f"{r.name or r.pattern!r} labelnames "
                f"{sorted(r.labelnames)}")

    @staticmethod
    def _binding_referenced(pctx, r) -> bool:
        """True when the registration's binding is loaded anywhere
        beyond its defining assignment — a property returning it, a
        health() read, a handoff into another object all count as the
        family being wired. Binding keys are 'path::name' (module
        scope) or 'path:Class.attr' (see project._binding_of)."""
        if "::" in r.binding:
            name = r.binding.split("::", 1)[1]
            ctx = pctx.files[r.path]
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Name) and node.id == name \
                        and isinstance(node.ctx, ast.Load):
                    return True
            return False
        attr = r.binding.rsplit(".", 1)[1]
        for ctx in pctx.files.values():
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Attribute) \
                        and node.attr == attr \
                        and isinstance(node.ctx, ast.Load):
                    return True
        return False
