"""trace-env-read — no `os.environ` reads inside compute-path functions.

The bug class behind the PR-1 flash-attention bwd-tiles patch: an env
var read while jit traces a function is baked into the first compiled
executable for that shape, and changing the variable afterwards is a
silent no-op (the jit cache is keyed on shapes, not on the
environment). Any function in the compute packages can end up under a
`jit` trace (layers run inside the caller's jitted train step), so the
rule is structural, not call-graph-based: env reads in `ops/`, `nn/`,
`parallel/`, `models/` and `serving/` must happen at module import
time — snapshot the knob into `bigdl_tpu/utils/envknobs.py` and read
the snapshot.

Module-top-level reads (import time, by construction before any trace)
are allowed.

ISSUE 17 additions ride the existing prefixes: `ops/paged_decode.py`
(the one-launch decode kernel's tile knob `BIGDL_PAGED_DECODE_TILES`
is an envknobs import snapshot — its launch wrapper runs inside the
jitted decode step, the canonical place this bug class bites) and
`serving/quant.py` (layout choices are CONSTRUCTOR args on the
engine, never env — a quantization knob read here would freeze the
first engine's layout into every later one).

ISSUE 18 likewise: the speculation flywheel's knobs — adaptive
lookahead (`adapt_k`, `k_min`, `adapt_window`, `raise_at`,
`lower_at`, `collapse_at`, `probe_every` on `SpeculativeEngine`) and
distillation (`seq_len`, `batch_size`, `learningrate`, `epochs`,
`zero`, `mesh` on `DraftDistiller`) — are CONSTRUCTOR args, never
env, and `parallel/param_layout.py` rides the `parallel/` prefix:
the spine's shard helpers run inside the zero2 step trace, exactly
where an env read would freeze into the first executable.
"""

from __future__ import annotations

import ast

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name, dotted

_READ_CALLS = {"os.environ.get", "os.getenv", "environ.get",
               "os.environ.pop", "os.environ.setdefault"}


@register
class TraceEnvRead(Rule):
    name = "trace-env-read"
    severity = "error"
    description = ("os.environ read inside a compute-path function — "
                   "resolved at trace time, baked into the compiled "
                   "executable; snapshot at import via "
                   "utils/envknobs instead")
    scope = ("bigdl_tpu/ops/", "bigdl_tpu/nn/", "bigdl_tpu/parallel/",
             "bigdl_tpu/models/", "bigdl_tpu/serving/")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            hit = None
            if isinstance(node, ast.Call) \
                    and call_name(node) in _READ_CALLS:
                hit = call_name(node)
            elif isinstance(node, ast.Subscript) \
                    and dotted(node.value) == "os.environ":
                hit = "os.environ[...]"
            if hit is None:
                continue
            if not ctx.enclosing_functions(node):
                continue  # module-top-level = import time: fine
            yield self.finding(
                ctx, node,
                f"{hit} inside a function is a trace-time env read "
                f"(value is frozen into the first compiled executable "
                f"per shape; later changes are a silent no-op) — "
                f"snapshot the knob at import in "
                f"bigdl_tpu/utils/envknobs.py and read the snapshot")
