"""missing-reference-docstring — every nn/ layer cites its reference.

Repo convention (CLAUDE.md): "Every layer cites its reference file in
the docstring (`reference: nn/Xxx.scala`)". The citation is the
traceability link back to the source framework's component inventory
(SURVEY.md §2) — it is how a reader verifies parity claims and how
the completeness contract is audited.

A public class in `bigdl_tpu/nn/` satisfies the rule if ANY of:

* its own docstring contains a `reference: ...` / `Reference
  parity: ...` citation or a `no (direct) reference` disclaimer
  (TPU-first extensions say so explicitly);
* the module docstring lists it by name (the common style is a
  module-level `Reference parity: nn/A.scala, nn/B.scala, ...`
  header naming every class in the file).

Private (`_`-prefixed) classes and classes without bases (plain data
holders) are exempt.
"""

from __future__ import annotations

import ast
import re

from bigdl_tpu.analysis.engine import Rule, register

_OK_DOC = re.compile(
    r"reference(?:\s+parity)?:\s*\S+|no\s+(?:\w+\s+)?reference",
    re.IGNORECASE)


@register
class MissingReferenceDocstring(Rule):
    name = "missing-reference-docstring"
    severity = "warning"
    description = ("nn/ layer class with no `reference: nn/Xxx.scala` "
                   "citation")
    scope = ("bigdl_tpu/nn/",)

    def check(self, ctx):
        module_doc = ast.get_docstring(ctx.tree) or ""
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name.startswith("_") or not node.bases:
                continue
            doc = ast.get_docstring(node) or ""
            if _OK_DOC.search(doc):
                continue
            if node.name in module_doc:
                continue
            yield self.finding(
                ctx, node,
                f"class `{node.name}` cites no reference — add "
                f"`reference: nn/{node.name}.scala` (or `no reference "
                f"counterpart: <why>`) to its docstring, or name it "
                f"in the module's `Reference parity:` header")
