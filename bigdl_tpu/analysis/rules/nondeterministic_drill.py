"""nondeterministic-drill — drill/serving code uses the injectable
clock and seeded RNG, never the wall clock or global `random`.

The fault drills (scripts/fault_drill.py) are bit-deterministic by
contract: every leg asserts exact counters/events, which only works
because the engine clock is injectable (`InferenceEngine(clock=)`) and
every random stream is explicitly seeded (np.random.RandomState(seed),
jax.random.PRNGKey). A `time.time()` or bare `random.random()` on
those paths reintroduces run-to-run drift that CPU CI can't
distinguish from a real regression.

The scope covers the whole fleet plane (ISSUE 7): serving/router.py
and serving/autoscaler.py via the serving/ prefix, plus the loadgen
traffic harness — its two-runs-identical-JSON acceptance dies the
moment a wall-clock read or global RNG draw sneaks in. ISSUE 14 adds
`bigdl_tpu/obs/slo.py`: alert evaluation is a pure function of (the
sampler's window, the injected clock) by contract — the slo_alert
drill pins firing AND resolution byte-for-byte, bundle bytes
included, which a `time.time()` in a state transition would break the
same way it breaks the loadgen report. ISSUE 20's scenario plane
rides the same serving/ prefix — `serving/scenarios.py` (every
arrival/spec draw comes from ONE np.random.RandomState(spec seed);
compile twice, get the same trace) and `serving/sim.py` (simulated
time IS the injected clock: a SimulatedEngine constructed without
`clock=` refuses to start, and a wall-clock read in the cost model
would put real milliseconds into a virtual-seconds timeline) — the
10⁵-request byte-identity acceptance depends on both. The ISSUE-9
elastic-training legs (preempt_resume / ckpt_async_torn / torn_shard
/ worldsize_resume) are covered by the scripts/fault_drill.py entry:
their kill/torn-save steps must come from a FaultPlan schedule
("preempt@5"), never an unseeded draw — the fixtures pin both sides.
scripts/multihost_smoke.py stays OUT of scope deliberately: its
launcher polls real subprocesses on the wall clock (kill timing), and
its determinism claim is about the sha256 of the TRAINED PARAMETERS
across runs, not about the polling timeline.

Allowed: *references* to clock functions (e.g. the
`clock: Callable = time.monotonic` default — that IS the injection
point), `time.sleep` (models injected stragglers; not a clock read),
seeded constructors (`np.random.RandomState(...)`,
`np.random.default_rng(...)`), and all of `jax.random.*`.
"""

from __future__ import annotations

import ast

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name

_CLOCK_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                "time.monotonic_ns", "time.perf_counter_ns",
                "datetime.now", "datetime.datetime.now",
                "datetime.utcnow"}
_RNG_OK = {"np.random.RandomState", "numpy.random.RandomState",
           "np.random.default_rng", "numpy.random.default_rng",
           "np.random.SeedSequence", "numpy.random.SeedSequence"}


@register
class NondeterministicDrill(Rule):
    name = "nondeterministic-drill"
    severity = "error"
    description = ("wall clock / unseeded RNG in drill or serving "
                   "code — use the injectable clock / seeded streams")
    scope = ("bigdl_tpu/serving/", "bigdl_tpu/utils/faults.py",
             "bigdl_tpu/obs/slo.py",
             "scripts/fault_drill.py", "scripts/loadgen.py")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name in _CLOCK_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{name}() bypasses the injectable clock — "
                    f"thread the engine/drill clock "
                    f"(InferenceEngine(clock=...)) so drills stay "
                    f"bit-deterministic")
            elif (name.startswith(("random.", "np.random.",
                                   "numpy.random."))
                  and name not in _RNG_OK
                  and not name.startswith(("np.random.RandomState.",
                                           "numpy.random.RandomState."))):
                yield self.finding(
                    ctx, node,
                    f"{name}() draws from a global/unseeded stream — "
                    f"use np.random.RandomState(seed) or "
                    f"jax.random with an explicit key")
