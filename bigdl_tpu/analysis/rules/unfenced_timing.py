"""unfenced-timing — timing windows over device work must close with a
real device→host fetch.

`block_until_ready` is optimistic through the axon remote-TPU tunnel
(CLAUDE.md, bench.py "Measurement notes"): a `time.perf_counter()`
stop-read taken after merely *dispatching* device work measures
dispatch, not execution. Every timing window that contains device work
must see a genuine fetch (`float(loss)`, `np.asarray`,
`jax.device_get`, `utils.profiler.device_sync` / `FencedTimer.fence`)
after the last dispatched call and before (or on) the stop-read.

Heuristic, deliberately conservative: the window is an assignment
`t0 = time.perf_counter()` (or time.time/monotonic) to a stop
expression `time.*() - t0` in the same function; "device work" is a
call whose name looks like a step/decode/forward dispatch; a call
whose name mentions fetch/fence/sync counts as self-fencing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from bigdl_tpu.analysis.engine import Rule, register
from bigdl_tpu.analysis.rules._common import call_name, functions, \
    last_segment

_TIME_CALLS = {"time.time", "time.monotonic", "time.perf_counter"}
_FENCE_NAMES = {"float", "int", "np.asarray", "numpy.asarray",
                "np.array", "numpy.array", "jax.device_get",
                "device_get", "jax.block_until_ready"}
_FENCE_HINT = re.compile(r"(fetch|fence|sync|block_until_ready)")
_DEVICE_WORK = re.compile(
    r"(?:^|_)(step|decode|prefill|forward|apply|train|sample|"
    r"run_one|dispatch|loss|grad|update)(?:$|_)")


def _is_time_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_name(node) in _TIME_CALLS


@register
class UnfencedTiming(Rule):
    name = "unfenced-timing"
    severity = "warning"
    description = ("time.* window over device work with no "
                   "device→host fetch before the stop-read")
    scope = ("bigdl_tpu/", "scripts/", "bench.py", "examples/")

    def check(self, ctx):
        for fn in list(functions(ctx.tree)) + [ctx.tree]:
            yield from self._check_body(ctx, fn)

    def _check_body(self, ctx, fn):
        starts: Dict[str, int] = {}       # var -> assignment line
        fences: List[int] = []
        work: List[int] = []
        stops: List[tuple] = []           # (node, var)
        # walk in source order; nested defs get their own pass, so
        # skip their interiors here
        own_nested = {n for f in ast.walk(fn)
                      if isinstance(f, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and f is not fn
                      for n in ast.walk(f) if n is not f}
        for node in ast.walk(fn):
            if node in own_nested:
                continue
            if isinstance(node, ast.Assign) and _is_time_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        starts[t.id] = node.lineno
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Sub) \
                    and _is_time_call(node.left) \
                    and isinstance(node.right, ast.Name):
                stops.append((node, node.right.id))
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _FENCE_NAMES \
                        or _FENCE_HINT.search(last_segment(name)):
                    fences.append(node.lineno)
                elif _DEVICE_WORK.search(last_segment(name)):
                    work.append(node.lineno)
        for node, var in stops:
            t0 = starts.get(var)
            if t0 is None:
                continue
            in_window = [w for w in work if t0 < w < node.lineno]
            if not in_window:
                continue
            last_work = max(in_window)
            if any(last_work <= f <= node.lineno for f in fences):
                continue
            yield self.finding(
                ctx, node,
                f"timing window [{var} @ line {t0} → here] contains "
                f"device work (line {last_work}) but no device→host "
                f"fetch before the stop-read — block_until_ready lies "
                f"through the tunnel; fence with float(loss) / "
                f"np.asarray / utils.profiler.FencedTimer")
