"""event-kind-contract — every emitted/consumed event kind must exist
in the machine-readable `EVENT_KINDS` registry (obs/events.py).

The telemetry schema is open at RUNTIME (an experiment may emit
anything), but committed code is a contract: `obs/journey.py`,
`obs/flightrecorder.py`'s trigger set, `scripts/obs_report.py` and the
fault-drill assertions all consume kinds by string literal, and a
producer/consumer drifting apart fails silently — the drill just sees
zero events. This rule pins both sides to the registry:

* every `emit_event("<kind>", ...)` / `<log>.emit("<kind>", ...)` with
  a literal kind must name a registered kind;
* the statically visible keyword fields at the call site must be
  declared (required or optional) for that kind, and — when the call
  has no `**splat` hiding fields — every required field must be
  passed;
* every consumer-side kind literal (an `.events("<kind>")` filter, a
  `rec["kind"] == "<kind>"` / `kind in (...)` comparison) must
  reference a producible (registered) kind.

Metric-family snapshots share the "kind" key (`fam["kind"] ==
"histogram"`), so the metric kind names are a documented carve-out of
the consumer check (see project.METRIC_FAMILY_KINDS).
"""

from __future__ import annotations

from bigdl_tpu.analysis.engine import ProjectRule, register
from bigdl_tpu.analysis.project import METRIC_FAMILY_KINDS


@register
class EventKindContract(ProjectRule):
    name = "event-kind-contract"
    severity = "error"
    description = ("emit_event kinds/fields and consumer kind literals "
                   "must match the obs EVENT_KINDS registry")

    def check_project(self, pctx):
        reg = pctx.event_registry
        if reg is None:
            return            # no registry in scope (bare subtree)
        for extra in pctx.event_registries[1:]:
            yield self.finding(
                pctx.files[extra.path], _at(extra.path, extra.line),
                f"duplicate EVENT_KINDS registry (the authoritative "
                f"one is {reg.path}:{reg.line}) — there is exactly one "
                f"source of truth for event kinds")
        for p in pctx.event_producers:
            ctx = pctx.files[p.path]
            if p.kind not in reg.kinds:
                yield self.finding(
                    ctx, p.node,
                    f"emit_event kind {p.kind!r} is not registered in "
                    f"{reg.path}::EVENT_KINDS — document it (required/"
                    f"optional fields) before emitting it")
                continue
            req, opt = reg.kinds[p.kind]
            if req is None:
                continue      # non-literal registry entry: waived
            allowed = set(req) | set(opt or ())
            for field in p.fields:
                if field not in allowed:
                    yield self.finding(
                        ctx, p.node,
                        f"emit_event({p.kind!r}) passes undeclared "
                        f"field {field!r} — add it to the kind's "
                        f"required/optional set in EVENT_KINDS or drop "
                        f"it")
            if not p.has_splat:
                missing = [f for f in req if f not in p.fields]
                if missing:
                    yield self.finding(
                        ctx, p.node,
                        f"emit_event({p.kind!r}) misses required "
                        f"field(s) {missing} — consumers (journey "
                        f"builder, obs_report, drills) rely on them")
        for c in pctx.event_consumers:
            if c.kind in reg.kinds or c.kind in METRIC_FAMILY_KINDS:
                continue
            yield self.finding(
                pctx.files[c.path], c.node,
                f"consumer references event kind {c.kind!r} that no "
                f"producer can emit (not in {reg.path}::EVENT_KINDS) — "
                f"the filter/branch is dead")


class _at:
    """Minimal lineno/col carrier for findings not tied to an AST
    node we kept around."""

    def __init__(self, path: str, line: int, col: int = 0):
        self.lineno = line
        self.col_offset = col
