"""Deterministic fault injection for the training loop.

Reference parity: the reference never tests its recovery path directly —
it inherits Spark task retry and exercises it only when a node actually
dies (SURVEY.md §5.3). Here the recovery code (DistriOptimizer
reload-latest retry, Checkpoint atomic publish + newest-valid fallback,
utils/anomaly guard) is a tested contract: this registry injects the
failures on demand, deterministically by step number, so every drill is
reproducible bit-for-bit (scripts/fault_drill.py, tests/test_fault_drill.py).

Plan syntax (env `BIGDL_FAULTS` or `FaultPlan("...")`):

    kind@step[xN][,kind@step...]     e.g. "nan@4,step@7,ckpt_corrupt@6x2"

Each entry fires at most N times (default 1) when its fault point is
consulted with that step number. One-shot by default on purpose: the
recovery path REPLAYS the failed step (reload latest checkpoint +
deterministic batch-stream fast-forward), so a fault that re-fired on
the replayed step would spin the retry budget down instead of proving
recovery.

Fault kinds and where they are consulted:

    step          raise before dispatching train step `step`
                  (LocalOptimizer.run / DistriOptimizer.run)
    nan           poison the batch for step `step` with NaNs — loss and
                  gradients go NaN through the real math, exercising the
                  anomaly guard end-to-end
    data          raise from the training batch iterator at global
                  stream position `step` (optimizer._batch_iterator)
    ckpt_torn     abort Checkpoint.save(step) after the staging dir is
                  partially written, before publish — the crash-mid-write
                  model; latest() must never surface the leftovers
    ckpt_corrupt  complete Checkpoint.save(step) normally, then truncate
                  the published model.npz (or, for a SHARDED save, a
                  middle optim shard's npz) — load() must fall back to
                  the newest valid checkpoint
    preempt       simulated worker kill: raise Preempted before
                  dispatching train step `step`. Unlike `step`, this is
                  NOT retryable in-process — DistriOptimizer's retry
                  budget re-raises it (a preempted TPU worker is dead;
                  the pod restarts the job with --resume, which the
                  preempt_resume drill models end to end)
    ckpt_async_torn
                  kill the checkpoint writer mid-sharded-save (after at
                  least one shard unit, before the manifest-last
                  publish): the torn dir has units but no MANIFEST.json,
                  so it never becomes a latest() candidate; with
                  async_save the error surfaces at the next
                  Checkpoint.save()/wait() — the background-writer
                  death model (drill kill_mid_save/ckpt_async_torn)

Serving kinds — consulted inside the serving engine's step loop
(bigdl_tpu/serving/engine.py), keyed by the engine's DECODE step
number (engine.stats["decode_steps"] at consult time):

    serve_nan     poison one row's logits (the lowest occupied slot)
                  to NaN INSIDE the jitted decode step via the (B,)
                  poison operand — exercises the finite-logits guard
                  and per-request 'poisoned' eviction end-to-end
    serve_err     raise before dispatching the decode step — the
                  transient step failure the retry-with-backoff
                  budget absorbs (consulted per ATTEMPT: xN makes the
                  failure persist across retries)
    serve_slow    sleep inside the dispatch+fetch region — the hung
                  device call / straggler model the step watchdog
                  (step_timeout_s) must convert into a StepTimeout

The plan is process-global (`get_plan()`/`set_plan()`); `get_plan()`
lazily builds one from `BIGDL_FAULTS` so subprocess drills (multihost
legs) inherit injection through the environment.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("bigdl_tpu.faults")

ENV_VAR = "BIGDL_FAULTS"

KINDS = ("step", "nan", "data", "ckpt_torn", "ckpt_corrupt",
         "preempt", "ckpt_async_torn",
         "serve_nan", "serve_err", "serve_slow")


class FaultInjected(RuntimeError):
    """Raised by an injected failure (never by real code paths)."""


class Preempted(FaultInjected):
    """An injected worker preemption (`preempt@step`): the in-process
    retry paths must NOT absorb this — the modeled worker is gone, and
    recovery is a fresh process with `resume_from_checkpoint()`."""


class FaultPlan:
    """Parsed injection plan; `fires(kind, step)` consumes one shot."""

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self._budget: Dict[Tuple[str, int], int] = {}
        self.fired: List[Tuple[str, int]] = []
        for entry in filter(None, (e.strip() for e in self.spec.split(","))):
            m = re.fullmatch(r"([a-z_]+)@(\d+)(?:x(\d+))?", entry)
            if not m:
                raise ValueError(
                    f"bad fault entry {entry!r}: expected 'kind@step[xN]'")
            kind, step, times = m.group(1), int(m.group(2)), \
                int(m.group(3) or 1)
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}: expected one of {KINDS}")
            key = (kind, step)
            self._budget[key] = self._budget.get(key, 0) + times

    def __bool__(self):
        return bool(self._budget)

    def fires(self, kind: str, step: int) -> bool:
        """True (and consumes one shot) if `kind` is armed for `step`."""
        key = (kind, int(step))
        left = self._budget.get(key, 0)
        if left <= 0:
            return False
        self._budget[key] = left - 1
        self.fired.append(key)
        logger.warning("fault injected: %s@%d", kind, step)
        # every shot that fires is a structured event — the drills
        # assert on telemetry, not stdout (ISSUE 5)
        from bigdl_tpu import obs

        obs.emit_event("fault_injected", fault=kind, step=int(step))
        return True

    def maybe_raise(self, kind: str, step: int) -> None:
        if self.fires(kind, step):
            raise FaultInjected(f"injected fault {kind}@{int(step)}")

    def maybe_preempt(self, step: int) -> None:
        """Consulted by both training loops BEFORE the step's retry
        scope: a preemption is a dead worker, not a transient step
        failure, so the retry budget must never absorb it (recovery is
        a fresh process with --resume; drill preempt_resume)."""
        if self.fires("preempt", step):
            raise Preempted(
                f"injected fault preempt@{int(step)}: "
                f"worker killed before step dispatch")


_NO_FAULTS = FaultPlan("")
_plan: Optional[FaultPlan] = None


def get_plan() -> FaultPlan:
    """The active plan — from `set_plan`, else `BIGDL_FAULTS`, else empty."""
    global _plan
    if _plan is None:
        _plan = FaultPlan(os.environ.get(ENV_VAR, ""))
    return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install a plan programmatically (None → re-read the env lazily)."""
    global _plan
    _plan = plan


def poison_minibatch(mb):
    """A NaN-input copy of a MiniBatch: every float feature becomes NaN,
    so the step's loss/gradients go non-finite through the real math.
    Raises if the batch has NO float feature (integer-token models):
    a 'nan' fault that cannot actually poison anything would otherwise
    log 'fault injected' and let the drill pass vacuously."""
    import numpy as np

    from bigdl_tpu.dataset.sample import MiniBatch

    poisoned = [0]

    def nan_like(x):
        if isinstance(x, tuple):
            return tuple(nan_like(e) for e in x)
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.floating):
            poisoned[0] += 1
            return np.full_like(a, np.nan)
        return a

    out = MiniBatch(nan_like(mb.input), mb.target)
    if not poisoned[0]:
        raise ValueError(
            "nan fault: minibatch has no floating-point input to poison "
            "(integer-token model?) — inject 'step' or 'data' faults "
            "instead, or poison the loss path directly")
    if hasattr(mb, "real_size"):
        out.real_size = mb.real_size
    return out


def corrupt_file(path: str, mode: str = "truncate") -> None:
    """Damage an on-disk checkpoint artifact in place.

    `truncate`: keep the first half of the file (a torn write / partial
    flush); `garble`: overwrite the middle third with 0xFF (bit rot).
    Both are detected by checkpoint verification — truncation breaks the
    npz zip directory, garbling breaks the per-array checksums.
    """
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "garble":
        with open(path, "r+b") as f:
            f.seek(size // 3)
            f.write(b"\xff" * max(size // 3, 1))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
