"""Numeric-anomaly guard for the training loop.

Reference parity: the reference has NO numeric health monitoring — a NaN
loss silently poisons the weights and every later checkpoint (SURVEY.md
§5.3 lists retry/reload as the only safety net, and it only fires on an
*exception*). TensorFlow's stated fault-tolerance contract is user-level
checkpointing plus health monitoring (arXiv 1605.08695 §4.3); this
module is the monitoring half for this framework.

Split of responsibilities (keeps the guard cheap and deterministic):

* Inside the jitted step the loops compute a health pair — the loss's
  finiteness and the global (pre-clip) gradient norm — and select the
  update with `jnp.where(ok, new, old)`. An anomalous update is
  therefore discarded ON DEVICE, bit-exactly (`skip_step`: the returned
  params/slots/module-state are the inputs, same bits), regardless of
  how fast the host reacts. `ok = isfinite(loss) & isfinite(gnorm) &
  (gnorm <= max_gnorm)`; the spike threshold `max_gnorm` is a scalar
  argument fed by the host each step, so spike policy changes never
  retrace. `health_ok` below is that predicate.
* On the host, `AnomalyGuard.observe(ok, gnorm, step)` tracks the
  gradient-norm EMA (arming the spike threshold after `warmup_steps`),
  counts consecutive anomalies against `max_consecutive` (mirroring the
  DistriOptimizer retry budget), and returns the policy action:

      skip_step  "skipped"  — update already discarded on device; the
                              step still consumes its batch so the loop
                              advances past bad data
      rollback   "rollback" — the loop reloads the latest checkpoint
                              (the existing DistriOptimizer
                              reload-latest path, now shared)
      halt       raises AnomalyError immediately

  Exhausting `max_consecutive` raises AnomalyError under every policy:
  persistent non-finite math means the run is broken, and silently
  skipping forever would hide it. Rollback has its own budget shape:
  the replayed steps between reload and the anomaly are healthy, so
  the consecutive counter alone would reset every cycle and a
  data-inherent anomaly (a NaN baked into the dataset) would
  rollback-loop forever — `observe` therefore also counts rollbacks
  triggered by the SAME step number and raises once that replay streak
  exceeds `max_consecutive` (progress past the step resets it).

The guard is opt-in (`Optimizer.set_anomaly_guard(...)`); when unset the
step functions are built exactly as before — zero overhead. When set,
the extra cost is two scalar reductions in-step and a scalar
device→host fetch per step — per MICRO-batch under gradient
accumulation, where each micro-gradient's health must reach the host
before the accumulation bookkeeping for the next one (the guarded
accumulation path trades the async-dispatch overlap for screening).
"""

from __future__ import annotations

import logging
import math
from typing import Optional

logger = logging.getLogger("bigdl_tpu.optim")

POLICIES = ("skip_step", "rollback", "halt")


class AnomalyError(RuntimeError):
    """Numeric anomaly under policy 'halt', or anomaly budget exhausted."""


def health_ok(loss, gnorm, max_gnorm):
    """Jit-side health predicate: finite loss, finite grad norm, norm
    under the host-fed spike threshold. NaN compares false, so the
    `<=` alone rejects NaN norms; the explicit isfinite terms also
    reject inf when the threshold itself is inf (disabled)."""
    import jax.numpy as jnp

    return (jnp.isfinite(loss) & jnp.isfinite(gnorm)
            & (gnorm <= max_gnorm))


def select_update(ok, new, old):
    """Jit-side per-leaf where(ok): the computed update on healthy
    steps, the bit-identical inputs on anomalous ones — the single
    definition of the guard's on-device discard (used by the local
    step builder and the dp shard_map bodies)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(ok, a, b), new, old)


def rows_finite(x):
    """Jit-side per-ROW health predicate: (B, ...) → (B,) bool, True
    iff every element of the row is finite. The serving plane's poison
    guard (bigdl_tpu/serving/engine.py): the decode step returns this
    reduction over the logits as a (B,) operand fetched alongside the
    sampled tokens, so a NaN/inf row evicts only its own request — the
    per-request analog of `health_ok`'s per-step predicate."""
    import jax.numpy as jnp

    return jnp.all(jnp.isfinite(x), axis=tuple(range(1, x.ndim)))


def global_norm(tree):
    """sqrt(sum of squares) over a pytree or flat vector (jit-side)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


class AnomalyGuard:
    """Policy + budget + spike detector for per-step health pairs.

    policy          'skip_step' | 'rollback' | 'halt'
    max_consecutive raise AnomalyError after this many anomalies in a
                    row (the consecutive — not lifetime — budget, same
                    shape as DistriOptimizer.max_retries)
    spike_factor    None disables spike detection (finiteness only);
                    else a step whose grad norm exceeds
                    `spike_factor * EMA(grad norm)` is anomalous
    ema_decay       EMA smoothing for the grad-norm baseline
    warmup_steps    healthy steps observed before the spike threshold
                    arms (early norms are noisy; never arms on NaN)
    """

    def __init__(self, policy: str = "skip_step", max_consecutive: int = 3,
                 spike_factor: Optional[float] = None,
                 ema_decay: float = 0.95, warmup_steps: int = 10):
        if policy not in POLICIES:
            raise ValueError(
                f"policy {policy!r}: expected one of {POLICIES}")
        if max_consecutive < 1:
            raise ValueError("max_consecutive must be >= 1")
        if spike_factor is not None and spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        self.policy = policy
        self.max_consecutive = max_consecutive
        self.spike_factor = spike_factor
        self.ema_decay = ema_decay
        self.warmup_steps = warmup_steps
        self._ema: Optional[float] = None
        self._healthy_seen = 0
        self.consecutive = 0
        self.anomalies = 0  # every anomaly observed, any policy
        self.skipped = 0    # updates discarded-and-moved-past (skip_step)
        self.rollbacks = 0
        self.last_anomaly_step: Optional[int] = None
        self._rollback_step: Optional[int] = None
        self._rollback_streak = 0
        from bigdl_tpu import obs

        self._anomaly_counter = obs.get_registry().counter(
            "training_anomalies_total",
            "anomaly-guard observations by resulting action",
            labelnames=("action",))

    # ------------------------------------------------------------- threshold
    def threshold(self) -> float:
        """Current max allowed grad norm (fed to the jitted step). inf
        until spike detection is enabled AND warmed up."""
        if (self.spike_factor is None or self._ema is None
                or self._healthy_seen < self.warmup_steps):
            return math.inf
        return self.spike_factor * self._ema

    # --------------------------------------------------------------- observe
    def observe(self, ok: bool, gnorm: float, step: int) -> str:
        """Record one step's health pair; returns 'ok', 'skipped' or
        'rollback', or raises AnomalyError (halt / budget exhausted)."""
        if ok:
            self.consecutive = 0
            self._healthy_seen += 1
            if math.isfinite(gnorm):
                self._ema = gnorm if self._ema is None else (
                    self.ema_decay * self._ema
                    + (1.0 - self.ema_decay) * gnorm)
            return "ok"

        self.consecutive += 1
        self.anomalies += 1
        self.last_anomaly_step = step
        detail = (f"step {step}: non-finite or spiking update "
                  f"(grad norm {gnorm:g}, threshold {self.threshold():g})")
        if self.policy == "halt":
            self._note("halt", step, gnorm)
            raise AnomalyError(detail)
        if self.consecutive > self.max_consecutive:
            self._note("budget_exhausted", step, gnorm)
            raise AnomalyError(
                f"{detail} — {self.consecutive} consecutive anomalies "
                f"exceed max_consecutive={self.max_consecutive}")
        if self.policy == "rollback":
            # the replay between reload and this step is healthy, so
            # `consecutive` resets every cycle — budget the number of
            # times the SAME step re-triggers a rollback instead, or a
            # data-inherent anomaly would rollback-loop forever
            if step == self._rollback_step:
                self._rollback_streak += 1
            else:
                self._rollback_step, self._rollback_streak = step, 1
            if self._rollback_streak > self.max_consecutive:
                self._note("budget_exhausted", step, gnorm)
                raise AnomalyError(
                    f"{detail} — step {step} re-triggered rollback on "
                    f"{self._rollback_streak} consecutive replays "
                    f"(max_consecutive={self.max_consecutive}); the "
                    f"anomaly is deterministic, rolling back again "
                    f"cannot recover")
            self.rollbacks += 1
            self._note("rollback", step, gnorm)
            logger.warning("anomaly guard: %s; rolling back to the "
                           "latest checkpoint (replay %d/%d for this "
                           "step)", detail, self._rollback_streak,
                           self.max_consecutive)
            return "rollback"
        self.skipped += 1
        self._note("skipped", step, gnorm)
        logger.warning("anomaly guard: %s; update skipped on device "
                       "(%d/%d consecutive)", detail, self.consecutive,
                       self.max_consecutive)
        return "skipped"

    def _note(self, action: str, step: int, gnorm: float) -> None:
        """Telemetry for one anomaly: counter + structured event
        (drills assert on these instead of stdout). `gnorm` is already
        a host float — the loop fetched it to call observe()."""
        from bigdl_tpu import obs

        if not obs.enabled():
            return
        self._anomaly_counter.labels(action=action).inc()
        obs.emit_event("anomaly", plane="training", step=int(step),
                       action=action, policy=self.policy,
                       gnorm=float(gnorm))

    def stats(self) -> dict:
        return {"policy": self.policy, "anomalies": self.anomalies,
                "skipped": self.skipped, "rollbacks": self.rollbacks,
                "consecutive": self.consecutive,
                "last_anomaly_step": self.last_anomaly_step,
                "gnorm_ema": self._ema}
