"""Torch7 `.t7` wire format: load/save of tensors, tables, and modules.

Reference parity: utils/TorchFile.scala (`load`, `save`) — the
reference's interop with the Lua-Torch serialization format. The format
(little-endian, as produced by `torch.save` in Torch7's binary mode):

    object  := int32 type-tag, payload
    NUMBER  := float64
    STRING  := int32 len, bytes
    BOOLEAN := int32 0/1
    TABLE   := int32 heap-index, int32 n, n x (key obj, value obj)
    TORCH   := int32 heap-index, STRING version ("V 1"), STRING class,
               class payload
    tensor payload  := int32 ndim, int64[ndim] size, int64[ndim] stride,
                       int64 storage-offset (1-based), storage object
    storage payload := int64 n, n x element

Heap-indexed objects (tables, torch objects) appear once; later
occurrences serialize as a bare index — the reader memoizes, the writer
assigns sequential indices.

Module mapping (Torch layouts → ours, NHWC/HWIO — same transposes as
utils/torch_interop.py): Linear (out,in)→(in,out); SpatialConvolution
OIHW→HWIO; BatchNorm running stats into module state. Lua-Torch classes
covered: Sequential, Linear, SpatialConvolution, SpatialMaxPooling,
SpatialAveragePooling, SpatialBatchNormalization / BatchNormalization,
ReLU, Tanh, Sigmoid, LogSoftMax, SoftMax, Dropout, View, Reshape.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

T_NIL, T_NUMBER, T_STRING, T_TABLE, T_TORCH, T_BOOLEAN = 0, 1, 2, 3, 4, 5
T_FUNCTION, T_LEGACY_RECUR_FUNCTION, T_RECUR_FUNCTION = 6, 7, 8

_TENSOR_DTYPES = {
    "torch.DoubleTensor": np.float64, "torch.FloatTensor": np.float32,
    "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
    "torch.ShortTensor": np.int16, "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
}
_STORAGE_DTYPES = {k.replace("Tensor", "Storage"): v
                   for k, v in _TENSOR_DTYPES.items()}
_NP_TO_TORCH = {np.dtype(np.float32): "Float", np.dtype(np.float64): "Double",
                np.dtype(np.int64): "Long", np.dtype(np.int32): "Int",
                np.dtype(np.int16): "Short", np.dtype(np.uint8): "Byte",
                np.dtype(np.int8): "Char"}


class TorchObject:
    """A non-tensor `torch.class` instance: class name + field table."""

    def __init__(self, torch_class: str, fields: Dict):
        self.torch_class = torch_class
        self.fields = fields

    def __repr__(self):
        return f"TorchObject({self.torch_class})"


# ------------------------------------------------------------------ reader

class _Reader:
    def __init__(self, f):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _unpack(self, fmt, size):
        raw = self.f.read(size)
        if len(raw) != size:
            raise ValueError("truncated .t7 stream")
        return struct.unpack(fmt, raw)[0]

    def read_int(self) -> int:
        return self._unpack("<i", 4)

    def read_long(self) -> int:
        return self._unpack("<q", 8)

    def read_double(self) -> float:
        return self._unpack("<d", 8)

    def read_string(self) -> str:
        n = self.read_int()
        raw = self.f.read(n)
        # Lua strings are byte strings: binary payloads are legal.
        # surrogateescape maps undecodable bytes to lone surrogates
        # that write_string encodes back to the exact original bytes —
        # load/save round-trips are lossless and valid UTF-8 is
        # unaffected (the writer mirrors this; see write_string).
        return raw.decode("utf-8", errors="surrogateescape")

    def read_object(self) -> Any:
        tag = self.read_int()
        if tag == T_NIL:
            return None
        if tag == T_NUMBER:
            v = self.read_double()
            return int(v) if v.is_integer() else v
        if tag == T_STRING:
            return self.read_string()
        if tag == T_BOOLEAN:
            return bool(self.read_int())
        if tag == T_TABLE:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            table: Dict = {}
            self.memo[idx] = table
            n = self.read_int()
            for _ in range(n):
                k = self.read_object()
                table[k] = self.read_object()
            return table
        if tag == T_TORCH:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            version = self.read_string()
            cls = self.read_string() if version.startswith("V ") else version
            if cls in _TENSOR_DTYPES:
                out = self._read_tensor(np.dtype(_TENSOR_DTYPES[cls]))
            elif cls in _STORAGE_DTYPES:
                out = self._read_storage(np.dtype(_STORAGE_DTYPES[cls]))
            else:
                # generic torch.class: payload is its field table
                placeholder = TorchObject(cls, {})
                self.memo[idx] = placeholder
                payload = self.read_object()
                placeholder.fields = payload if isinstance(payload, dict) \
                    else {"value": payload}
                return placeholder
            self.memo[idx] = out
            return out
        if tag in (T_FUNCTION, T_RECUR_FUNCTION, T_LEGACY_RECUR_FUNCTION):
            raise ValueError("function objects in .t7 are not supported")
        raise ValueError(f"unknown .t7 type tag {tag}")

    def _read_storage(self, dtype) -> np.ndarray:
        n = self.read_long()
        raw = self.f.read(n * dtype.itemsize)
        if len(raw) != n * dtype.itemsize:
            raise ValueError("truncated .t7 stream in storage data")
        return np.frombuffer(raw, dtype=dtype).copy()

    def _read_tensor(self, dtype) -> np.ndarray:
        ndim = self.read_int()
        sizes = [self.read_long() for _ in range(ndim)]
        strides = [self.read_long() for _ in range(ndim)]
        offset = self.read_long() - 1
        storage = self.read_object()
        if ndim == 0 or storage is None or any(s == 0 for s in sizes):
            return np.zeros(sizes, dtype)
        # bounds-check before as_strided: a malformed file must raise,
        # not read out-of-bounds memory
        last = offset + sum((sz - 1) * st for sz, st in zip(sizes, strides))
        if offset < 0 or min(strides) < 0 or last >= storage.shape[0]:
            raise ValueError(
                f".t7 tensor (shape {sizes}, strides {strides}, offset "
                f"{offset}) exceeds its storage of {storage.shape[0]} "
                "elements")
        view = np.lib.stride_tricks.as_strided(
            storage[offset:], shape=sizes,
            strides=[s * dtype.itemsize for s in strides])
        return np.ascontiguousarray(view)


# ------------------------------------------------------------------ writer

class _Writer:
    def __init__(self, f):
        self.f = f
        self.memo: Dict[int, int] = {}  # id(obj) -> heap index
        self.next_idx = 1

    def write_int(self, v: int):
        self.f.write(struct.pack("<i", v))

    def write_long(self, v: int):
        self.f.write(struct.pack("<q", v))

    def write_double(self, v: float):
        self.f.write(struct.pack("<d", v))

    def write_string(self, s):
        # bytes pass through; str encodes utf-8 with surrogateescape so
        # strings produced by read_string's binary fallback restore
        # their exact original bytes (see read_string)
        raw = s if isinstance(s, bytes) else s.encode(
            "utf-8", errors="surrogateescape")
        self.write_int(len(raw))
        self.f.write(raw)

    def _heap(self, obj) -> Optional[int]:
        """Existing heap index (meaning: write a bare reference), or
        None after registering the object."""
        if id(obj) in self.memo:
            return self.memo[id(obj)]
        self.memo[id(obj)] = self.next_idx
        self.next_idx += 1
        return None

    def write_object(self, obj: Any):
        if obj is None:
            self.write_int(T_NIL)
        elif isinstance(obj, bool):
            self.write_int(T_BOOLEAN)
            self.write_int(1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self.write_int(T_NUMBER)
            self.write_double(float(obj))
        elif isinstance(obj, (str, bytes)):
            self.write_int(T_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray):
            if obj.ndim == 0:
                # Torch7 has no 0-d tensors (ndim=0 means empty); a
                # scalar's natural wire form is a Lua number
                self.write_int(T_NUMBER)
                self.write_double(float(obj))
            else:
                self._write_tensor(obj)
        elif isinstance(obj, (list, tuple)):
            self.write_object({i + 1: v for i, v in enumerate(obj)})
        elif isinstance(obj, dict):
            self.write_int(T_TABLE)
            ref = self._heap(obj)
            if ref is not None:
                self.write_int(ref)
                return
            self.write_int(self.memo[id(obj)])
            self.write_int(len(obj))
            for k, v in obj.items():
                self.write_object(k)
                self.write_object(v)
        elif isinstance(obj, TorchObject):
            self.write_int(T_TORCH)
            ref = self._heap(obj)
            if ref is not None:
                self.write_int(ref)
                return
            self.write_int(self.memo[id(obj)])
            self.write_string("V 1")
            self.write_string(obj.torch_class)
            self.write_object(obj.fields)
        else:
            raise TypeError(f"cannot serialize {type(obj).__name__} to .t7")

    def _write_tensor(self, obj: np.ndarray):
        kind = _NP_TO_TORCH.get(obj.dtype)
        if kind is None:
            raise TypeError(f"no torch tensor type for dtype {obj.dtype}")
        self.write_int(T_TORCH)
        ref = self._heap(obj)
        if ref is not None:
            self.write_int(ref)
            return
        arr = np.ascontiguousarray(obj)
        self.write_int(self.memo[id(obj)])
        self.write_string("V 1")
        self.write_string(f"torch.{kind}Tensor")
        self.write_int(arr.ndim)
        for s in arr.shape:
            self.write_long(s)
        # contiguous element strides
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self.write_long(s)
        self.write_long(1)  # storage offset, 1-based
        self.write_int(T_TORCH)
        self.write_int(self.next_idx)
        self.next_idx += 1
        self.write_string("V 1")
        self.write_string(f"torch.{kind}Storage")
        self.write_long(arr.size)
        self.f.write(arr.tobytes())


# ----------------------------------------------------- torch-nn -> modules

def _lua_list(table: Dict) -> List:
    """A Lua array-style table ({1: a, 2: b, ...}) as a Python list."""
    out = []
    i = 1
    while i in table:
        out.append(table[i])
        i += 1
    return out


def _f32(a) -> np.ndarray:
    return np.asarray(a, np.float32)


def _to_module(obj: TorchObject):
    """Map a Lua-Torch nn object onto (module, variables)."""
    from bigdl_tpu import nn

    cls = obj.torch_class.split(".")[-1]
    f = obj.fields

    if cls == "Sequential":
        children = [_to_module(m) for m in _lua_list(f.get("modules", {}))]
        seq = nn.Sequential(*[m for m, _ in children])
        variables = {"params": {}, "state": {}}
        for (child, cv), key in zip(children, seq._keys):
            variables["params"][key] = cv["params"]
            variables["state"][key] = cv["state"]
        return seq, variables
    if cls == "Linear":
        w = _f32(f["weight"])                      # (out, in)
        m = nn.Linear(w.shape[1], w.shape[0], with_bias="bias" in f)
        p = {"weight": w.T.copy()}
        if "bias" in f:
            p["bias"] = _f32(f["bias"]).reshape(-1)
        return m, {"params": p, "state": {}}
    if cls == "SpatialConvolution":
        n_in, n_out = int(f["nInputPlane"]), int(f["nOutputPlane"])
        kw, kh = int(f["kW"]), int(f["kH"])
        w = _f32(f["weight"]).reshape(n_out, n_in, kh, kw)  # OIHW
        m = nn.SpatialConvolution(
            n_in, n_out, kernel_w=kw, kernel_h=kh,
            stride_w=int(f.get("dW", 1)), stride_h=int(f.get("dH", 1)),
            pad_w=int(f.get("padW", 0)), pad_h=int(f.get("padH", 0)),
            with_bias="bias" in f)
        p = {"weight": w.transpose(2, 3, 1, 0).copy()}       # -> HWIO
        if "bias" in f:
            p["bias"] = _f32(f["bias"]).reshape(-1)
        return m, {"params": p, "state": {}}
    if cls in ("SpatialBatchNormalization", "BatchNormalization"):
        mean, var = _f32(f["running_mean"]), _f32(f["running_var"])
        affine = "weight" in f
        ctor = (nn.SpatialBatchNormalization
                if cls == "SpatialBatchNormalization"
                else nn.BatchNormalization)
        m = ctor(mean.shape[0], eps=float(f.get("eps", 1e-5)),
                 momentum=float(f.get("momentum", 0.1)), affine=affine)
        p = {}
        if affine:
            p = {"weight": _f32(f["weight"]), "bias": _f32(f["bias"])}
        return m, {"params": p,
                   "state": {"running_mean": mean, "running_var": var}}
    if cls == "SpatialMaxPooling":
        m = nn.SpatialMaxPooling(
            int(f["kW"]), int(f["kH"]), int(f.get("dW", f["kW"])),
            int(f.get("dH", f["kH"])), int(f.get("padW", 0)),
            int(f.get("padH", 0)))
        return m, {"params": {}, "state": {}}
    if cls == "SpatialAveragePooling":
        m = nn.SpatialAveragePooling(
            int(f["kW"]), int(f["kH"]), int(f.get("dW", f["kW"])),
            int(f.get("dH", f["kH"])), int(f.get("padW", 0)),
            int(f.get("padH", 0)))
        return m, {"params": {}, "state": {}}
    if cls == "Dropout":
        return nn.Dropout(float(f.get("p", 0.5))), {"params": {}, "state": {}}
    if cls in ("View", "Reshape"):
        size = f.get("size")
        dims = [int(d) for d in np.ravel(_lua_list(size)
                                         if isinstance(size, dict) else size)]
        return nn.Reshape(dims), {"params": {}, "state": {}}
    simple = {"ReLU": nn.ReLU, "Tanh": nn.Tanh, "Sigmoid": nn.Sigmoid,
              "LogSoftMax": nn.LogSoftMax, "SoftMax": nn.SoftMax,
              "Identity": nn.Identity}
    if cls in simple:
        return simple[cls](), {"params": {}, "state": {}}
    raise ValueError(f"unsupported Lua-Torch class in .t7: {obj.torch_class}")


# ----------------------------------------------------- modules -> torch-nn

def _zeros_like(a: np.ndarray) -> np.ndarray:
    return np.zeros_like(a)


def _from_module(module, variables) -> TorchObject:
    from bigdl_tpu import nn

    p = variables.get("params", {})
    s = variables.get("state", {})
    t = type(module).__name__

    if t == "Sequential":
        mods = []
        for key, child in zip(module._keys, module.modules):
            mods.append(_from_module(
                child, {"params": p.get(key, {}), "state": s.get(key, {})}))
        return TorchObject("nn.Sequential",
                           {"modules": {i + 1: m for i, m in enumerate(mods)},
                            "train": False})
    if t == "Linear":
        w = np.asarray(p["weight"]).T.copy()       # (in,out) -> (out,in)
        fields = {"weight": w, "gradWeight": _zeros_like(w)}
        if "bias" in p:
            b = np.asarray(p["bias"])
            fields.update(bias=b, gradBias=_zeros_like(b))
        return TorchObject("nn.Linear", fields)
    if t == "SpatialConvolution":
        if isinstance(module.pad_w, (tuple, list)) or \
                isinstance(module.pad_h, (tuple, list)):
            raise ValueError(
                "Torch7 SpatialConvolution has no asymmetric padding; "
                f"cannot export pad_w={module.pad_w}, "
                f"pad_h={module.pad_h} to .t7")
        w = np.asarray(p["weight"]).transpose(3, 2, 0, 1).copy()  # HWIO->OIHW
        fields = {
            "nInputPlane": module.n_input_plane,
            "nOutputPlane": module.n_output_plane,
            "kW": module.kernel_w, "kH": module.kernel_h,
            "dW": module.stride_w, "dH": module.stride_h,
            "padW": module.pad_w, "padH": module.pad_h,
            "weight": w, "gradWeight": _zeros_like(w),
        }
        if "bias" in p:
            b = np.asarray(p["bias"])
            fields.update(bias=b, gradBias=_zeros_like(b))
        return TorchObject("nn.SpatialConvolution", fields)
    if t in ("SpatialBatchNormalization", "BatchNormalization"):
        fields = {
            "running_mean": np.asarray(s["running_mean"]),
            "running_var": np.asarray(s["running_var"]),
            "eps": module.eps, "momentum": module.momentum,
            "affine": bool(p),
        }
        if p:
            fields.update(weight=np.asarray(p["weight"]),
                          bias=np.asarray(p["bias"]))
        return TorchObject(f"nn.{t}", fields)
    if t == "SpatialMaxPooling":
        return TorchObject("nn.SpatialMaxPooling", {
            "kW": module.kernel_w, "kH": module.kernel_h,
            "dW": module.stride_w, "dH": module.stride_h,
            "padW": module.pad_w, "padH": module.pad_h})
    if t == "SpatialAveragePooling":
        return TorchObject("nn.SpatialAveragePooling", {
            "kW": module.kernel_w, "kH": module.kernel_h,
            "dW": module.stride_w, "dH": module.stride_h,
            "padW": module.pad_w, "padH": module.pad_h})
    if t == "Dropout":
        return TorchObject("nn.Dropout", {"p": module.p})
    if t == "Reshape":
        return TorchObject("nn.Reshape",
                           {"size": [int(d) for d in module.size]})
    simple = {"ReLU": "nn.ReLU", "Tanh": "nn.Tanh", "Sigmoid": "nn.Sigmoid",
              "LogSoftMax": "nn.LogSoftMax", "SoftMax": "nn.SoftMax",
              "Identity": "nn.Identity"}
    if t in simple:
        return TorchObject(simple[t], {})
    raise ValueError(f"cannot export module {t} to .t7")


# ----------------------------------------------------------------- surface

def load_t7(path: str, to_module: bool = True):
    """Load a `.t7` file (reference: utils/TorchFile.scala#load).

    Tensors come back as numpy arrays, Lua tables as dicts. A Lua-Torch
    nn object (with `to_module=True`, the default) is mapped onto this
    framework: returns `(module, variables)`.
    """
    with open(path, "rb") as f:
        obj = _Reader(f).read_object()
    if to_module and isinstance(obj, TorchObject) \
            and obj.torch_class.startswith("nn."):
        return _to_module(obj)
    return obj


def save_t7(path: str, obj: Any, variables: Optional[Dict] = None):
    """Save to `.t7` (reference: utils/TorchFile.scala#save): numpy
    arrays as torch tensors, dicts/lists as tables, and a Module (+its
    `variables`, defaulting to the built ones) as the matching Lua-Torch
    nn object tree."""
    from bigdl_tpu.nn.module import Module

    if isinstance(obj, Module):
        if variables is None:
            variables = obj.variables
        obj = _from_module(obj, variables)
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)
