"""Shared interop helpers: flatten a module tree into a linear op list.

Used by the Caffe and TensorFlow persisters (reference: the per-format
`Converter` hierarchies under utils/caffe/ and utils/tf/ both walk the
module graph the same way).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from bigdl_tpu import nn
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.module import Module


def linearize(module: Module, variables: Dict[str, Any],
              n_inputs: int = 1) -> Tuple[List[Tuple[Module, Dict, List[int]]],
                                          List[int]]:
    """Flatten nested Sequential/Graph containers into a topo-ordered list
    of (leaf module, its variables, input entry ids). Entry id -1..-n are
    the graph inputs (-1 is the first); returns (entries, output_ids)."""
    entries: List[Tuple[Module, Dict, List[int]]] = []

    def walk(mod: Module, v: Dict[str, Any], in_ids: List[int]) -> List[int]:
        if isinstance(mod, Graph):
            id_of: Dict[int, List[int]] = {}
            if len(mod.input_nodes) == 1:
                id_of[id(mod.input_nodes[0])] = list(in_ids)
            else:
                for inp_node, gid in zip(mod.input_nodes, in_ids):
                    id_of[id(inp_node)] = [gid]
            for node in mod._order:
                if node.module is None:
                    continue
                key = mod._keys[id(node)]
                parent_ids = []
                for p in node.inputs:
                    parent_ids.extend(id_of[id(p)])
                sub_v = {"params": v["params"][key],
                         "state": v["state"][key]}
                id_of[id(node)] = walk(node.module, sub_v, parent_ids)
            outs = []
            for n in mod.output_nodes:
                outs.extend(id_of[id(n)])
            return outs
        if isinstance(mod, nn.Sequential):
            cur = in_ids
            for k, m in zip(mod._keys, mod.modules):
                sub_v = {"params": v["params"][k],
                         "state": v["state"][k]}
                cur = walk(m, sub_v, cur)
            return cur
        eid = len(entries)
        entries.append((mod, v, list(in_ids)))
        return [eid]

    out_ids = walk(module, variables, [-(i + 1) for i in range(n_inputs)])
    return entries, out_ids
