"""Import-time snapshots of the BIGDL_* performance env knobs.

Why this module exists: reading `os.environ` while jit traces a
function bakes the value into the first compiled executable for that
(shape, dtype, flags) combination — changing the variable afterwards
is a silent no-op for shapes already in jit's cache, and a sweep that
rotates the knob in-process silently measures one config under many
labels (the PR-1 flash-attention bwd-tiles lesson; graftlint rule
`trace-env-read` now bans env reads from compute code outright).

So every perf knob is resolved HERE, exactly once, at import — before
any trace can exist — and compute code reads the module-level
snapshot. The semantics become strictly more predictable than the old
trace-time read: the value in the environment when `bigdl_tpu` is
imported wins, full stop.

Legitimate in-process knob rotation (the fused-RNN tile sweep in
scripts/profile_bilstm.py, the kill-switch test) mutates the
environment and then calls `refresh()` — an *explicit* re-snapshot.
Callers doing that own the jit-cache consequence: already-compiled
shapes keep their old tiles; rotate knobs only with fresh shapes or
fresh jit roots (profile_bilstm builds a fresh jitted step per
config, so each re-traces under the new snapshot).

Knobs:

* `BIGDL_FUSED_RNN` — "0"/"false"/"off" disables the persistent-RNN
  Pallas kernels in auto mode (`FUSED_RNN_ENABLED`).
* `BIGDL_FUSED_RNN_BLOCK_N` — batch-tile row override for the fused
  RNN kernels (`FUSED_RNN_BLOCK_N`).
* `BIGDL_FLASH_FWD_TILES` / `BIGDL_FLASH_BWD_TILES` — "BQxBK" tile
  overrides for the flash-attention forward / fused-backward kernels
  (`FLASH_FWD_TILES` / `FLASH_BWD_TILES`). Malformed values raise at
  import — failing fast beats silently sweeping the default tiles.
* `BIGDL_PAGED_DECODE_TILES` — "BTxHT" (KV-block-tile x head-tile)
  override for the one-launch paged-attention decode kernel
  (`PAGED_DECODE_TILES`; ops/paged_decode.py). Both must divide the
  launch's block-table width / local head count — the kernel raises
  otherwise, same fail-fast contract as the flash tiles.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def _parse_tiles(var: str) -> Optional[Tuple[int, int]]:
    v = os.environ.get(var)
    if not v:
        return None
    try:
        bq, bk = v.lower().split("x")
        return int(bq), int(bk)
    except ValueError:
        raise ValueError(
            f"{var}={v!r}: expected 'BQxBK', e.g. '512x1024'") from None


def _parse_optional_int(var: str) -> Optional[int]:
    v = os.environ.get(var)
    return int(v) if v else None


def _parse_switch(var: str, default: str = "1") -> bool:
    return os.environ.get(var, default).lower() not in (
        "0", "false", "off")


FUSED_RNN_ENABLED: bool = True
FUSED_RNN_BLOCK_N: Optional[int] = None
FLASH_FWD_TILES: Optional[Tuple[int, int]] = None
FLASH_BWD_TILES: Optional[Tuple[int, int]] = None
PAGED_DECODE_TILES: Optional[Tuple[int, int]] = None


def refresh() -> None:
    """Re-snapshot every knob from the current environment. For
    in-process sweeps/tests that rotate a knob deliberately; see the
    module docstring for the jit-cache caveat."""
    global FUSED_RNN_ENABLED, FUSED_RNN_BLOCK_N
    global FLASH_FWD_TILES, FLASH_BWD_TILES, PAGED_DECODE_TILES
    FUSED_RNN_ENABLED = _parse_switch("BIGDL_FUSED_RNN")
    FUSED_RNN_BLOCK_N = _parse_optional_int("BIGDL_FUSED_RNN_BLOCK_N")
    FLASH_FWD_TILES = _parse_tiles("BIGDL_FLASH_FWD_TILES")
    FLASH_BWD_TILES = _parse_tiles("BIGDL_FLASH_BWD_TILES")
    PAGED_DECODE_TILES = _parse_tiles("BIGDL_PAGED_DECODE_TILES")


refresh()
