"""Table — heterogeneous, 1-indexed activity container.

Reference parity: utils/Table.scala#Table and the `T()` factory. In the
reference a Table is the `Activity` used for multi-input/multi-output
modules. Here a Table is a *pytree* (registered with JAX), so tables flow
through `jit`/`grad`/`vmap` unchanged; plain tuples/lists/dicts are equally
accepted anywhere an activity is expected.
"""

from __future__ import annotations

import jax


class Table(dict):
    """Dict with 1-indexed integer convenience access, registered as a pytree.

    ``T(a, b, c)`` builds ``Table({1: a, 2: b, 3: c})`` mirroring the
    reference's ``T()`` factory (utils/Table.scala#T.apply).
    """

    def insert(self, value):
        self[len(self) + 1] = value
        return self

    def __repr__(self):
        inner = ", ".join(f"{k}: {v!r}" for k, v in self.items())
        return f"Table({inner})"


def sort_key(k):
    """Order dict keys numerically first, then strings — `repr` ordering
    would put 10 before 2 and permute tables with >= 10 entries."""
    return (isinstance(k, str), k)


def _table_flatten(t: Table):
    keys = sorted(t.keys(), key=sort_key)
    return [t[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values):
    return Table(zip(keys, values))


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)


def T(*args, **kwargs) -> Table:
    """Build a Table: positional args become 1-indexed entries."""
    t = Table()
    for v in args:
        t.insert(v)
    for k, v in kwargs.items():
        t[k] = v
    return t
