"""Torch interop: import torch.nn models into bigdl_tpu modules.

Reference parity: utils/TorchFile.scala (SURVEY.md §2.5), split in two:
the Torch7 `.t7` wire format itself lives in utils/torch_file.py
(`load_t7`/`save_t7`); this module covers the modern Torch ecosystem —
PyTorch — converting `torch.nn` modules (architecture + weights) into
our Module/variables pair.

Layout conversions (we are NHWC/HWIO, torch is NCHW/OIHW):
    Linear.weight  (out, in)      → (in, out)
    Conv2d.weight  (O, I, kH, kW) → (kH, kW, I, O)
    converted conv/pool/bn modules consume NHWC input — feed images as
    (N, H, W, C); a leading `Transpose` is inserted automatically by
    `from_torch` only when you pass `input_layout="NCHW"`.

Import is by module-type dispatch over `torch.nn` containers; a clear
error names any unsupported layer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def _conv(tm) -> Tuple[Module, Dict[str, Any]]:
    if tm.groups != 1 and tm.groups != tm.in_channels:
        pass  # grouped conv maps directly via n_group
    m = nn.SpatialConvolution(
        tm.in_channels, tm.out_channels,
        kernel_w=tm.kernel_size[1], kernel_h=tm.kernel_size[0],
        stride_w=tm.stride[1], stride_h=tm.stride[0],
        pad_w=tm.padding[1], pad_h=tm.padding[0],
        n_group=tm.groups, with_bias=tm.bias is not None)
    w = _np(tm.weight).transpose(2, 3, 1, 0)  # OIHW → HWIO
    p = {"weight": w}
    if tm.bias is not None:
        p["bias"] = _np(tm.bias)
    return m, {"params": p, "state": {}}


def _linear(tm) -> Tuple[Module, Dict[str, Any]]:
    m = nn.Linear(tm.in_features, tm.out_features,
                  with_bias=tm.bias is not None)
    p = {"weight": _np(tm.weight).T}
    if tm.bias is not None:
        p["bias"] = _np(tm.bias)
    return m, {"params": p, "state": {}}


def _batchnorm(tm, spatial: bool) -> Tuple[Module, Dict[str, Any]]:
    cls = nn.SpatialBatchNormalization if spatial else nn.BatchNormalization
    m = cls(tm.num_features, eps=tm.eps, momentum=tm.momentum or 0.1,
            affine=tm.affine)
    p = {}
    if tm.affine:
        p = {"weight": _np(tm.weight), "bias": _np(tm.bias)}
    state = {"running_mean": _np(tm.running_mean),
             "running_var": _np(tm.running_var)}
    return m, {"params": p, "state": state}


def _embedding(tm) -> Tuple[Module, Dict[str, Any]]:
    m = nn.LookupTable(tm.num_embeddings, tm.embedding_dim)
    return m, {"params": {"weight": _np(tm.weight)}, "state": {}}


def _pair(v):
    return (v, v) if isinstance(v, int) else v


def _pool(tm, is_max: bool) -> Tuple[Module, Dict[str, Any]]:
    k = _pair(tm.kernel_size)
    s = _pair(tm.stride if tm.stride is not None else tm.kernel_size)
    pad = _pair(tm.padding)
    cls = nn.SpatialMaxPooling if is_max else nn.SpatialAveragePooling
    kw = dict(kernel_w=k[1], kernel_h=k[0], stride_w=s[1], stride_h=s[0],
              pad_w=pad[1], pad_h=pad[0],
              ceil_mode=bool(getattr(tm, "ceil_mode", False)))
    if not is_max:
        kw["count_include_pad"] = bool(getattr(tm, "count_include_pad",
                                               True))
    m = cls(**kw)
    return m, {"params": {}, "state": {}}


def from_torch(tm, input_layout: str = "NHWC"
               ) -> Tuple[Module, Dict[str, Any]]:
    """Convert a torch.nn module tree → (Module, variables).

    input_layout="NCHW" prepends an NCHW→NHWC transpose so the converted
    model accepts the same input tensors the torch model did.
    """
    import torch.nn as tnn

    def convert(tm) -> Tuple[Module, Dict[str, Any]]:
        if isinstance(tm, tnn.Sequential):
            children, params, state = [], {}, {}
            seq = nn.Sequential()
            for child in tm:
                cm, cv = convert(child)
                seq.add(cm)
                key = seq._keys[-1]
                params[key] = cv["params"]
                state[key] = cv["state"]
            return seq, {"params": params, "state": state}
        if isinstance(tm, tnn.Linear):
            return _linear(tm)
        if isinstance(tm, tnn.Conv2d):
            return _conv(tm)
        if isinstance(tm, tnn.BatchNorm2d):
            return _batchnorm(tm, spatial=True)
        if isinstance(tm, tnn.BatchNorm1d):
            return _batchnorm(tm, spatial=False)
        if isinstance(tm, tnn.Embedding):
            return _embedding(tm)
        if isinstance(tm, tnn.MaxPool2d):
            return _pool(tm, is_max=True)
        if isinstance(tm, tnn.AvgPool2d):
            return _pool(tm, is_max=False)
        if isinstance(tm, tnn.ReLU):
            return nn.ReLU(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.ReLU6):
            return nn.ReLU6(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.Tanh):
            return nn.Tanh(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.Sigmoid):
            return nn.Sigmoid(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.GELU):
            return nn.GELU(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.Softmax):
            return nn.SoftMax(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.LogSoftmax):
            return nn.LogSoftMax(), {"params": {}, "state": {}}
        if isinstance(tm, tnn.Dropout):
            return nn.Dropout(tm.p), {"params": {}, "state": {}}
        if isinstance(tm, tnn.Flatten):
            if getattr(tm, "start_dim", 1) != 1:
                raise NotImplementedError("Flatten(start_dim != 1)")
            return (nn.Reshape((-1,), batch_mode=True),
                    {"params": {}, "state": {}})
        if isinstance(tm, tnn.Identity):
            return nn.Identity(), {"params": {}, "state": {}}
        raise NotImplementedError(
            f"torch module {type(tm).__name__} has no bigdl_tpu mapping")

    module, variables = convert(tm)
    if input_layout == "NCHW":
        wrapped = nn.Sequential()
        # NCHW→NHWC via 1-based swap pairs: [N,C,H,W]→[N,H,C,W]→[N,H,W,C]
        wrapped.add(nn.Transpose(((2, 3), (3, 4))))
        wrapped.add(module)
        k0, k1 = wrapped._keys
        variables = {"params": {k0: {}, k1: variables["params"]},
                     "state": {k0: {}, k1: variables["state"]}}
        return wrapped, variables
    return module, variables
