"""Numerical-debug helpers.

Reference parity: SURVEY.md §5.2 — the reference has no sanitizers
(JVM memory safety + tensor confinement); the functional-JAX equivalents
are NaN trapping and deterministic seeding, provided here.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator

import jax
import jax.numpy as jnp

__all__ = ["debug_nans", "assert_all_finite", "deterministic"]


@contextlib.contextmanager
def debug_nans(enable: bool = True) -> Iterator[None]:
    """Trap NaNs at their producing op (jax_debug_nans): any jitted
    computation that produces a NaN re-runs un-jitted and raises with the
    exact primitive. Expensive — test/debug only."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def assert_all_finite(tree: Any, name: str = "tree") -> None:
    """Eager finite-ness check over a pytree (params, grads, …)."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            if not bool(jnp.isfinite(leaf).all()):
                bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(
            f"non-finite values in {name} at: {', '.join(bad)}")


@contextlib.contextmanager
def deterministic(seed: int = 0) -> Iterator[jax.Array]:
    """Deterministic-seed test mode: yields a PRNG key and pins the
    threefry partitionable implementation so the stream is identical
    across shardings/devices."""
    prev = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        yield jax.random.PRNGKey(seed)
    finally:
        jax.config.update("jax_threefry_partitionable", prev)
