"""Logging configuration (reference parity: utils/LoggerFilter.scala —
`redirectSparkInfoLogs` mutes Spark INFO chatter to a `bigdl.log` file
while keeping framework logs on the console)."""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

# the chatty third-party loggers we demote (the reference's equivalent
# list was org.apache.spark.*)
_NOISY = ("jax._src", "jax", "absl", "tensorflow", "h5py")


def redirect_logs(path: Optional[str] = None,
                  noisy: Sequence[str] = _NOISY,
                  console_level: int = logging.INFO) -> None:
    """Send noisy third-party INFO logs to `path` (default ./bigdl.log)
    instead of the console; framework loggers keep logging to console.

    Mirrors LoggerFilter.redirectSparkInfoLogs: chatter is preserved in
    the file for debugging but doesn't drown the training iteration log.
    """
    path = path or os.path.join(os.getcwd(), "bigdl.log")
    file_handler = logging.FileHandler(path)
    file_handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s - %(message)s"))
    for name in noisy:
        lg = logging.getLogger(name)
        lg.handlers = [file_handler]
        lg.propagate = False
        lg.setLevel(logging.INFO)

    root = logging.getLogger()
    if not root.handlers:
        console = logging.StreamHandler()
        console.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s - %(message)s"))
        root.addHandler(console)
    root.setLevel(console_level)
