"""Mixed-precision policy utilities.

TPU-first replacement for the reference's FP16 wire compression
(parameters/FP16CompressedTensor.scala): on TPU the MXU computes natively
in bfloat16, so instead of compressing gradients for the network we run
the whole forward/backward in bf16 while keeping fp32 master weights and
optimizer state — the standard mixed-precision recipe. bf16 shares
fp32's exponent range, so no loss scaling is needed (unlike fp16).

Usage::

    params32 = ...                      # master weights, float32
    def loss_fn(p32, x, y):
        p16 = cast_floats(p32, jnp.bfloat16)
        out, _ = model.apply({"params": p16, "state": state},
                             cast_floats(x, jnp.bfloat16))
        return criterion(jnp.asarray(out, jnp.float32), y)
    grads = jax.grad(loss_fn)(params32, x, y)   # grads are float32
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every floating-point leaf of a pytree to `dtype`; non-float
    leaves (int labels, rng keys, …) pass through untouched."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


class Policy:
    """A jmp-style precision policy: what dtype to store parameters in,
    compute in, and emit outputs in."""

    def __init__(self, param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                 output_dtype=jnp.float32):
        self.param_dtype = param_dtype
        self.compute_dtype = compute_dtype
        self.output_dtype = output_dtype

    def cast_to_compute(self, tree):
        return cast_floats(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return cast_floats(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return cast_floats(tree, self.output_dtype)


DEFAULT_MIXED = Policy()
FULL_PRECISION = Policy(compute_dtype=jnp.float32)
