"""Profiling / tracing.

Reference parity: SURVEY.md §5.1 — the reference has no tracer, only
per-iteration `optim/Metrics` counters and the `*OptimizerPerf` harness;
its TPU equivalent is `jax.profiler` TensorBoard traces plus fenced
per-step timing, both provided here.

Usage::

    with profiler.trace("/tmp/tb"):            # XLA+host trace
        for batch in data:
            with profiler.step(i):             # marks step boundaries
                step_fn(...)

    t = profiler.FencedTimer()
    with t:
        out = step_fn(...)
        t.fence(out)                           # device-honest timing
    logger.info("step %.3fs", t.elapsed)       # or obs registry — the
                                               # telemetry convention:
                                               # never print()

View traces in TensorBoard's Profile tab (the trace dir also contains
`.xplane.pb` files usable with `xprof`).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator, Optional

import jax

__all__ = ["trace", "step", "annotate", "FencedTimer", "device_sync"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (device + host) into `log_dir`."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step(step_num: int):
    """Annotate one training step inside a trace() region; shows up as a
    step marker in the TensorBoard profile."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step_num)


def annotate(name: str):
    """Named host-side trace region (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def device_sync(*values: Any) -> None:
    """Block until device work producing `values` is complete. Fetches one
    scalar-sized element per array to force a real device→host round-trip
    (plain block_until_ready can be optimistic through remote-device
    transports)."""
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(values):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            arr = jax.numpy.ravel(leaf)[:1] if getattr(leaf, "size", 1) else leaf
            np.asarray(arr)


class FencedTimer:
    """Wall-clock timer whose stop is fenced by a real device fetch, so it
    measures completed device work, not dispatch."""

    def __init__(self):
        self.elapsed: Optional[float] = None
        self._t0: Optional[float] = None
        self._fenced = False

    def __enter__(self) -> "FencedTimer":
        self._t0 = time.perf_counter()
        self._fenced = False
        return self

    def fence(self, *values: Any) -> None:
        device_sync(*values)
        self.elapsed = time.perf_counter() - self._t0
        self._fenced = True

    def __exit__(self, *exc) -> None:
        if not self._fenced:
            self.elapsed = time.perf_counter() - self._t0
