"""Object/tensor file IO (reference parity: utils/File.scala —
`File.save`/`File.load` with HDFS-aware paths).

Here the scheme dispatch covers local paths and `gs://` (via fsspec or
gcsfs when available — gated, not required); objects serialize with
pickle for parity with the reference's Java serialization, and pytrees of
arrays with `save_tensors`/`load_tensors` (npz)."""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Dict

import numpy as np

__all__ = ["save", "load", "save_tensors", "load_tensors"]


def _open(path: str, mode: str):
    if "://" in path and not path.startswith("file://"):
        try:
            import fsspec

            return fsspec.open(path, mode).open()
        except ImportError as e:
            raise NotImplementedError(
                f"remote path {path!r} needs fsspec installed") from e
    path = path[len("file://"):] if path.startswith("file://") else path
    if "w" in mode:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    return open(path, mode)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Serialize any python object (reference: File.save)."""
    if not overwrite and os.path.exists(path):
        raise FileExistsError(path)
    with _open(path, "wb") as f:
        pickle.dump(obj, f)


def load(path: str) -> Any:
    """Inverse of `save` (reference: File.load)."""
    with _open(path, "rb") as f:
        return pickle.load(f)


def save_tensors(tree: Dict[str, Any], path: str) -> None:
    """Save a flat dict (or pytree flattened by '/'-joined keys) of
    arrays as npz."""
    flat: Dict[str, np.ndarray] = {}

    def rec(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                rec(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    rec("", tree)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    with _open(path, "wb") as f:
        f.write(buf.getvalue())


def load_tensors(path: str) -> Dict[str, Any]:
    """Inverse of `save_tensors`; '/'-joined keys rebuild the nesting."""
    with _open(path, "rb") as f:
        data = np.load(io.BytesIO(f.read()))
    out: Dict[str, Any] = {}
    for key in data.files:
        parts = key.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = data[key]
    return out
