"""TensorFlow model interop (reference parity: utils/tf/ —
TensorflowLoader, TensorflowSaver, per-op converters)."""

from bigdl_tpu.utils.tf.loader import TensorflowLoader, load
from bigdl_tpu.utils.tf.saver import TensorflowSaver, save

__all__ = ["TensorflowLoader", "TensorflowSaver", "load", "save"]
