"""TensorFlow GraphDef export.

Reference parity: utils/tf/TensorflowSaver.scala — walk the module graph,
emit one or more TF nodes per module, write a frozen GraphDef that real
TensorFlow (or our own loader) can read. Weights are already NHWC/HWIO so
they serialize with no transposition.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.interop import linearize
from bigdl_tpu.utils.tf import bigdl_tf_pb2 as pb

__all__ = ["TensorflowSaver", "save"]


def _set_shape(shape_proto, dims):
    for d in dims:
        shape_proto.dim.add().size = int(d)


class TensorflowSaver:
    """Export (module, variables) → frozen GraphDef .pb."""

    def __init__(self, module: Module, variables: Dict[str, Any],
                 input_shape: Sequence[int], input_name: str = "input"):
        self.module = module
        self.variables = variables
        self.input_shape = tuple(int(d) for d in input_shape)  # NHWC
        self.input_name = input_name
        self._names: Dict[str, int] = {}

    def _fresh(self, base: str) -> str:
        base = base.replace("/", "_")
        n = self._names.get(base, 0)
        self._names[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    # ---- node emission helpers ----------------------------------------

    def _node(self, gd, op: str, name: str, inputs: Sequence[str],
              dtype: int = pb.DT_FLOAT) -> Any:
        n = gd.node.add()
        n.name = self._fresh(name)
        n.op = op
        n.input.extend(inputs)
        n.attr["T"].type = dtype
        return n

    def _const(self, gd, name: str, arr: np.ndarray) -> str:
        arr = np.asarray(arr)
        if arr.dtype in (np.float64,):
            arr = arr.astype(np.float32)
        n = gd.node.add()
        n.name = self._fresh(name)
        n.op = "Const"
        dt = {np.dtype(np.float32): pb.DT_FLOAT,
              np.dtype(np.int32): pb.DT_INT32,
              np.dtype(np.int64): pb.DT_INT64}[arr.dtype]
        n.attr["dtype"].type = dt
        t = n.attr["value"].tensor
        t.dtype = dt
        _set_shape(t.tensor_shape, arr.shape)
        t.tensor_content = np.ascontiguousarray(arr).tobytes()
        return n.name

    # ---- per-module emitters ------------------------------------------

    def build_graph(self) -> Any:
        gd = pb.GraphDef()
        gd.versions.producer = 27
        ph = gd.node.add()
        ph.name = self._fresh(self.input_name)
        ph.op = "Placeholder"
        ph.attr["dtype"].type = pb.DT_FLOAT
        # batch dim exported as unknown (-1) so any batch size feeds
        _set_shape(ph.attr["shape"].shape, (-1,) + self.input_shape[1:])

        entries, out_ids = linearize(self.module, self.variables)
        ref_of = {-1: ph.name}
        for i, (mod, v, in_ids) in enumerate(entries):
            ins = [ref_of[j] for j in in_ids]
            ref_of[i] = self._emit(gd, mod, v, ins)
        # mark outputs with a stable Identity node
        for k, oid in enumerate(out_ids):
            self._node(gd, "Identity", f"output_{k}" if k else "output",
                       [ref_of[oid]])
        return gd

    def save(self, path: str) -> None:
        gd = self.build_graph()
        with open(path, "wb") as f:
            f.write(gd.SerializeToString())

    def _emit(self, gd, mod: Module, v: Dict[str, Any],
              ins: List[str]) -> str:
        p = v.get("params", {})
        s = v.get("state", {})
        name = mod.name or type(mod).__name__

        if isinstance(mod, nn.SpatialConvolution):
            w = self._const(gd, f"{name}_w", np.asarray(p["weight"]))
            same = mod.pad_w == -1
            if not same and (mod.pad_w or mod.pad_h):
                pads = self._const(gd, f"{name}_pads", np.asarray(
                    [[0, 0], [mod.pad_h, mod.pad_h],
                     [mod.pad_w, mod.pad_w], [0, 0]], np.int32))
                pad_n = self._node(gd, "Pad", f"{name}_pad", [ins[0], pads])
                pad_n.attr["Tpaddings"].type = pb.DT_INT32
                src = pad_n.name
            else:
                src = ins[0]
            conv = self._node(gd, "Conv2D", name, [src, w])
            conv.attr["strides"].list.i.extend(
                [1, mod.stride_h, mod.stride_w, 1])
            conv.attr["padding"].s = b"SAME" if same else b"VALID"
            conv.attr["data_format"].s = b"NHWC"
            if isinstance(mod, nn.SpatialDilatedConvolution):
                conv.attr["dilations"].list.i.extend(
                    [1, mod.dilation_h, mod.dilation_w, 1])
            out = conv.name
            if mod.with_bias:
                b = self._const(gd, f"{name}_b", np.asarray(p["bias"]))
                out = self._node(gd, "BiasAdd", f"{name}_biasadd",
                                 [out, b]).name
            return out

        if isinstance(mod, nn.Linear):
            w = self._const(gd, f"{name}_w", np.asarray(p["weight"]))
            mm = self._node(gd, "MatMul", name, [ins[0], w])
            mm.attr["transpose_a"].b = False
            mm.attr["transpose_b"].b = False
            out = mm.name
            if mod.with_bias:
                b = self._const(gd, f"{name}_b", np.asarray(p["bias"]))
                out = self._node(gd, "BiasAdd", f"{name}_biasadd",
                                 [out, b]).name
            return out

        if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            op = "MaxPool" if isinstance(mod, nn.SpatialMaxPooling) \
                else "AvgPool"
            n = self._node(gd, op, name, [ins[0]])
            n.attr["ksize"].list.i.extend([1, mod.kernel_h, mod.kernel_w, 1])
            n.attr["strides"].list.i.extend(
                [1, mod.stride_h, mod.stride_w, 1])
            n.attr["padding"].s = b"SAME" if mod.pad_w == -1 else b"VALID"
            n.attr["data_format"].s = b"NHWC"
            if mod.pad_w not in (-1, 0) or mod.pad_h not in (-1, 0):
                raise NotImplementedError(
                    "TF export of explicitly-padded pooling")
            return n.name

        if isinstance(mod, (nn.BatchNormalization,
                            nn.SpatialBatchNormalization)):
            scale = np.asarray(p["weight"]) if "weight" in p else \
                np.ones(mod.n_output, np.float32)
            offset = np.asarray(p["bias"]) if "bias" in p else \
                np.zeros(mod.n_output, np.float32)
            n = self._node(gd, "FusedBatchNorm", name, [
                ins[0],
                self._const(gd, f"{name}_scale", scale),
                self._const(gd, f"{name}_offset", offset),
                self._const(gd, f"{name}_mean",
                            np.asarray(s["running_mean"])),
                self._const(gd, f"{name}_var",
                            np.asarray(s["running_var"])),
            ])
            n.attr["epsilon"].f = mod.eps
            n.attr["is_training"].b = False
            n.attr["data_format"].s = b"NHWC"
            return n.name

        simple = {nn.ReLU: "Relu", nn.ReLU6: "Relu6", nn.Tanh: "Tanh",
                  nn.Sigmoid: "Sigmoid", nn.ELU: "Elu",
                  nn.SoftPlus: "Softplus", nn.SoftSign: "Softsign",
                  nn.SoftMax: "Softmax", nn.LogSoftMax: "LogSoftmax",
                  nn.Abs: "Abs", nn.Exp: "Exp", nn.Log: "Log",
                  nn.Sqrt: "Sqrt", nn.Square: "Square"}
        for cls, op in simple.items():
            if type(mod) is cls:
                return self._node(gd, op, name, [ins[0]]).name
        if isinstance(mod, nn.LeakyReLU):
            n = self._node(gd, "LeakyRelu", name, [ins[0]])
            n.attr["alpha"].f = mod.negval
            return n.name
        if isinstance(mod, (nn.Dropout, nn.Identity)):
            # inference export: dropout is identity (reference does the same)
            return self._node(gd, "Identity", name, [ins[0]]).name

        if isinstance(mod, nn.Reshape):
            dims = list(mod.size)
            if mod.batch_mode is not False:
                dims = [-1] + dims
            shape = self._const(gd, f"{name}_shape",
                                np.asarray(dims, np.int32))
            n = self._node(gd, "Reshape", name, [ins[0], shape])
            n.attr["Tshape"].type = pb.DT_INT32
            return n.name

        if isinstance(mod, nn.JoinTable):
            axis = self._const(gd, f"{name}_axis",
                               np.asarray(mod.dimension - 1, np.int32))
            n = self._node(gd, "ConcatV2", name, list(ins) + [axis])
            n.attr["N"].i = len(ins)
            n.attr["Tidx"].type = pb.DT_INT32
            return n.name
        if isinstance(mod, nn.CAddTable):
            if len(ins) == 2:
                return self._node(gd, "AddV2", name, ins).name
            n = self._node(gd, "AddN", name, ins)
            n.attr["N"].i = len(ins)
            return n.name
        if isinstance(mod, nn.CMulTable):
            return self._node(gd, "Mul", name, ins).name
        if isinstance(mod, nn.CSubTable):
            return self._node(gd, "Sub", name, ins).name
        if isinstance(mod, nn.CMaxTable):
            return self._node(gd, "Maximum", name, ins).name
        if isinstance(mod, nn.CAdd):
            b = self._const(gd, f"{name}_b", np.asarray(p["bias"]))
            if len(mod.size) == 1:
                return self._node(gd, "BiasAdd", name, [ins[0], b]).name
            return self._node(gd, "AddV2", name, [ins[0], b]).name

        if isinstance(mod, nn.SpatialCrossMapLRN):
            n = self._node(gd, "LRN", name, [ins[0]])
            n.attr["depth_radius"].i = (mod.size - 1) // 2
            n.attr["alpha"].f = mod.alpha / mod.size
            n.attr["beta"].f = mod.beta
            n.attr["bias"].f = mod.k
            return n.name

        raise NotImplementedError(
            f"TF export of {type(mod).__name__} ({name})")


def save(module: Module, variables: Dict[str, Any], path: str,
         input_shape: Sequence[int], input_name: str = "input") -> None:
    """Convenience: TensorflowSaver(...).save(path)."""
    TensorflowSaver(module, variables, input_shape, input_name).save(path)
