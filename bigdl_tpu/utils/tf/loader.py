"""TensorFlow frozen-graph interop: load a GraphDef into a bigdl_tpu
Graph, per-op converters, numpy const evaluation.

Reference parity: utils/tf/TensorflowLoader.scala (frozen GraphDef →
module graph via per-op converters under utils/tf/loaders/),
utils/tf/TensorflowSaver.scala (the mirror writer lives in saver.py).
The reference also ships a mini TF training session
(utils/tf/BigDLSessionImpl.scala); here importing a frozen graph yields a
native trainable model directly — every converted layer's parameters are
ordinary pytree leaves, so `Optimizer` fine-tunes them like any other
model and no session shim is needed.

TPU-first notes
---------------
TF frozen graphs are already NHWC/HWIO — this framework's native layouts —
so conv/linear weights load with **zero transposition** (unlike the Caffe
path). Parsing uses the bundled wire-compatible proto subset
(bigdl_tf.proto); real TensorFlow is never imported.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.graph import Graph, Input, Node
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.tf import bigdl_tf_pb2 as pb

__all__ = ["TensorflowLoader", "load"]

_NP_DTYPES = {
    pb.DT_FLOAT: np.float32,
    pb.DT_DOUBLE: np.float64,
    pb.DT_INT32: np.int32,
    pb.DT_INT64: np.int64,
    pb.DT_BOOL: np.bool_,
    pb.DT_UINT8: np.uint8,
    pb.DT_INT8: np.int8,
    pb.DT_INT16: np.int16,
    pb.DT_BFLOAT16: np.float32,  # widened on read
}

_VAL_FIELDS = {
    pb.DT_FLOAT: "float_val",
    pb.DT_DOUBLE: "double_val",
    pb.DT_INT32: "int_val",
    pb.DT_INT64: "int64_val",
    pb.DT_BOOL: "bool_val",
}

_PASSTHROUGH_OPS = {"Identity", "StopGradient", "CheckNumerics",
                    "PreventGradient", "Snapshot"}

_ACTIVATIONS = {
    "Relu": nn.ReLU, "Relu6": nn.ReLU6, "Tanh": nn.Tanh,
    "Sigmoid": nn.Sigmoid, "Elu": nn.ELU, "Softplus": nn.SoftPlus,
    "Softsign": nn.SoftSign, "Softmax": nn.SoftMax,
    "LogSoftmax": nn.LogSoftMax, "Abs": nn.Abs, "Exp": nn.Exp,
    "Log": nn.Log, "Sqrt": nn.Sqrt, "Square": nn.Square,
}

_BINARY_OPS = {
    "Add": nn.CAddTable, "AddV2": nn.CAddTable, "Sub": nn.CSubTable,
    "Mul": nn.CMulTable, "RealDiv": nn.CDivTable,
    "Maximum": nn.CMaxTable, "Minimum": nn.CMinTable,
}

# constant folding: frozen keras graphs decompose BatchNorm into
# rsqrt(var+eps)*gamma / beta-mean*... chains whose inner nodes are
# pure-const arithmetic — fold them at load so only the data-path
# Mul/Add (affine scale/bias, below) needs a module
_FOLDABLE = {
    "Add": np.add, "AddV2": np.add, "Sub": np.subtract,
    "Mul": np.multiply, "RealDiv": np.divide,
    "Maximum": np.maximum, "Minimum": np.minimum,
    "Rsqrt": lambda a: 1.0 / np.sqrt(a), "Sqrt": np.sqrt,
    "Square": np.square, "Neg": np.negative, "Exp": np.exp,
    "Log": np.log, "Abs": np.abs,
    "Reshape": lambda a, s: np.reshape(a, [int(x) for x in s]),
}


def _tensor_to_np(t) -> np.ndarray:
    dtype = _NP_DTYPES.get(t.dtype)
    if dtype is None:
        raise NotImplementedError(f"TF dtype {t.dtype}")
    shape = tuple(int(d.size) for d in t.tensor_shape.dim)
    if t.tensor_content:
        if t.dtype == pb.DT_BFLOAT16:
            raw = np.frombuffer(t.tensor_content, np.uint16).astype(np.uint32)
            return (raw << 16).view(np.float32).reshape(shape).copy()
        return np.frombuffer(t.tensor_content, dtype).reshape(shape).copy()
    field = _VAL_FIELDS.get(t.dtype)
    if field is None:
        raise NotImplementedError(f"TF dtype {t.dtype} without content")
    vals = np.asarray(list(getattr(t, field)), dtype)
    if vals.size == 0:
        return np.zeros(shape, dtype)
    n = int(np.prod(shape)) if shape else 1
    if vals.size == 1 and n > 1:  # splat encoding
        vals = np.full(n, vals[0], dtype)
    return vals.reshape(shape)


def _require_nhwc(tf_node) -> None:
    """Converters assume NHWC (the framework's native layout). NCHW
    frozen graphs (GPU-trained) would import with silently wrong
    results — refuse instead."""
    fmt = tf_node.attr["data_format"].s if "data_format" in tf_node.attr \
        else b""
    if fmt not in (b"", b"NHWC"):
        raise NotImplementedError(
            f"{tf_node.name}: data_format={fmt.decode()!r} — only NHWC "
            "frozen graphs are supported (transpose the graph to NHWC "
            "before freezing)")


def _norm(ref: str) -> Optional[str]:
    """'name:0' → 'name'; '^name' (control dep) → None."""
    if ref.startswith("^"):
        return None
    return ref.split(":")[0]


class TensorflowLoader:
    """Load a frozen TF GraphDef (.pb) → (Graph, variables).

    `inputs`/`outputs` name the boundary nodes, as in the reference's
    TensorflowLoader.load(graphFile, inputs, outputs); both default to
    being inferred (Placeholders / unconsumed nodes).
    """

    def __init__(self, graph_path: str,
                 inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None):
        self.graph_path = graph_path
        self.inputs = list(inputs) if inputs else None
        self.outputs = list(outputs) if outputs else None

    # ---- graph assembly -----------------------------------------------

    def load(self) -> Tuple[Graph, Dict[str, Any]]:
        import jax

        graph_def = pb.GraphDef()
        with open(self.graph_path, "rb") as f:
            graph_def.ParseFromString(f.read())

        nodes = {n.name: n for n in graph_def.node}
        consts: Dict[str, np.ndarray] = {}
        mod_node: Dict[str, Node] = {}
        node_vars: Dict[int, Dict[str, Any]] = {}
        input_nodes: List[Node] = []
        input_names = []

        def const_of(name: str) -> Optional[np.ndarray]:
            """Resolve `name` to a numpy constant, through passthrough ops."""
            if name in consts:
                return consts[name]
            n = nodes.get(name)
            while n is not None and n.op in _PASSTHROUGH_OPS:
                nxt = _norm(n.input[0])
                if nxt in consts:
                    return consts[nxt]
                n = nodes.get(nxt)
            return None

        def wire(module: Module, parents: List[Node], name: str,
                 variables: Optional[Dict[str, Any]] = None) -> Node:
            module.set_name(name.replace("/", "_"))
            node = Node.wire(module, parents)
            if variables is not None:
                node_vars[id(node)] = variables
            return node

        order = self._topo_order(nodes)
        for tf_node in order:
            name, op = tf_node.name, tf_node.op
            ins = [i for i in (_norm(r) for r in tf_node.input)
                   if i is not None]
            if op == "Const":
                consts[name] = _tensor_to_np(tf_node.attr["value"].tensor)
                continue
            if op in ("NoOp",):
                continue
            if op == "Placeholder" or op == "PlaceholderV2":
                if self.inputs is not None and name not in self.inputs:
                    continue
                node = Input()
                mod_node[name] = node
                input_nodes.append(node)
                input_names.append(name)
                continue
            if op in _PASSTHROUGH_OPS:
                if ins and ins[0] in mod_node:
                    mod_node[name] = mod_node[ins[0]]
                continue
            if op in _FOLDABLE and ins and not any(i in mod_node
                                                   for i in ins):
                vals = [const_of(i) for i in ins]
                if all(v is not None for v in vals):
                    consts[name] = np.asarray(_FOLDABLE[op](*vals))
                    continue
            if op == "Squeeze" and ins and ins[0] not in mod_node:
                val = const_of(ins[0])
                if val is not None:
                    dims = tuple(int(d) for d in
                                 tf_node.attr["squeeze_dims"].list.i)
                    consts[name] = np.squeeze(val, dims or None)
                    continue
            handled = self._convert(tf_node, op, ins, consts, const_of,
                                    mod_node, wire)
            if handled is not None:
                mod_node[name] = handled

        outputs = self.outputs
        if outputs is None:
            consumed = set()
            for n in graph_def.node:
                consumed.update(i for i in (_norm(r) for r in n.input) if i)
            outputs = [n.name for n in graph_def.node
                       if n.name not in consumed and n.name in mod_node
                       and mod_node[n.name] not in input_nodes]
        out_nodes, seen = [], set()
        for o in outputs:
            node = mod_node.get(_norm(o))
            if node is None:
                raise ValueError(f"output {o!r} not found/convertible")
            if id(node) not in seen:
                seen.add(id(node))
                out_nodes.append(node)
        if not out_nodes:
            raise ValueError("TF graph has no convertible output nodes")

        if self.inputs is not None:
            order_map = {n: i for i, n in enumerate(self.inputs)}
            pairs = sorted(zip(input_names, input_nodes),
                           key=lambda p: order_map.get(p[0], 1 << 30))
            input_nodes = [p[1] for p in pairs]

        graph = Graph(input_nodes, out_nodes)
        variables = graph.init(jax.random.PRNGKey(0))
        for node_id, v in node_vars.items():
            key = graph._keys.get(node_id)
            if key is not None:
                variables["params"][key] = v["params"]
                variables["state"][key] = v["state"]
        return graph, variables

    @staticmethod
    def _topo_order(nodes: Dict[str, Any]) -> List[Any]:
        seen: Dict[str, int] = {}
        out: List[Any] = []

        def visit(name: str):
            state = seen.get(name)
            if state == 2:
                return
            if state == 1:
                raise ValueError(f"cycle at TF node {name!r}")
            seen[name] = 1
            n = nodes.get(name)
            if n is not None:
                for r in n.input:
                    nr = _norm(r)
                    if nr is not None and nr in nodes:
                        visit(nr)
                out.append(n)
            seen[name] = 2

        for name in nodes:
            visit(name)
        return out

    # ---- per-op converters --------------------------------------------

    def _convert(self, tf_node, op, ins, consts, const_of, mod_node, wire
                 ) -> Optional[Node]:
        attr = tf_node.attr
        name = tf_node.name

        def parent(i=0) -> Node:
            p = mod_node.get(ins[i])
            if p is None:
                raise NotImplementedError(
                    f"node {name!r} ({op}): input {ins[i]!r} is not a "
                    f"converted module (unsupported producer)")
            return p

        if op in _ACTIVATIONS:
            return wire(_ACTIVATIONS[op](), [parent()], name)
        if op == "LeakyRelu":
            alpha = attr["alpha"].f if "alpha" in attr else 0.2
            return wire(nn.LeakyReLU(alpha), [parent()], name)
        if op == "Neg":
            return wire(nn.Power(1.0, -1.0, 0.0), [parent()], name)
        if op == "Rsqrt":
            return wire(nn.Power(-0.5, 1.0, 0.0), [parent()], name)

        if op == "Conv2D":
            return self._conv2d(tf_node, ins, const_of, parent, wire)
        if op == "DepthwiseConv2dNative":
            return self._depthwise(tf_node, ins, const_of, parent, wire)
        if op == "MatMul":
            w = const_of(ins[1])
            if w is None:
                x, y = parent(0), parent(1)
                return wire(nn.MM(trans_a=attr["transpose_a"].b,
                                  trans_b=attr["transpose_b"].b),
                            [x, y], name)
            if attr["transpose_a"].b:
                raise NotImplementedError(
                    f"{name}: MatMul with transpose_a on the const-weight "
                    "path is not supported (would silently transpose the "
                    "activations)")
            if attr["transpose_b"].b:
                w = w.T
            lin = nn.Linear(w.shape[0], w.shape[1], with_bias=False)
            return wire(lin, [parent()], name,
                        {"params": {"weight": w.astype(np.float32)},
                         "state": {}})
        if op == "BiasAdd":
            _require_nhwc(tf_node)
            b = const_of(ins[1])
            if b is None:
                return wire(nn.CAddTable(), [parent(0), parent(1)], name)
            cadd = nn.CAdd(tuple(b.shape))
            return wire(cadd, [parent()], name,
                        {"params": {"bias": b.astype(np.float32)},
                         "state": {}})
        if op in _BINARY_OPS:
            rhs = const_of(ins[1]) if len(ins) > 1 else None
            lhs = const_of(ins[0])
            if rhs is not None and rhs.size == 1:
                c = float(rhs.reshape(()))
                scale, shift = {"Mul": (c, 0.0), "RealDiv": (1.0 / c, 0.0),
                                "Add": (1.0, c), "AddV2": (1.0, c),
                                "Sub": (1.0, -c)}.get(op, (None, None))
                if scale is not None:
                    return wire(nn.Power(1.0, scale, shift), [parent(0)],
                                name)
            if lhs is not None and lhs.size == 1 and op in ("Add", "AddV2",
                                                            "Mul"):
                c = float(lhs.reshape(()))
                scale, shift = (c, 0.0) if op == "Mul" else (1.0, c)
                return wire(nn.Power(1.0, scale, shift), [parent(1)], name)
            # data (×|+) const VECTOR — the data-path half of a frozen
            # decomposed BatchNorm: an affine CMul/CAdd with the folded
            # constant as its (trainable, fine-tunable) weight
            cv, pi = (rhs, 0) if rhs is not None else (lhs, 1)
            if cv is not None and (pi == 0 or op in ("Add", "AddV2",
                                                     "Mul")):
                w = cv.astype(np.float32)
                if op == "Mul":
                    return wire(nn.CMul(w.shape), [parent(pi)], name,
                                {"params": {"weight": w}, "state": {}})
                if op == "RealDiv":
                    return wire(nn.CMul(w.shape), [parent(pi)], name,
                                {"params": {"weight": 1.0 / w},
                                 "state": {}})
                if op in ("Add", "AddV2"):
                    return wire(nn.CAdd(w.shape), [parent(pi)], name,
                                {"params": {"bias": w}, "state": {}})
                if op == "Sub":  # data - const
                    return wire(nn.CAdd(w.shape), [parent(pi)], name,
                                {"params": {"bias": -w}, "state": {}})
            return wire(_BINARY_OPS[op](), [parent(0), parent(1)], name)

        if op in ("MaxPool", "AvgPool"):
            _require_nhwc(tf_node)
            ks = [int(i) for i in attr["ksize"].list.i]
            st = [int(i) for i in attr["strides"].list.i]
            same = attr["padding"].s == b"SAME"
            pad = -1 if same else 0
            if op == "MaxPool":
                m = nn.SpatialMaxPooling(ks[2], ks[1], st[2], st[1],
                                         pad_w=pad, pad_h=pad)
            else:
                # TF AvgPool never counts padded cells
                m = nn.SpatialAveragePooling(ks[2], ks[1], st[2], st[1],
                                             pad_w=pad, pad_h=pad,
                                             count_include_pad=False)
            return wire(m, [parent()], name)

        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            _require_nhwc(tf_node)
            scale = const_of(ins[1])
            offset = const_of(ins[2])
            mean = const_of(ins[3])
            var = const_of(ins[4])
            if any(a is None for a in (scale, offset, mean, var)):
                raise NotImplementedError(
                    f"{name}: FusedBatchNorm with non-const params "
                    "(training-mode graph?) — freeze the graph first")
            eps = attr["epsilon"].f if "epsilon" in attr else 1e-3
            bn = nn.SpatialBatchNormalization(int(scale.shape[0]), eps=eps)
            v = {"params": {"weight": scale.astype(np.float32),
                            "bias": offset.astype(np.float32)},
                 "state": {"running_mean": mean.astype(np.float32),
                           "running_var": var.astype(np.float32)}}
            return wire(bn, [parent()], name, v)

        if op == "Reshape":
            shape = const_of(ins[1])
            if shape is None:
                shape = self._flatten_shape_idiom(ins[1])
            if shape is None:
                raise NotImplementedError(
                    f"{name}: Reshape with dynamic shape")
            dims = [int(d) for d in np.asarray(shape).ravel()]
            if len(dims) >= 1 and (dims[0] == -1 or dims[0] > 0):
                # leading dim is the batch in frozen inference graphs
                return wire(nn.Reshape(dims[1:] if len(dims) > 1 else [-1],
                                       batch_mode=True), [parent()], name)
            return wire(nn.Reshape(dims, batch_mode=False), [parent()],
                        name)
        if op == "Squeeze":
            dims = [int(i) for i in attr["squeeze_dims"].list.i]
            if not dims:
                m = nn.Squeeze()
            elif len(dims) == 1:
                m = nn.Squeeze(dims[0] + 1)
            else:
                m = nn.Sequential()
                for d in sorted(dims, reverse=True):  # descending: safe
                    m.add(nn.Squeeze(d + 1))
            return wire(m, [parent()], name)
        if op == "ExpandDims":
            ax = const_of(ins[1])
            if ax is None:
                raise NotImplementedError(f"{name}: dynamic ExpandDims")
            return wire(nn.Unsqueeze(int(ax) + 1), [parent()], name)

        if op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = const_of(ins[-1])
                data_ins = ins[:-1]
            else:  # legacy: axis first
                axis = const_of(ins[0])
                data_ins = ins[1:]
            if axis is None:
                raise NotImplementedError(f"{name}: dynamic concat axis")
            parents = [mod_node[i] for i in data_ins]
            return wire(nn.JoinTable(dimension=int(axis) + 1),
                        parents, name)

        if op == "Mean":
            axes = const_of(ins[1])
            if axes is None:
                raise NotImplementedError(f"{name}: dynamic Mean axes")
            keep = attr["keep_dims"].b if "keep_dims" in attr else False
            axes = sorted(int(a) for a in np.asarray(axes).ravel())
            seq = nn.Sequential()
            for a in reversed(axes):  # descending: safe when squeezing
                seq.add(nn.Mean(dimension=a + 1, squeeze=not keep))
            return wire(seq, [parent()], name)

        if op == "Pad":
            pads = const_of(ins[1])
            if pads is None:
                raise NotImplementedError(f"{name}: dynamic Pad")
            pads = np.asarray(pads)
            if pads.shape[0] == 4 and not pads[0].any() and not \
                    pads[3].any():
                (t, b), (l, r) = pads[1], pads[2]
                return wire(nn.SpatialZeroPadding(int(l), int(r), int(t),
                                                  int(b)), [parent()], name)
            raise NotImplementedError(f"{name}: non-spatial Pad")

        if op == "LRN":
            r = int(attr["depth_radius"].i) if "depth_radius" in attr else 5
            alpha = attr["alpha"].f if "alpha" in attr else 1.0
            beta = attr["beta"].f if "beta" in attr else 0.5
            bias = attr["bias"].f if "bias" in attr else 1.0
            size = 2 * r + 1
            # TF alpha is per-element; ours (like caffe/torch) is summed
            return wire(nn.SpatialCrossMapLRN(size, alpha * size, beta,
                                              bias), [parent()], name)

        if op in ("Pack", "Shape", "StridedSlice", "Fill"):
            return None  # shape-arithmetic scaffolding; consumed elsewhere

        raise NotImplementedError(f"TF op {op!r} (node {name!r})")

    def _conv2d(self, tf_node, ins, const_of, parent, wire):
        attr = tf_node.attr
        _require_nhwc(tf_node)
        w = const_of(ins[1])  # HWIO — native layout, no transpose
        if w is None:
            raise NotImplementedError(f"{tf_node.name}: non-const filter")
        st = [int(i) for i in attr["strides"].list.i]
        same = attr["padding"].s == b"SAME"
        dil = [int(i) for i in attr["dilations"].list.i] or [1, 1, 1, 1]
        kh, kw, n_in, n_out = w.shape
        pad = -1 if same else 0
        if dil[1] == 1 and dil[2] == 1:
            m = nn.SpatialConvolution(n_in, n_out, kw, kh, st[2], st[1],
                                      pad, pad, with_bias=False)
        else:
            m = nn.SpatialDilatedConvolution(
                n_in, n_out, kw, kh, st[2], st[1], pad, pad,
                dilation_w=dil[2], dilation_h=dil[1], with_bias=False)
        return wire(m, [parent()], tf_node.name,
                    {"params": {"weight": w.astype(np.float32)},
                     "state": {}})

    def _depthwise(self, tf_node, ins, const_of, parent, wire):
        attr = tf_node.attr
        _require_nhwc(tf_node)
        w = const_of(ins[1])  # (H, W, C, mult)
        if w is None:
            raise NotImplementedError(f"{tf_node.name}: non-const filter")
        st = [int(i) for i in attr["strides"].list.i]
        same = attr["padding"].s == b"SAME"
        kh, kw, c, mult = w.shape
        pad = -1 if same else 0
        m = nn.SpatialConvolution(c, c * mult, kw, kh, st[2], st[1],
                                  pad, pad, n_group=c, with_bias=False)
        # grouped-conv weight (H, W, I/g=1, O=C*mult): channel c's
        # multipliers occupy O slots [c*mult, (c+1)*mult) — exactly the
        # C-major flatten of TF's trailing (C, mult) dims
        wg = np.ascontiguousarray(w.reshape(kh, kw, 1, c * mult))
        return wire(m, [parent()], tf_node.name,
                    {"params": {"weight": wg.astype(np.float32)},
                     "state": {}})

    def _flatten_shape_idiom(self, shape_ref: str) -> Optional[list]:
        # The Shape→StridedSlice→Pack flatten idiom needs runtime shapes;
        # frozen inference graphs almost always have const shapes instead.
        return None


def load(graph_path: str, inputs: Optional[Sequence[str]] = None,
         outputs: Optional[Sequence[str]] = None
         ) -> Tuple[Graph, Dict[str, Any]]:
    """Convenience: TensorflowLoader(...).load()."""
    return TensorflowLoader(graph_path, inputs, outputs).load()
