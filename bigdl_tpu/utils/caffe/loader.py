"""Caffe model interop: load prototxt/caffemodel into bigdl_tpu, and
persist bigdl_tpu models back out as Caffe nets.

Reference parity: utils/caffe/CaffeLoader.scala (prototxt + caffemodel →
Graph, weight copy by layer name, V1/V2 layer support),
utils/caffe/CaffePersister.scala (module graph → NetParameter),
utils/caffe/Converter.scala / LayerConverter.scala (per-type converters).

TPU-first notes
---------------
Caffe is NCHW/OIHW; this framework is NHWC/HWIO (XLA:TPU's preferred
layouts).  The loader transposes weights at conversion time and builds a
model that consumes NHWC input (pass ``input_layout="NCHW"`` to prepend a
transpose and feed original Caffe-layout tensors).  Caffe's implicit
flatten before InnerProduct orders features (C, H, W); the loader emits an
explicit NHWC→NCHW transpose + reshape so the imported fully-connected
weights apply verbatim.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.graph import Graph, Input, Node
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import T

from bigdl_tpu.utils.caffe import bigdl_caffe_pb2 as pb

__all__ = ["CaffeLoader", "CaffePersister", "load", "persist"]

# caffe axis (NCHW) → 1-based dimension over our NHWC tensors
_NCHW_TO_NHWC_DIM = {0: 1, 1: 4, 2: 2, 3: 3}


def _blob_shape(blob) -> Tuple[int, ...]:
    if blob.HasField("shape"):
        return tuple(int(d) for d in blob.shape.dim)
    legacy = (blob.num, blob.channels, blob.height, blob.width)
    return tuple(int(d) for d in legacy if d)


def _blob_array(blob) -> np.ndarray:
    arr = np.asarray(blob.data, dtype=np.float32)
    shape = _blob_shape(blob)
    return arr.reshape(shape) if shape else arr


def _fill_blob(blob, arr: np.ndarray) -> None:
    blob.shape.dim.extend(int(d) for d in arr.shape)
    blob.data.extend(float(v) for v in np.asarray(arr, np.float32).ravel())


def _sym_pad(mod) -> Tuple[int, int]:
    """Caffe's proto has only symmetric uint32 pad_h/pad_w. Tuple
    (low, high) padding (e.g. a space-to-depth stem) must fail loudly
    here, not as an opaque protobuf TypeError at field assignment."""
    if isinstance(mod.pad_h, tuple) or isinstance(mod.pad_w, tuple):
        raise ValueError(
            "Caffe has no asymmetric padding: layer %r has pad_h=%r, "
            "pad_w=%r; re-export with symmetric integer padding"
            % (mod.name, mod.pad_h, mod.pad_w))
    return mod.pad_h, mod.pad_w


def _zeros_variables(module: Module) -> Dict[str, Any]:
    import jax

    return module.init(jax.random.PRNGKey(0))


class _Layer:
    """Generation-neutral view of a LayerParameter / V1LayerParameter."""

    def __init__(self, name, type_, bottoms, tops, blobs, proto):
        self.name = name
        self.type = type_
        self.bottoms = list(bottoms)
        self.tops = list(tops)
        self.blobs = list(blobs)
        self.proto = proto  # parameter access (field names shared V1/V2)


_V1_TYPE_NAMES = {
    pb.V1LayerParameter.CONCAT: "Concat",
    pb.V1LayerParameter.CONVOLUTION: "Convolution",
    pb.V1LayerParameter.DATA: "Data",
    pb.V1LayerParameter.DROPOUT: "Dropout",
    pb.V1LayerParameter.ELTWISE: "Eltwise",
    pb.V1LayerParameter.FLATTEN: "Flatten",
    pb.V1LayerParameter.INNER_PRODUCT: "InnerProduct",
    pb.V1LayerParameter.LRN: "LRN",
    pb.V1LayerParameter.POOLING: "Pooling",
    pb.V1LayerParameter.POWER: "Power",
    pb.V1LayerParameter.RELU: "ReLU",
    pb.V1LayerParameter.SIGMOID: "Sigmoid",
    pb.V1LayerParameter.SOFTMAX: "Softmax",
    pb.V1LayerParameter.SOFTMAX_LOSS: "SoftmaxWithLoss",
    pb.V1LayerParameter.SPLIT: "Split",
    pb.V1LayerParameter.TANH: "TanH",
}

_DATA_TYPES = {"Data", "ImageData", "HDF5Data", "MemoryData", "DummyData",
               "Input"}
_SKIP_TYPES = {"Accuracy", "Silence"}


def _iter_layers(net) -> List[_Layer]:
    out = []
    for l in net.layer:
        out.append(_Layer(l.name, l.type, l.bottom, l.top, l.blobs, l))
    for l in net.layers:  # V1
        tname = _V1_TYPE_NAMES.get(l.type)
        if tname is None:
            raise NotImplementedError(
                f"V1 caffe layer type {l.type} ({l.name}) unsupported")
        out.append(_Layer(l.name, tname, l.bottom, l.top, l.blobs, l))
    return out


def _test_phase(layer: _Layer) -> bool:
    for rule in layer.proto.include:
        if rule.HasField("phase") and rule.phase != pb.TEST:
            return False
    for rule in layer.proto.exclude:
        if rule.HasField("phase") and rule.phase == pb.TEST:
            return False
    return True


class CaffeLoader:
    """Load (prototxt, caffemodel) → (Graph, variables).

    The prototxt defines the architecture; the caffemodel supplies weights
    matched **by layer name** exactly as the reference's
    CaffeLoader.copyParameters does — unmatched layers keep their fresh
    initialization (a warning is collected in ``self.unmatched``).
    """

    def __init__(self, def_path: Optional[str] = None,
                 model_path: Optional[str] = None,
                 input_layout: str = "NHWC"):
        if def_path is None and model_path is None:
            raise ValueError("need a prototxt and/or caffemodel path")
        self.def_path = def_path
        self.model_path = model_path
        self.input_layout = input_layout
        self.unmatched: List[str] = []

    # ---- parsing -------------------------------------------------------

    def _read(self) -> Tuple[Any, Dict[str, List[Any]]]:
        from google.protobuf import text_format

        weights: Dict[str, List[Any]] = {}
        binary = None
        if self.model_path:
            binary = pb.NetParameter()
            with open(self.model_path, "rb") as f:
                binary.ParseFromString(f.read())
            for l in _iter_layers(binary):
                if l.blobs:
                    weights[l.name] = l.blobs
        if self.def_path:
            net = pb.NetParameter()
            with open(self.def_path, "r") as f:
                text_format.Merge(f.read(), net)
        else:
            net = binary
        return net, weights

    # ---- layer converters ---------------------------------------------

    def _convert(self, layer: _Layer, blobs: List[Any], rank: int,
                 in_shape: Optional[Sequence[int]] = None,
                 ) -> Tuple[Module, Optional[Dict[str, Any]], int]:
        """→ (module, variables | None for stateless, output_rank).

        `in_shape` is the bottom blob's NHWC shape when known — needed to
        fresh-initialize Convolution/InnerProduct layers that have no
        weights in the caffemodel (reference: CaffeLoader.copyParameters
        matches by name; unmatched layers keep their init).
        """
        t, p = layer.type, layer.proto
        if t == "Convolution":
            return self._conv(p, blobs, in_shape) + (4,)
        if t == "Deconvolution":
            return self._deconv(p, blobs, in_shape) + (4,)
        if t == "InnerProduct":
            return self._inner_product(p, blobs, rank, in_shape) + (2,)
        if t == "Pooling":
            return self._pooling(p.pooling_param), None, 4
        if t in ("ReLU", "ReLU6"):
            slope = getattr(p, "relu_param", None)
            if slope is not None and slope.negative_slope:
                return nn.LeakyReLU(slope.negative_slope), None, rank
            return nn.ReLU(), None, rank
        if t == "TanH":
            return nn.Tanh(), None, rank
        if t == "Sigmoid":
            return nn.Sigmoid(), None, rank
        if t in ("Softmax", "SoftmaxWithLoss", "SigmoidCrossEntropyLoss",
                 "EuclideanLoss", "HingeLoss"):
            # loss layers degrade to their prediction op (label bottoms are
            # dropped by the caller); plain Euclidean/Hinge pass through
            if t in ("EuclideanLoss", "HingeLoss"):
                return nn.Identity(), None, rank
            if t == "SigmoidCrossEntropyLoss":
                return nn.Sigmoid(), None, rank
            return nn.SoftMax(), None, rank
        if t == "LRN":
            lp = p.lrn_param
            if lp.norm_region != pb.LRNParameter.ACROSS_CHANNELS:
                raise NotImplementedError("WITHIN_CHANNEL LRN")
            return (nn.SpatialCrossMapLRN(int(lp.local_size), lp.alpha,
                                          lp.beta, lp.k), None, 4)
        if t == "Dropout":
            return nn.Dropout(p.dropout_param.dropout_ratio), None, rank
        if t == "Power":
            pp = p.power_param
            return nn.Power(pp.power, pp.scale, pp.shift), None, rank
        if t == "Flatten":
            return self._flatten(), None, 2
        if t == "Reshape":
            dims = tuple(int(d) for d in p.reshape_param.shape.dim)
            if dims in ((0, -1), (-1,)):
                return self._flatten(), None, 2
            raise NotImplementedError(f"Reshape{dims} (only flatten forms)")
        if t == "Concat":
            axis = p.concat_param.axis if p.concat_param.HasField("axis") \
                else p.concat_param.concat_dim
            if axis < 0:  # caffe allows negative axes, counted from the end
                axis += rank
            dim = _NCHW_TO_NHWC_DIM[axis] if rank == 4 else axis + 1
            return nn.JoinTable(dimension=dim, n_input_dims=rank), None, rank
        if t == "Eltwise":
            ep = p.eltwise_param
            coeff = list(ep.coeff)
            if ep.operation == pb.EltwiseParameter.PROD:
                return nn.CMulTable(), None, rank
            if ep.operation == pb.EltwiseParameter.MAX:
                return nn.CMaxTable(), None, rank
            if coeff and coeff == [1.0, -1.0]:
                return nn.CSubTable(), None, rank
            if coeff and any(c != 1.0 for c in coeff):
                raise NotImplementedError(f"Eltwise SUM coeff={coeff}")
            return nn.CAddTable(), None, rank
        if t == "BatchNorm":
            return self._batch_norm(p, blobs) + (4 if rank == 4 else rank,)
        if t == "Scale":
            return self._scale(p, blobs) + (rank,)
        raise NotImplementedError(f"caffe layer type {t!r} ({layer.name})")

    @staticmethod
    def _flatten() -> Module:
        # NHWC → NCHW then flatten: keeps Caffe's (C,H,W) feature order so
        # imported InnerProduct weights apply verbatim.
        seq = nn.Sequential()
        seq.add(nn.Transpose(((2, 4), (3, 4))))  # NHWC → NCHW
        seq.add(nn.Reshape((-1,), batch_mode=True))
        return seq

    def _conv(self, p, blobs, in_shape=None):
        cp = p.convolution_param
        kh = int(cp.kernel_h or (cp.kernel_size[0] if cp.kernel_size else 1))
        kw = int(cp.kernel_w or (cp.kernel_size[-1] if cp.kernel_size else 1))
        sh = int(cp.stride_h or (cp.stride[0] if cp.stride else 1))
        sw = int(cp.stride_w or (cp.stride[-1] if cp.stride else 1))
        ph = int(cp.pad_h or (cp.pad[0] if cp.pad else 0))
        pw = int(cp.pad_w or (cp.pad[-1] if cp.pad else 0))
        dil_h = int(cp.dilation[0]) if cp.dilation else 1
        dil_w = int(cp.dilation[-1]) if cp.dilation else 1
        dil = max(dil_h, dil_w)
        n_out = int(cp.num_output)
        group = int(cp.group)
        if not blobs:
            # unmatched layer: fresh init, channels from the bottom shape
            if in_shape is None or len(in_shape) != 4:
                raise ValueError(
                    "Convolution without weights needs a known input shape "
                    "(declare input_shape in the prototxt)")
            n_in = int(in_shape[-1])
            if dil > 1:
                m = nn.SpatialDilatedConvolution(
                    n_in, n_out, kw, kh, sw, sh, pw, ph,
                    dilation_w=dil_w, dilation_h=dil_h,
                    n_group=group, with_bias=cp.bias_term)
            else:
                m = nn.SpatialConvolution(
                    n_in, n_out, kw, kh, sw, sh, pw, ph, n_group=group,
                    with_bias=cp.bias_term)
            return m, None
        w = _blob_array(blobs[0])  # (O, I/g, kH, kW)
        n_in = int(w.shape[1]) * group
        if dil > 1:
            m = nn.SpatialDilatedConvolution(
                n_in, n_out, kw, kh, sw, sh, pw, ph,
                dilation_w=dil_w, dilation_h=dil_h,
                n_group=group, with_bias=cp.bias_term)
        else:
            m = nn.SpatialConvolution(
                n_in, n_out, kw, kh, sw, sh, pw, ph, n_group=group,
                with_bias=cp.bias_term)
        params = {"weight": w.transpose(2, 3, 1, 0)}  # OIHW → HWIO
        if cp.bias_term:
            params["bias"] = _blob_array(blobs[1]).reshape(-1)
        return m, {"params": params, "state": {}}

    def _deconv(self, p, blobs, in_shape=None):
        """Caffe Deconvolution → SpatialFullConvolution (transposed
        conv). Blob layout is (I, O/g, kH, kW) — input channels FIRST,
        the transpose of Convolution's (O, I/g, kH, kW). Grouped and
        dilated variants map onto the module's n_group/dilation."""
        cp = p.convolution_param
        kh = int(cp.kernel_h or (cp.kernel_size[0] if cp.kernel_size else 1))
        kw = int(cp.kernel_w or (cp.kernel_size[-1] if cp.kernel_size else 1))
        sh = int(cp.stride_h or (cp.stride[0] if cp.stride else 1))
        sw = int(cp.stride_w or (cp.stride[-1] if cp.stride else 1))
        ph = int(cp.pad_h or (cp.pad[0] if cp.pad else 0))
        pw = int(cp.pad_w or (cp.pad[-1] if cp.pad else 0))
        group = int(cp.group) if cp.group else 1
        # dilation is a repeated field with the same per-axis [0]/[-1]
        # convention as kernel_size/stride/pad (h first, then w)
        dil_h = int(cp.dilation[0]) if cp.dilation else 1
        dil_w = int(cp.dilation[-1]) if cp.dilation else 1
        n_out = int(cp.num_output)
        if not blobs:
            if in_shape is None or len(in_shape) != 4:
                raise ValueError(
                    "Deconvolution without weights needs a known input "
                    "shape (declare input_shape in the prototxt)")
            m = nn.SpatialFullConvolution(
                int(in_shape[-1]), n_out, kw, kh, sw, sh, pw, ph,
                with_bias=cp.bias_term, n_group=group,
                dilation_w=dil_w, dilation_h=dil_h)
            return m, None
        w = _blob_array(blobs[0])  # (I, O/g, kH, kW)
        n_in = int(w.shape[0])
        m = nn.SpatialFullConvolution(
            n_in, n_out, kw, kh, sw, sh, pw, ph,
            with_bias=cp.bias_term, n_group=group,
            dilation_w=dil_w, dilation_h=dil_h)
        if group == 1:
            wn = w.transpose(2, 3, 1, 0)          # IOHW → HWOI
        else:
            # per-group (I/g, O/g, kH, kW) slices stack along the module
            # weight's O axis: (kH, kW, O_total, I/g)
            ig = n_in // group
            wn = np.concatenate(
                [w[g * ig:(g + 1) * ig].transpose(2, 3, 1, 0)
                 for g in range(group)], axis=2)
        params = {"weight": wn}
        if cp.bias_term:
            params["bias"] = _blob_array(blobs[1]).reshape(-1)
        return m, {"params": params, "state": {}}

    def _inner_product(self, p, blobs, rank, in_shape=None):
        ip = p.inner_product_param
        n_out = int(ip.num_output)
        if not blobs:
            # unmatched layer: fresh init, fan-in from the bottom shape
            if in_shape is None:
                raise ValueError(
                    "InnerProduct without weights needs a known input shape "
                    "(declare input_shape in the prototxt)")
            n_in = 1
            for d in in_shape[1:]:
                n_in *= int(d)
            lin = nn.Linear(n_in, n_out, with_bias=ip.bias_term)
            if rank == 4:
                seq = self._flatten()
                seq.add(lin)
                return seq, None
            return lin, None
        if ip.transpose:
            # blob stored input-major (K, num_output); use as-is after
            # reshaping in that orientation (caffe InnerProduct transpose)
            w = _blob_array(blobs[0]).reshape(-1, n_out).T.copy()
        else:
            w = _blob_array(blobs[0]).reshape(n_out, -1)
        n_in = w.shape[1]
        lin = nn.Linear(n_in, n_out, with_bias=ip.bias_term)
        params = {"weight": w.T}  # (O, I) → (I, O)
        if ip.bias_term:
            params["bias"] = _blob_array(blobs[1]).reshape(-1)
        lin_vars = {"params": params, "state": {}}
        if rank == 4:
            seq = self._flatten()
            seq.add(lin)
            variables = _zeros_variables(seq)
            variables["params"][seq._keys[-1]] = lin_vars["params"]
            return seq, variables
        return lin, lin_vars

    @staticmethod
    def _pooling(pp) -> Module:
        is_max = pp.pool == pb.PoolingParameter.MAX
        if pp.global_pooling:
            red = nn.Max if is_max else nn.Mean
            seq = nn.Sequential()
            seq.add(red(dimension=2, squeeze=False))  # H
            seq.add(red(dimension=3, squeeze=False))  # W
            return seq
        kh = int(pp.kernel_h or pp.kernel_size)
        kw = int(pp.kernel_w or pp.kernel_size)
        sh = int(pp.stride_h or pp.stride)
        sw = int(pp.stride_w or pp.stride)
        ph = int(pp.pad_h or pp.pad)
        pw = int(pp.pad_w or pp.pad)
        # Caffe pooling rounds output size UP by default (ceil semantics);
        # round_mode=FLOOR (upstream caffe.proto field 13) opts out
        ceil = pp.round_mode != pb.PoolingParameter.FLOOR
        cls = nn.SpatialMaxPooling if is_max else nn.SpatialAveragePooling
        m = cls(kernel_w=kw, kernel_h=kh, stride_w=sw, stride_h=sh,
                pad_w=pw, pad_h=ph, ceil_mode=ceil)
        return m

    @staticmethod
    def _batch_norm(p, blobs):
        bp = p.batch_norm_param
        m = nn.SpatialBatchNormalization(
            n_output=int(_blob_shape(blobs[0])[0]) if blobs else 0,
            eps=bp.eps, momentum=1.0 - bp.moving_average_fraction,
            affine=False)
        if not blobs:
            return m, None
        mean = _blob_array(blobs[0]).reshape(-1)
        var = _blob_array(blobs[1]).reshape(-1)
        sf = float(_blob_array(blobs[2]).ravel()[0]) if len(blobs) > 2 else 1.0
        sf = sf if sf != 0 else 1.0
        state = {"running_mean": mean / sf, "running_var": var / sf}
        return m, {"params": {}, "state": state}

    @staticmethod
    def _scale(p, blobs):
        sp = p.scale_param
        gamma = _blob_array(blobs[0]).reshape(-1) if blobs else None
        size = (gamma.shape[0],) if gamma is not None else (1,)
        if sp.bias_term:
            seq = nn.Sequential()
            seq.add(nn.CMul(size))
            seq.add(nn.CAdd(size))
            if gamma is None:
                return seq, None
            beta = _blob_array(blobs[1]).reshape(-1)
            k0, k1 = seq._keys
            return seq, {"params": {k0: {"weight": gamma},
                                    k1: {"bias": beta}},
                         "state": {k0: {}, k1: {}}}
        m = nn.CMul(size)
        if gamma is None:
            return m, None
        return m, {"params": {"weight": gamma}, "state": {}}

    # ---- graph assembly -----------------------------------------------

    def load(self) -> Tuple[Graph, Dict[str, Any]]:
        import jax

        import jax.numpy as jnp

        net, weights = self._read()
        blob_node: Dict[str, Node] = {}
        blob_rank: Dict[str, int] = {}
        blob_shape: Dict[str, Optional[Tuple[int, ...]]] = {}
        input_nodes: List[Node] = []
        node_vars: Dict[int, Dict[str, Any]] = {}

        def to_nhwc(shape):
            s = tuple(int(d) for d in shape)
            return (s[0], s[2], s[3], s[1]) if len(s) == 4 else s

        def add_input(name: str, shape: Optional[Sequence[int]]):
            node = Input()
            blob_node[name] = node
            blob_rank[name] = len(shape) if shape else 4
            blob_shape[name] = to_nhwc(shape) if shape else None
            input_nodes.append(node)

        # net-level inputs (input/input_shape/input_dim prototxt style)
        for i, name in enumerate(net.input):
            if i < len(net.input_shape):
                shape = tuple(net.input_shape[i].dim)
            elif net.input_dim:
                shape = tuple(net.input_dim[4 * i:4 * i + 4])
            else:
                shape = None
            add_input(name, shape)

        def out_shape(module, variables, in_shapes):
            """Abstract-eval the module to get its output NHWC shape."""
            if any(s is None for s in in_shapes):
                return None
            try:
                xs = [jax.ShapeDtypeStruct(tuple(s), jnp.float32)
                      for s in in_shapes]
                args = xs if len(xs) == 1 else [T(*xs)]
                res = jax.eval_shape(
                    lambda v, *a: module.apply(v, *a, training=False)[0],
                    variables, *args)
                return tuple(res.shape)
            except Exception:
                return None

        for layer in _iter_layers(net):
            if not _test_phase(layer):
                continue
            if layer.type in _SKIP_TYPES:
                continue
            if layer.type in _DATA_TYPES:
                shape = None
                ipp = getattr(layer.proto, "input_param", None)
                if ipp is not None and ipp.shape:
                    shape = tuple(ipp.shape[0].dim)
                # Data layers expose (data, label); only data becomes input
                add_input(layer.tops[0], shape)
                for extra in layer.tops[1:]:
                    blob_node[extra] = blob_node[layer.tops[0]]
                    blob_rank[extra] = 1
                    blob_shape[extra] = None
                continue
            if layer.type == "Split":
                src = blob_node[layer.bottoms[0]]
                for top in layer.tops:
                    blob_node[top] = src
                    blob_rank[top] = blob_rank[layer.bottoms[0]]
                    blob_shape[top] = blob_shape.get(layer.bottoms[0])
                continue
            bottoms = [b for b in layer.bottoms if b in blob_node]
            if layer.type.endswith("Loss") and bottoms:
                bottoms = bottoms[:1]  # drop label/weight bottoms
            if not bottoms:
                raise ValueError(f"layer {layer.name}: unknown bottoms "
                                 f"{layer.bottoms}")
            rank = blob_rank[bottoms[0]]
            blobs = list(layer.blobs) or weights.get(layer.name, [])
            if not blobs and layer.type in ("Convolution", "InnerProduct"):
                self.unmatched.append(layer.name)
            module, variables, out_rank = self._convert(
                layer, blobs, rank, blob_shape.get(bottoms[0]))
            module.set_name(layer.name)
            parents = [blob_node[b] for b in bottoms]
            node = Node.wire(module, parents)
            if variables is not None:
                node_vars[id(node)] = variables
            top = layer.tops[0] if layer.tops else layer.name
            blob_node[top] = node
            blob_rank[top] = out_rank
            shape_vars = variables if variables is not None else \
                jax.eval_shape(module.init, jax.random.PRNGKey(0))
            blob_shape[top] = out_shape(
                module, shape_vars, [blob_shape.get(b) for b in bottoms])

        # graph outputs: blobs never consumed as bottoms of real layers
        # (skipped layers like Accuracy must not hide a terminal blob)
        consumed = set()
        for layer in _iter_layers(net):
            if _test_phase(layer) and layer.type not in _DATA_TYPES \
                    and layer.type not in _SKIP_TYPES:
                consumed.update(layer.bottoms)
        outputs = [n for b, n in blob_node.items()
                   if b not in consumed and not (n in input_nodes)]
        # dedupe, keep definition order
        seen, uniq = set(), []
        for n in outputs:
            if id(n) not in seen:
                seen.add(id(n))
                uniq.append(n)
        if not uniq:
            raise ValueError("caffe net has no output blobs")

        graph = Graph(input_nodes, uniq, name=net.name or None)
        variables = graph.init(jax.random.PRNGKey(0))
        for node_id, v in node_vars.items():
            key = graph._keys.get(node_id)
            if key is not None:
                variables["params"][key] = v["params"]
                for sk, sv in v["state"].items():
                    variables["state"][key][sk] = sv

        if self.input_layout == "NCHW":
            seq = nn.Sequential()
            seq.add(nn.Transpose(((2, 3), (3, 4))))  # NCHW → NHWC
            seq.add(graph)
            k0, k1 = seq._keys
            variables = {"params": {k0: {}, k1: variables["params"]},
                         "state": {k0: {}, k1: variables["state"]}}
            return seq, variables
        return graph, variables


def load(def_path: Optional[str] = None, model_path: Optional[str] = None,
         input_layout: str = "NHWC") -> Tuple[Module, Dict[str, Any]]:
    """Convenience: CaffeLoader(...).load()
    (reference: utils/caffe/CaffeLoader.scala#CaffeLoader.loadCaffe)."""
    return CaffeLoader(def_path, model_path, input_layout).load()


# ---------------------------------------------------------------------------
# Persister
# ---------------------------------------------------------------------------


class CaffePersister:
    """Export a bigdl_tpu model as (prototxt, caffemodel)
    (reference: utils/caffe/CaffePersister.scala#CaffePersister.persist).

    Supports the converter-covered layer set.  The exported net is in
    Caffe's native NCHW layout: conv/linear weights are transposed back and
    the loader's flatten idiom (Transpose+Reshape) becomes ``Flatten``.
    """

    def __init__(self, module: Module, variables: Dict[str, Any],
                 input_shape: Sequence[int], name: str = "bigdl_tpu"):
        self.module = module
        self.variables = variables
        self.input_shape = tuple(int(d) for d in input_shape)  # NCHW
        self.name = name
        self._names_used: Dict[str, int] = {}

    def _fresh(self, base: str) -> str:
        n = self._names_used.get(base, 0)
        self._names_used[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    # ---- flatten sequence of (module, vars, inputs) -------------------

    def _linearize(self):
        """Yield (module, variables, input_ids) entries in topo order."""
        from bigdl_tpu.utils.interop import linearize

        return linearize(self.module, self.variables)

    # ---- emission ------------------------------------------------------

    def build_net(self):
        net = pb.NetParameter()
        net.name = self.name
        net.input.append("data")
        shp = net.input_shape.add()
        shp.dim.extend(self.input_shape)

        entries, _ = self._linearize()
        blob_of = {-1: "data"}
        i = 0
        while i < len(entries):
            mod, v, in_ids = entries[i]
            consumed = self._emit(net, entries, i, blob_of)
            i += consumed
        return net

    def persist(self, def_path: str, model_path: str) -> None:
        from google.protobuf import text_format

        net = self.build_net()
        with open(model_path, "wb") as f:
            f.write(net.SerializeToString())
        # prototxt: architecture only
        arch = pb.NetParameter()
        arch.CopyFrom(net)
        for l in arch.layer:
            del l.blobs[:]
        with open(def_path, "w") as f:
            f.write(text_format.MessageToString(arch))

    def _new_layer(self, net, type_: str, name: str, bottoms: List[str]
                   ) -> Tuple[Any, str]:
        l = net.layer.add()
        l.name = self._fresh(name)
        l.type = type_
        l.bottom.extend(bottoms)
        top = l.name
        l.top.append(top)
        return l, top

    def _emit(self, net, entries, i, blob_of) -> int:
        """Emit entry i (possibly merging the flatten idiom); returns how
        many entries were consumed."""
        mod, v, in_ids = entries[i]
        bots = [blob_of[j] for j in in_ids]
        p = v.get("params", {})

        def finish(layer, top, n_entries=1):
            blob_of[i + n_entries - 1] = top
            return n_entries

        # flatten idiom: exactly Transpose((2,4),(3,4)) then Reshape((-1,))
        # (the NHWC→NCHW + flatten pair _flatten() emits) — anything else
        # keeps its own layers
        if isinstance(mod, nn.Transpose) and i + 1 < len(entries) and \
                mod.permutations == [(2, 4), (3, 4)] and \
                isinstance(entries[i + 1][0], nn.Reshape) and \
                entries[i + 1][0].size == (-1,) and \
                entries[i + 1][0].batch_mode is not False:
            l, top = self._new_layer(net, "Flatten", mod.name,
                                     bots)
            blob_of[i] = top
            return finish(l, top, 2)
        if isinstance(mod, nn.SpatialFullConvolution):
            l, top = self._new_layer(net, "Deconvolution", mod.name, bots)
            cp = l.convolution_param
            cp.num_output = mod.n_output_plane
            cp.kernel_h, cp.kernel_w = mod.kernel_h, mod.kernel_w
            cp.stride_h, cp.stride_w = mod.stride_h, mod.stride_w
            cp.pad_h, cp.pad_w = _sym_pad(mod)
            cp.bias_term = mod.with_bias
            if mod.n_group > 1:
                cp.group = mod.n_group
            if mod.dilation_h != mod.dilation_w:
                # repeated field, h first then w (loader convention)
                cp.dilation.extend([mod.dilation_h, mod.dilation_w])
            elif mod.dilation_w > 1:
                cp.dilation.append(mod.dilation_w)
            wm = np.asarray(p["weight"])               # (kH,kW,O_tot,I/g)
            g = mod.n_group
            og = mod.n_output_plane // g
            # inverse of the loader mapping: O-blocks → caffe I axis
            w = np.concatenate(
                [wm[:, :, j * og:(j + 1) * og, :].transpose(3, 2, 0, 1)
                 for j in range(g)], axis=0)           # (I, O/g, kH, kW)
            _fill_blob(l.blobs.add(), w)
            if mod.with_bias:
                _fill_blob(l.blobs.add(), np.asarray(p["bias"]))
            return finish(l, top)
        if isinstance(mod, nn.SpatialConvolution):
            l, top = self._new_layer(net, "Convolution",
                                     mod.name, bots)
            cp = l.convolution_param
            cp.num_output = mod.n_output_plane
            cp.kernel_h, cp.kernel_w = mod.kernel_h, mod.kernel_w
            cp.stride_h, cp.stride_w = mod.stride_h, mod.stride_w
            cp.pad_h, cp.pad_w = _sym_pad(mod)
            cp.group = mod.n_group
            cp.bias_term = mod.with_bias
            if isinstance(mod, nn.SpatialDilatedConvolution):
                if mod.dilation_h != mod.dilation_w:
                    # repeated field, h first then w (loader convention)
                    cp.dilation.extend([mod.dilation_h, mod.dilation_w])
                else:
                    cp.dilation.append(mod.dilation_h)
            w = np.asarray(p["weight"]).transpose(3, 2, 0, 1)  # HWIO→OIHW
            _fill_blob(l.blobs.add(), w)
            if mod.with_bias:
                _fill_blob(l.blobs.add(), np.asarray(p["bias"]))
            return finish(l, top)
        if isinstance(mod, nn.Linear):
            l, top = self._new_layer(net, "InnerProduct",
                                     mod.name, bots)
            ip = l.inner_product_param
            ip.num_output = mod.output_size
            ip.bias_term = mod.with_bias
            _fill_blob(l.blobs.add(), np.asarray(p["weight"]).T)
            if mod.with_bias:
                _fill_blob(l.blobs.add(), np.asarray(p["bias"]))
            return finish(l, top)
        if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            l, top = self._new_layer(net, "Pooling", mod.name, bots)
            pp = l.pooling_param
            pp.pool = (pb.PoolingParameter.MAX
                       if isinstance(mod, nn.SpatialMaxPooling)
                       else pb.PoolingParameter.AVE)
            pp.kernel_h, pp.kernel_w = mod.kernel_h, mod.kernel_w
            pp.stride_h, pp.stride_w = mod.stride_h, mod.stride_w
            pp.pad_h, pp.pad_w = mod.pad_h, mod.pad_w
            if not mod.ceil_mode:
                pp.round_mode = pb.PoolingParameter.FLOOR
            return finish(l, top)
        simple = {nn.ReLU: "ReLU", nn.Tanh: "TanH", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax"}
        for cls, tname in simple.items():
            if type(mod) is cls:
                l, top = self._new_layer(net, tname,
                                         mod.name, bots)
                return finish(l, top)
        if isinstance(mod, nn.LeakyReLU):
            l, top = self._new_layer(net, "ReLU", mod.name, bots)
            l.relu_param.negative_slope = mod.negval
            return finish(l, top)
        if isinstance(mod, nn.SpatialCrossMapLRN):
            l, top = self._new_layer(net, "LRN", mod.name, bots)
            lp = l.lrn_param
            lp.local_size = mod.size
            lp.alpha, lp.beta, lp.k = mod.alpha, mod.beta, mod.k
            return finish(l, top)
        if isinstance(mod, nn.Dropout):
            l, top = self._new_layer(net, "Dropout", mod.name, bots)
            l.dropout_param.dropout_ratio = mod.init_p
            return finish(l, top)
        if isinstance(mod, nn.Power):
            l, top = self._new_layer(net, "Power", mod.name, bots)
            l.power_param.power = mod.power
            l.power_param.scale = mod.scale
            l.power_param.shift = mod.shift
            return finish(l, top)
        if isinstance(mod, nn.JoinTable):
            l, top = self._new_layer(net, "Concat", mod.name,
                                     bots)
            inv = {v_: k_ for k_, v_ in _NCHW_TO_NHWC_DIM.items()}
            l.concat_param.axis = inv.get(mod.dimension, mod.dimension - 1)
            return finish(l, top)
        if isinstance(mod, nn.CAddTable):
            l, top = self._new_layer(net, "Eltwise", mod.name, bots)
            l.eltwise_param.operation = pb.EltwiseParameter.SUM
            return finish(l, top)
        if isinstance(mod, nn.CMulTable):
            l, top = self._new_layer(net, "Eltwise", mod.name, bots)
            l.eltwise_param.operation = pb.EltwiseParameter.PROD
            return finish(l, top)
        if isinstance(mod, nn.CMaxTable):
            l, top = self._new_layer(net, "Eltwise", mod.name, bots)
            l.eltwise_param.operation = pb.EltwiseParameter.MAX
            return finish(l, top)
        if isinstance(mod, (nn.BatchNormalization,)):
            st = v.get("state", {})
            l, top = self._new_layer(net, "BatchNorm", mod.name, bots)
            l.batch_norm_param.eps = mod.eps
            l.batch_norm_param.use_global_stats = True
            _fill_blob(l.blobs.add(), np.asarray(st["running_mean"]))
            _fill_blob(l.blobs.add(), np.asarray(st["running_var"]))
            _fill_blob(l.blobs.add(), np.ones((1,), np.float32))
            if mod.affine:
                l2, top = self._new_layer(net, "Scale",
                                          (mod.name) + "_scale",
                                          [top])
                l2.scale_param.bias_term = True
                _fill_blob(l2.blobs.add(), np.asarray(p["weight"]))
                _fill_blob(l2.blobs.add(), np.asarray(p["bias"]))
            return finish(l, top)
        if isinstance(mod, nn.CMul):
            l, top = self._new_layer(net, "Scale", mod.name, bots)
            l.scale_param.bias_term = False
            _fill_blob(l.blobs.add(), np.asarray(p["weight"]).reshape(-1))
            return finish(l, top)
        if isinstance(mod, nn.CAdd):
            # standalone bias → Scale with unit gamma
            l, top = self._new_layer(net, "Scale", mod.name, bots)
            l.scale_param.bias_term = True
            b = np.asarray(p["bias"]).reshape(-1)
            _fill_blob(l.blobs.add(), np.ones_like(b))
            _fill_blob(l.blobs.add(), b)
            return finish(l, top)
        if isinstance(mod, nn.Identity):
            blob_of[i] = bots[0]
            return 1
        if isinstance(mod, (nn.Mean, nn.Max)) and not mod.squeeze:
            # global-pooling halves: merge pairs reducing H then W
            if i + 1 < len(entries) and type(entries[i + 1][0]) is type(mod):
                l, top = self._new_layer(net, "Pooling",
                                         mod.name, bots)
                l.pooling_param.pool = (pb.PoolingParameter.MAX
                                        if isinstance(mod, nn.Max)
                                        else pb.PoolingParameter.AVE)
                l.pooling_param.global_pooling = True
                blob_of[i] = top
                return finish(l, top, 2)
        raise NotImplementedError(
            f"caffe export: no converter for {type(mod).__name__}")


def persist(def_path: str, model_path: str, module: Module,
            variables: Dict[str, Any], input_shape: Sequence[int],
            name: str = "bigdl_tpu") -> None:
    """Convenience: CaffePersister(...).persist(...)."""
    CaffePersister(module, variables, input_shape, name).persist(
        def_path, model_path)
