"""Caffe model interop (reference: utils/caffe/ — CaffeLoader.scala,
CaffePersister.scala, Converter.scala)."""

from bigdl_tpu.utils.caffe.loader import (  # noqa: F401
    CaffeLoader,
    CaffePersister,
    load,
    persist,
)
