"""Engine — runtime/topology discovery and global configuration.

Reference parity: utils/Engine.scala (Engine.init, coreNumber, nodeNumber,
Engine.model/Engine.default thread pools) and utils/ThreadPool.scala.

TPU-first redesign: the reference's Engine discovers Spark executor/core
topology and builds OpenMP-pinned thread pools; here Engine discovers the
JAX device/process topology (PJRT) and builds the default
`jax.sharding.Mesh`. Thread pools are unnecessary — intra-op parallelism
belongs to XLA — so `core_number` reports host CPUs for the *input
pipeline* only.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np


def ensure_cpu_platform():
    """Honor `JAX_PLATFORMS=cpu` on images whose PJRT plugin (e.g. the
    axon remote-TPU tunnel) would otherwise win backend selection.

    Call BEFORE first backend use when simulating a mesh with
    `--xla_force_host_platform_device_count=N`. No-op unless the env
    var requests cpu. (tests/conftest.py and the scripts/ harnesses
    inline the same dance; this is the public entry for examples and
    user code.)"""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - jax internals moved
        pass


class Engine:
    """Process-wide runtime info. All methods are class-level, mirroring the
    reference's singleton `Engine` object."""

    _initialized = False
    _node_number: int = 1
    _core_number: int = 1

    @classmethod
    def init(cls) -> None:
        """Discover topology. Safe to call repeatedly.

        Reference parity: utils/Engine.scala#Engine.init — there it
        validates spark conf / executor cores; here it reads the PJRT
        process group (multi-host via jax.distributed) and host cores.
        """
        cls._node_number = jax.process_count()
        cls._core_number = os.cpu_count() or 1
        cls._initialized = True

    @classmethod
    def init_distributed(
        cls,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ) -> None:
        """Multi-host bring-up: one process per TPU host (the reference ran
        one Spark executor per node; utils/Engine.scala#Engine.init).

        Wraps `jax.distributed.initialize`, which wires the PJRT process
        group over DCN; collectives inside `jit` then span all hosts' chips.
        Off-cloud, scripts/launch_pod.sh exports BIGDL_COORDINATOR /
        BIGDL_NUM_PROCESSES / BIGDL_PROCESS_ID, picked up here; on Cloud
        TPU VMs everything is discovered from the metadata server and
        plain `Engine.init_distributed()` suffices.
        """
        if coordinator_address is None:
            coordinator_address = os.environ.get("BIGDL_COORDINATOR")
            if coordinator_address is not None:
                n = os.environ.get("BIGDL_NUM_PROCESSES")
                pid = os.environ.get("BIGDL_PROCESS_ID")
                if n is None or pid is None:
                    raise ValueError(
                        "BIGDL_COORDINATOR is set but "
                        f"BIGDL_NUM_PROCESSES={n!r} / "
                        f"BIGDL_PROCESS_ID={pid!r}; all three must be set "
                        "together (scripts/launch_pod.sh exports them)")
                num_processes = int(n)
                process_id = int(pid)
        if coordinator_address is not None:
            # CPU multi-process (the local[N]-style smoke/drill
            # topology, scripts/multihost_smoke.py): jax 0.4.x CPU
            # clients have NO default cross-process collectives — the
            # first sharded computation dies with "Multiprocess
            # computations aren't implemented on the CPU backend"
            # unless an implementation (gloo over TCP) is selected
            # before the backend is created. Read only at CPU-client
            # creation, so a no-op on TPU pods.
            plat = (os.environ.get("JAX_PLATFORMS")
                    or str(getattr(jax.config, "jax_platforms", None)
                           or ""))
            if (plat.startswith("cpu") and
                    not os.environ.get(
                        "JAX_CPU_COLLECTIVES_IMPLEMENTATION")):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except (AttributeError, ValueError):
                    pass  # newer jax: flag retired (gloo is the default)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        elif os.environ.get("TPU_NAME"):
            # Cloud TPU VM: topology from metadata, no flags needed.
            # IMPORTANT: nothing may touch a jax backend before this call
            # (backend init would make initialize() fail) — so no
            # process_count() precheck here.
            try:
                jax.distributed.initialize()
            except Exception as e:
                import logging

                logging.getLogger(__name__).warning(
                    "jax.distributed.initialize() failed (%s) — continuing "
                    "single-process; on a multi-host pod call "
                    "Engine.init_distributed() before any other jax use",
                    e)
        cls.init()

    @classmethod
    def node_number(cls) -> int:
        if not cls._initialized:
            cls.init()
        return cls._node_number

    @classmethod
    def core_number(cls) -> int:
        if not cls._initialized:
            cls.init()
        return cls._core_number

    @classmethod
    def device_count(cls) -> int:
        return jax.device_count()

    @classmethod
    def local_device_count(cls) -> int:
        return jax.local_device_count()

    @classmethod
    def default_mesh(cls, axis_names: Sequence[str] = ("data",)) -> jax.sharding.Mesh:
        """Build the default mesh over all devices.

        With one axis this is pure data parallelism — the direct analogue of
        the reference's partition-per-executor layout
        (parameters/AllReduceParameter.scala#AllReduceParameter.init).
        """
        devices = np.array(jax.devices())
        if len(axis_names) == 1:
            devices = devices.reshape(-1)
        else:
            raise ValueError(
                "default_mesh builds 1-D meshes; build multi-axis meshes via "
                "bigdl_tpu.parallel.mesh.make_mesh"
            )
        return jax.sharding.Mesh(devices, axis_names)
