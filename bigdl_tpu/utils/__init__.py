"""Cross-cutting utilities (reference: bigdl/utils/)."""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.shape import Shape
from bigdl_tpu.utils.logger_filter import redirect_logs
from bigdl_tpu.utils.torch_file import load_t7, save_t7
from bigdl_tpu.utils.anomaly import AnomalyError, AnomalyGuard
from bigdl_tpu.utils.faults import FaultInjected, FaultPlan
from bigdl_tpu.utils import profiler, precision

__all__ = ["Table", "T", "Engine", "Shape", "redirect_logs", "profiler",
           "precision", "load_t7", "save_t7", "AnomalyError",
           "AnomalyGuard", "FaultInjected", "FaultPlan"]
