"""Cross-cutting utilities (reference: bigdl/utils/)."""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.shape import Shape

__all__ = ["Table", "T", "Engine", "Shape"]
