"""Bounded-timeout backend probe — never hang on the axon tunnel.

The image boots a remote-TPU PJRT plugin ("axon") whose initialization
can block INDEFINITELY when the tunnel is down (observed twice:
PROFILE_r06 failed fast with "No ba16c7433 device found"; PROFILE_r07
blocked past 240 s with no error). Any entry point whose first backend
touch is an unguarded `jax.devices()` inherits that hang — bench.py
and scripts/validate_tpu.py both lost whole sessions to it.

Why a SUBPROCESS and not a watchdog thread: the hung init holds the
GIL (measured 2026-08-03 — libtpu's instance-metadata retry loop, 30
curl attempts per variable, runs inside a C call that never releases
it), so every other thread in the process freezes with it; a join
timeout cannot fire. A child process is killable from outside
regardless. The child pays one jax import (~seconds); on success the
parent's own backend init follows the same proven-healthy path. This
differs from the serving engine's step watchdog
(bigdl_tpu/serving/engine.py), which guards steady-state
dispatch+fetch — those PJRT calls DO release the GIL, so an
in-process daemon thread suffices there.

The child mirrors tests/conftest.py's CPU pinning when
JAX_PLATFORMS=cpu (pin the platform AND drop the axon factory before
first backend use), so a CPU-pinned probe never touches the tunnel.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Callable, Optional

logger = logging.getLogger("bigdl_tpu.tpu_probe")

ENV_TIMEOUT = "BIGDL_TPU_PROBE_TIMEOUT"

# intentional inline copy of utils/engine.ensure_cpu_platform: the
# child must not depend on bigdl_tpu being importable from its cwd
_CHILD_CODE = """\
import os
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge
        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        pass
import jax
print(jax.devices()[0].platform, flush=True)
"""


def default_timeout_s() -> float:
    """Seconds to wait for backend init (env BIGDL_TPU_PROBE_TIMEOUT,
    default 120 — generous for a healthy tunnel, far short of the
    580 s command budget the hang would otherwise consume)."""
    return float(os.environ.get(ENV_TIMEOUT, "120"))


def probe_platform(timeout_s: Optional[float] = None,
                   devices_fn: Optional[Callable[[], object]] = None
                   ) -> Optional[str]:
    """The backend platform string ("tpu"/"cpu"/...), or None if
    backend init did not complete within `timeout_s` (hang) or raised
    (no device reachable). `devices_fn` substitutes the backend touch
    for tests — it runs on a daemon thread in-process and must return
    the platform string directly."""
    if timeout_s is None:
        timeout_s = default_timeout_s()

    if devices_fn is not None:              # test hook: thread-based
        box: dict = {}

        def work():
            try:
                box["platform"] = devices_fn()
            except Exception as e:          # noqa: BLE001
                box["error"] = e

        th = threading.Thread(target=work, daemon=True, name="tpu-probe")
        th.start()
        th.join(timeout_s)
        if th.is_alive():
            logger.warning("backend probe still blocked after %.0f s",
                           timeout_s)
            return None
        if "error" in box:
            logger.warning("backend probe failed: %s", box["error"])
            return None
        return box["platform"]

    try:
        r = subprocess.run([sys.executable, "-c", _CHILD_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        logger.warning("backend probe subprocess still blocked after "
                       "%.0f s (axon tunnel hang?) — reporting no "
                       "backend", timeout_s)
        return None
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        logger.warning("backend probe failed (rc=%d): %s",
                       r.returncode, " | ".join(tail))
        return None
    lines = r.stdout.strip().splitlines()
    return lines[-1].strip() if lines else None
