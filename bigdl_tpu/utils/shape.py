"""Shape helper (reference parity: utils/Shape.scala)."""

from __future__ import annotations


class Shape(tuple):
    """An immutable shape tuple. ``Shape(1, 28, 28)`` or ``Shape((1, 28, 28))``."""

    def __new__(cls, *dims):
        if len(dims) == 1 and isinstance(dims[0], (tuple, list)):
            dims = tuple(dims[0])
        return super().__new__(cls, dims)

    @property
    def rank(self) -> int:
        return len(self)

    def numel(self) -> int:
        n = 1
        for d in self:
            n *= int(d)
        return n
