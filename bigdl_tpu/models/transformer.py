"""Decoder-only Transformer language model — the long-context flagship.

The reference's language-model family tops out at LSTM BPTT
(models/rnn/, SURVEY.md §2.5); this model is the TPU-first successor in
the same zoo slot, designed so every parallelism axis maps onto the mesh:

* **Stacked-parameter layers under `lax.scan`** — all L blocks share one
  pytree with a leading (L, ...) layer axis. One trace compiles once no
  matter the depth (XLA-friendly), tensor-parallel sharding is a single
  PartitionSpec per stacked leaf, and pipeline stages are contiguous
  slices of the layer axis (bigdl_tpu/parallel/pipeline.py).
* **Flash attention** on the hot path (bigdl_tpu/ops/flash_attention.py,
  Pallas on TPU), or **ring attention** over a mesh `seq` axis when
  `sp_axis` is set and apply() runs inside shard_map
  (bigdl_tpu/parallel/ring_attention.py).
* Pre-LayerNorm residual blocks, GELU MLP, learned positional embedding,
  weight-tied output head — standard GPT-2-style architecture.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


def _deq(w):
    """Duck-typed dequantize: a serving/quant.py QuantWeight knows how
    to `deq()` itself back to fp32; a plain array passes through. The
    serving paths call this at every gemm-weight use so one code path
    serves both layouts — and models/ never imports serving/."""
    return w.deq() if hasattr(w, "deq") else w


def _embed_rows(w, tokens):
    """Embedding-table row lookup for either layout. The quantized
    table is scaled PER ROW (axis=1 amax → scale (V, 1)), so a lookup
    gathers int8 rows and their scales and multiplies — O(rows·E)
    work, never the (V, E) fp32 dequant `_deq` would materialize."""
    if hasattr(w, "deq"):
        return w.q[tokens].astype(jnp.float32) * w.scale[tokens]
    return w[tokens]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_identity(x, axis):
    """Megatron's conjugate "f" operator: identity forward, psum backward.

    Placed where a replicated activation enters column-parallel compute,
    so its cotangent (which each TP shard holds only a partial of) is
    summed over the TP axis before reaching upstream replicated params —
    their grads then come out full and identical on every shard, needing
    no per-leaf corrections. The row-parallel psum in the forward is the
    conjugate "g" (psum forward; its transpose is already identity)."""
    return x


def _tpid_fwd(x, axis):
    return x, None


def _tpid_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


tp_identity.defvjp(_tpid_fwd, _tpid_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis):
    """Megatron's conjugate "g" operator: psum forward, identity backward.

    A bare lax.psum would not do: inside shard_map without replication
    tracking its AD transpose is another psum, which multiplies the
    (identical-per-shard) cotangents by the axis size. The custom VJP
    pins the backward to identity, which is the correct transpose here
    because the summed activation is replicated — each shard already
    holds the full cotangent."""
    return lax.psum(x, axis)


def _tpred_fwd(x, axis):
    return lax.psum(x, axis), None


def _tpred_bwd(axis, _, ct):
    return (ct,)


tp_reduce.defvjp(_tpred_fwd, _tpred_bwd)


def tp_shard_gather(x, axis):
    """Reconstruct a full activation from disjoint per-shard column
    slabs — the BIT-EXACT stand-in for Megatron's row-parallel psum on
    the serving path (ISSUE 10).

    A true row-split matmul psums PARTIAL sums, which changes the fp32
    accumulation order vs the unsharded gemm and breaks the serving
    plane's bitwise contract. Instead the sharded serving path keeps
    every contraction FULL-extent (the ops/kv_cache.py prefix-cache
    discipline) and uses ONE collective per layer half to concatenate
    the disjoint column shards back into the exact array the unsharded
    step holds — the zero2 discipline (all_gather of disjoint shards
    reconstructs the replicated value bit-for-bit) applied to
    activations. The downstream wo/w2 gemm then runs replicated over
    identical shapes, so its bits match the unsharded step exactly
    (pinned by tests/test_tp_serving.py and the tp_serve dryrun leg)."""
    return lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


@dataclass
class TransformerConfig:
    vocab_size: int = 256
    max_len: int = 512
    dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    dropout: float = 0.0
    causal: bool = True
    tie_embeddings: bool = True
    # rematerialize each block's activations in backward (jax.checkpoint):
    # memory O(layers + one block) instead of O(layers × acts) — the knob
    # that makes long-context training fit HBM (SURVEY.md §7 hard parts)
    remat: bool = False
    # remat policy: "full" recomputes the whole block (max memory
    # savings); "dots" saves matmul outputs and recomputes only the
    # cheap elementwise chain (jax.checkpoint_policies
    # .dots_with_no_batch_dims_saveable) — most of the memory win at a
    # fraction of the recompute FLOPs
    remat_policy: str = "full"
    # Switch/GShard-MoE FFN: moe_experts > 0 replaces EVERY block's MLP
    # with a routed mixture of moe_experts expert MLPs (parallel/moe.py
    # routing math; homogeneous across layers so the block scan stays
    # one compiled body). The auxiliary load-balancing loss is summed
    # over layers and added to .loss() scaled by moe_aux_weight.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # "top_k" (Switch/GShard, capacity dropping) or "expert_choice"
    # (dropless: experts pick tokens, perfectly balanced, aux==0;
    # NOT causally masked — see parallel/moe.py)
    moe_routing: str = "top_k"

    def __post_init__(self):
        if self.remat_policy not in ("full", "dots", "attn_saved"):
            raise ValueError(
                f"remat_policy {self.remat_policy!r}: expected 'full', "
                "'dots' or 'attn_saved'")
        if self.moe_experts and self.moe_top_k not in (1, 2):
            raise ValueError("moe_top_k must be 1 or 2")
        if self.moe_routing not in ("top_k", "expert_choice"):
            raise ValueError(
                f"moe_routing {self.moe_routing!r}: expected 'top_k' "
                "or 'expert_choice'")
        if (self.moe_experts and self.moe_routing == "expert_choice"
                and self.causal):
            # expert-choice routing selects tokens per expert over the
            # WHOLE sequence, so at train time an expert's choice for
            # position t depends on tokens after t — future-token
            # leakage under a causal LM objective (parallel/moe.py).
            # Surfaced here too, where the model is configured.
            import logging

            logging.getLogger("bigdl_tpu.models").warning(
                "moe_routing='expert_choice' with causal=True: "
                "expert-choice token selection reads the full sequence, "
                "leaking future tokens into the routing decision at "
                "train time; causal-LM eval/teacher-forcing metrics may "
                "be optimistic (see parallel/moe.py)")


class TransformerLM(Module):
    """apply(variables, tokens (B, S) int32) → log-probs (B, S, V).

    `sp_axis`: if set, attention runs as ring attention over that mesh
    axis — apply() must then be called inside shard_map with the
    sequence dimension sharded on `sp_axis` (positional embeddings are
    offset by the shard's global position automatically).

    `sp_mode`: "ring" (contiguous chunks) or "zigzag" — the causal
    load-balanced layout: device i holds global rows [i·h, (i+1)·h) ∪
    [(2n−1−i)·h, (2n−i)·h), every ring hop computes only visible
    half-blocks (half the causal flops, equal per-device work;
    parallel/ring_attention.py). Callers must feed tokens/targets
    PERMUTED into that layout — make_transformer_train_step does this
    when built with sp_mode="zigzag" (the LM loss is a mean over
    positions, so the permutation leaves it unchanged); positional
    embeddings are gathered by the zigzag position vector here.
    """

    def __init__(self, config: TransformerConfig,
                 sp_axis: Optional[str] = None,
                 tp_axis: Optional[str] = None,
                 attn_impl: Optional[str] = None,
                 sp_mode: str = "ring",
                 ep_axis: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.cfg = config
        self.sp_axis = sp_axis
        self.tp_axis = tp_axis
        self.attn_impl = attn_impl
        self.ep_axis = ep_axis
        if ep_axis is not None and not config.moe_experts:
            raise ValueError("ep_axis requires moe_experts > 0")
        if sp_mode not in ("ring", "zigzag"):
            raise ValueError(f"sp_mode must be ring|zigzag, got {sp_mode}")
        if sp_mode == "zigzag" and not config.causal:
            raise ValueError("zigzag sp_mode requires a causal model")
        self.sp_mode = sp_mode
        if config.moe_experts:
            if tp_axis is not None:
                raise NotImplementedError(
                    "MoE FFN under tensor parallelism (expert "
                    "parallelism shards experts instead; see "
                    "parallel/moe.py)")
            from bigdl_tpu.parallel.moe import MoE

            # routing/dispatch math only; its params are the per-layer
            # slices of the stacked block weights
            self._moe = MoE(config.dim, config.dim * config.mlp_ratio,
                            config.moe_experts,
                            capacity_factor=config.moe_capacity_factor,
                            top_k=config.moe_top_k,
                            routing=config.moe_routing,
                            expert_axis=ep_axis, name="moe_ffn")
        if config.dim % config.num_heads:
            raise ValueError("dim must be divisible by num_heads")
        self.head_dim = config.dim // config.num_heads

    # ------------------------------------------------------------ params
    def init_params(self, rng):
        c = self.cfg
        e, f, l = c.dim, c.dim * c.mlp_ratio, c.num_layers
        keys = iter(jax.random.split(rng, 16))

        def norm(key, shape, fan_in):
            return jax.random.normal(key, shape, jnp.float32) * (
                fan_in ** -0.5)

        blocks = {
            "ln1_g": jnp.ones((l, e)), "ln1_b": jnp.zeros((l, e)),
            "wq": norm(next(keys), (l, e, e), e),
            "wk": norm(next(keys), (l, e, e), e),
            "wv": norm(next(keys), (l, e, e), e),
            "wo": norm(next(keys), (l, e, e), e),
            "bq": jnp.zeros((l, e)), "bk": jnp.zeros((l, e)),
            "bv": jnp.zeros((l, e)), "bo": jnp.zeros((l, e)),
            "ln2_g": jnp.ones((l, e)), "ln2_b": jnp.zeros((l, e)),
        }
        if c.moe_experts:
            ex = c.moe_experts
            blocks.update({
                "router": norm(next(keys), (l, e, ex), e),
                "w1": norm(next(keys), (l, ex, e, f), e),
                "b1": jnp.zeros((l, ex, f)),
                "w2": norm(next(keys), (l, ex, f, e), f),
                "b2": jnp.zeros((l, ex, e)),
            })
        else:
            blocks.update({
                "w1": norm(next(keys), (l, e, f), e),
                "b1": jnp.zeros((l, f)),
                "w2": norm(next(keys), (l, f, e), f),
                "b2": jnp.zeros((l, e)),
            })
        p = {
            "embed": jax.random.normal(next(keys),
                                       (c.vocab_size, e)) * 0.02,
            "pos": jax.random.normal(next(keys), (c.max_len, e)) * 0.02,
            "blocks": blocks,
            "lnf_g": jnp.ones((e,)), "lnf_b": jnp.zeros((e,)),
        }
        if not c.tie_embeddings:
            p["head"] = norm(next(keys), (e, c.vocab_size), e)
        return p

    # ----------------------------------------------------------- forward
    @staticmethod
    def _ln(x, g, b, eps=1e-5):
        from bigdl_tpu.nn.normalization import layer_norm

        return layer_norm(x, g, b, eps)

    def _attention(self, q, k, v):
        from bigdl_tpu.ops.flash_attention import flash_attention
        from bigdl_tpu.parallel.ring_attention import (
            ring_attention, zigzag_ring_attention)

        if self.sp_axis is not None:
            if self.sp_mode == "zigzag":
                return zigzag_ring_attention(q, k, v, axis=self.sp_axis)
            return ring_attention(q, k, v, axis=self.sp_axis,
                                  causal=self.cfg.causal)
        return flash_attention(q, k, v, causal=self.cfg.causal,
                               impl=self.attn_impl)

    def _block(self, x, bp, dropout_rng, training, remat_mlp=False):
        """One pre-LN block. Works unchanged under tensor parallelism:
        with `tp_axis` set (inside shard_map), wq/wk/wv/w1 arrive
        column-sharded and wo/w2 row-sharded, so the local head count is
        inferred from the weight shape and the two row-parallel matmuls
        are followed by a psum — the Megatron-style split expressed as
        per-device code + XLA collectives.

        remat_mlp=True (the "attn_saved" policy) checkpoints ONLY the
        FFN half: the attention half runs outside any remat region, so
        the flash kernel's custom-vjp residuals (q,k,v,out,lse) stay
        saved and the backward does NOT re-run the forward kernel —
        under a whole-block policy nothing saves the Pallas call's
        outputs (it is not a dot_general), so the fwd kernel reruns
        once per layer in the backward (PROFILE_r05)."""
        c = self.cfg
        b, s, e = x.shape
        d = self.head_dim
        h_local = bp["wq"].shape[-1] // d     # = num_heads / tp_size

        y = self._ln(x, bp["ln1_g"], bp["ln1_b"])
        if self.tp_axis is not None:
            y = tp_identity(y, self.tp_axis)
        # NOTE: a fused qkv matmul (concat weights → one (E, 3HD) gemm →
        # split) was MEASURED SLOWER at 186M — 53.2k vs 55.3k tok/s
        # (PROFILE_r04/ANALYSIS.md): the per-scan-step weight concat and
        # qkv split cost more than the gemm fusion saves. Three gemms
        # at M=B·S are already MXU-efficient; don't re-fuse.
        q = (y @ bp["wq"] + bp["bq"]).reshape(b, s, h_local, d).transpose(0, 2, 1, 3)
        k = (y @ bp["wk"] + bp["bk"]).reshape(b, s, h_local, d).transpose(0, 2, 1, 3)
        v = (y @ bp["wv"] + bp["bv"]).reshape(b, s, h_local, d).transpose(0, 2, 1, 3)
        a = self._attention(q, k, v)
        a = a.transpose(0, 2, 1, 3).reshape(b, s, h_local * d)
        a = a @ bp["wo"]                      # row-parallel: partial sums
        if self.tp_axis is not None:
            a = tp_reduce(a, self.tp_axis)
        a = a + bp["bo"]
        if training and c.dropout > 0.0:
            keep = 1.0 - c.dropout
            k1, dropout_rng = jax.random.split(dropout_rng)
            a = jnp.where(jax.random.bernoulli(k1, keep, a.shape),
                          a, 0.0) / keep
        x = x + a

        def ffn(xres):
            y = self._ln(xres, bp["ln2_g"], bp["ln2_b"])
            aux = jnp.zeros((), jnp.float32)
            if c.moe_experts:
                moe_p = {"router": bp["router"], "w1": bp["w1"],
                         "b1": bp["b1"], "w2": bp["w2"], "b2": bp["b2"]}
                (y, aux), _ = self._moe.apply(
                    {"params": moe_p, "state": {}}, y)
            else:
                if self.tp_axis is not None:
                    y = tp_identity(y, self.tp_axis)
                y = jax.nn.gelu(y @ bp["w1"] + bp["b1"])
                y = y @ bp["w2"]              # row-parallel: partial sums
                if self.tp_axis is not None:
                    y = tp_reduce(y, self.tp_axis)
                y = y + bp["b2"]
            if training and c.dropout > 0.0:
                keep = 1.0 - c.dropout
                k2, _ = jax.random.split(dropout_rng)
                y = jnp.where(jax.random.bernoulli(k2, keep, y.shape),
                              y, 0.0) / keep
            return y, aux

        y, aux = (jax.checkpoint(ffn) if remat_mlp else ffn)(x)
        return x + y, aux

    def apply_hidden(self, variables, tokens, training=False, rng=None,
                     with_aux=False):
        """Forward up to the final LayerNorm: (B, S) int → (B, S, E).
        `with_aux=True` also returns the summed MoE load-balancing
        auxiliary (0.0 for dense configs).

        The training hot path: pair with `head(variables)` and
        `ops.losses.softmax_cross_entropy_chunked` so the (B, S, V)
        log-prob tensor is never materialized (the full `apply` keeps
        the reference-parity LogSoftMax output for eval/predict)."""
        c = self.cfg
        p = variables["params"]
        s = tokens.shape[-1]

        if self.sp_axis is not None and self.sp_mode == "zigzag":
            # zigzag layout: gather positions for half-chunks my and
            # 2n-1-my (rows arrive already permuted by the caller;
            # layout invariant lives in parallel/ring_attention.py)
            from bigdl_tpu.parallel.ring_attention import zigzag_positions

            if s % 2:
                raise ValueError(
                    f"zigzag sp_mode needs an even local sequence "
                    f"length, got {s}")
            from bigdl_tpu.parallel.shard_map_compat import axis_size
            n = axis_size(self.sp_axis)
            my = lax.axis_index(self.sp_axis)
            # positions(i) for traced i: both half starts are affine
            # in the device index, so index the stacked table
            zpos_table = jnp.stack(zigzag_positions(n, s))
            pos = p["pos"][zpos_table[my]]
        elif self.sp_axis is not None:
            pos_off = lax.axis_index(self.sp_axis) * s
            pos = lax.dynamic_slice_in_dim(p["pos"], pos_off, s, axis=0)
        else:
            pos = p["pos"][:s]
        x = p["embed"][tokens] + pos

        if training and c.dropout > 0.0 and rng is None:
            raise ValueError(f"{self.name}: dropout needs rng in training")
        base_rng = rng if rng is not None else jax.random.PRNGKey(0)

        remat_mlp = c.remat and c.remat_policy == "attn_saved"

        def body(carry, layer):
            x, aux_sum = carry
            bp, lrng = layer
            x, aux = self._block(x, bp, lrng, training,
                                 remat_mlp=remat_mlp)
            return (x, aux_sum + aux), None

        if c.remat:
            if c.remat_policy == "dots":
                body = jax.checkpoint(
                    body, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            elif c.remat_policy == "attn_saved":
                pass  # per-block FFN checkpoint only (see _block)
            else:
                body = jax.checkpoint(body)
        layer_rngs = jax.random.split(base_rng, c.num_layers)
        (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                               (p["blocks"], layer_rngs))

        h = self._ln(x, p["lnf_g"], p["lnf_b"])
        if with_aux:
            return h, aux
        return h

    def head(self, variables):
        """The (E, V) output projection (weight-tied to the embedding
        unless cfg.tie_embeddings=False). Dequantizes a quantized
        embedding/head leaf (serving/quant.py) — fp32 passes through."""
        p = variables["params"]
        return _deq(p["embed"]).T if self.cfg.tie_embeddings \
            else _deq(p["head"])

    def loss(self, variables, tokens, targets, training=False, rng=None,
             chunk: int = 256):
        """Fused mean-NLL training loss — never materializes (B, S, V)
        log-probs (ops/losses.softmax_cross_entropy_chunked)."""
        from bigdl_tpu.ops.losses import softmax_cross_entropy_chunked

        hidden, aux = self.apply_hidden(variables, tokens,
                                        training=training, rng=rng,
                                        with_aux=True)
        nll = softmax_cross_entropy_chunked(hidden, self.head(variables),
                                            targets, chunk=chunk)
        if self.cfg.moe_experts:
            return nll + self.cfg.moe_aux_weight * aux
        return nll

    def apply(self, variables, tokens, training=False, rng=None):
        x = self.apply_hidden(variables, tokens, training=training,
                              rng=rng)
        logits = x @ self.head(variables)
        return jax.nn.log_softmax(logits, axis=-1), variables["state"]

    # ------------------------------------------------- incremental decode
    # The serving plane (bigdl_tpu/serving/): a static-shape per-layer
    # KV cache + a one-row decode step, so generating T tokens costs
    # O(T·S) attention instead of the O(T·S²) of re-forwarding the whole
    # sequence per token — and both steps compile exactly once (fixed
    # max_len, position-indexed dynamic_update_slice writes; shared
    # primitives in bigdl_tpu/ops/kv_cache.py).
    #
    # Quantized serving (ISSUE 17): serving/quant.py repacks
    # serving_params' gemm weights into int8 QuantWeight leaves. The
    # paged trio dequantizes at use via the duck-typed helpers below —
    # models/ never imports serving/ (layering), it just honors any
    # leaf that knows how to `deq()` itself. fp32 leaves pass through
    # untouched, so the fp32 layout stays the bit-identity reference;
    # training paths (apply_hidden/loss) never see QuantWeight.

    def _serving_guard(self, tp_ok=False):
        """`tp_ok=True` on the PAGED trio: those paths are tp-aware
        (ISSUE 10 — head-parallel attention + column-split MLP with
        tp_shard_gather keeping every reduction full-extent) and run
        inside shard_map via bigdl_tpu/serving/tp.py. The dense cache
        path stays single-mesh."""
        if self.sp_axis is not None \
                or (self.tp_axis is not None and not tp_ok):
            raise NotImplementedError(
                "incremental decode runs single-mesh (no sp axis; tp "
                "only on the paged trio via serving/tp.py); build a "
                "plain TransformerLM for dense-cache serving")
        if self.cfg.moe_experts:
            raise NotImplementedError(
                "incremental decode for MoE FFNs (routing is per-token; "
                "not wired yet)")
        if not self.cfg.causal:
            raise ValueError("incremental decode requires causal=True")

    def init_cache(self, batch: int, max_len: Optional[int] = None,
                   dtype=jnp.float32):
        """Per-layer KV cache: a TUPLE of L dicts {'k','v'}, each
        (B, H, S, D). Per-layer (not (L, ...)-stacked) on purpose:
        decode unrolls the layer loop at trace time, and distinct
        buffers let XLA stream each layer's cache in place — a stacked
        cache pays a slice + re-stack copy of the whole thing every
        step (measured on the weights: 148 → 46 ms/token at 43M CPU,
        see serving_params). Batch-major so a serving engine splices
        one request into slot `b` with one dynamic_update_slice per
        layer. `dtype` may be bf16 (halves cache bytes; scores still
        accumulate fp32)."""
        from bigdl_tpu.ops.kv_cache import init_layer_cache

        self._serving_guard()
        c = self.cfg
        s = c.max_len if max_len is None else max_len
        if s > c.max_len:
            raise ValueError(f"cache max_len {s} > positional table "
                             f"{c.max_len}")
        return tuple(
            dict(zip(("k", "v"), init_layer_cache(
                batch, c.num_heads, s, self.head_dim, dtype)))
            for _ in range(c.num_layers))

    def serving_params(self, variables):
        """Repack the stacked (L, ...) training layout into per-layer
        tuples — the fast serving layout. The training stack is what
        makes lax.scan compile once and shard cleanly, but at decode
        time XLA cannot hoist `blocks[l]` slices of a jit argument: it
        copies every layer's weights out of the stack on every token
        (43M CPU: 148 ms/token stacked vs 46 unstacked). One-time
        O(params) repack; pass the result anywhere `variables` goes:
        `model.prefill({"params": sp}, ...)`."""
        from bigdl_tpu.parallel.param_layout import unstack_blocks

        p = variables["params"] if "params" in variables else variables
        if isinstance(p["blocks"], (tuple, list)):
            return p
        out = dict(p)
        out["blocks"] = unstack_blocks(p, self.cfg.num_layers)
        return out

    def _layer_blocks(self, p):
        """Per-layer block params from either layout (tuple passthrough;
        stacked → traced per-layer slices, correct but slow — use
        serving_params for the hot path). Routes through the
        param-layout spine's unstack walk (ISSUE 18)."""
        from bigdl_tpu.parallel.param_layout import unstack_blocks

        return unstack_blocks(p, self.cfg.num_layers)

    def _dense_ffn(self, y, bp):
        """Serving FFN. Under `tp_axis` (paged trio inside shard_map)
        w1/b1 arrive column-sharded: the gelu hidden is computed
        locally (1/tp of the up-projection flops), then
        tp_shard_gather concatenates the disjoint hidden shards so the
        w2 gemm keeps its FULL contraction extent over a replicated
        w2 — bitwise identical to the unsharded step (the down-proj
        flops are the price of bit-identity; see tp_shard_gather)."""
        y = jax.nn.gelu(y @ _deq(bp["w1"]) + bp["b1"])
        if self.tp_axis is not None:
            y = tp_shard_gather(y, self.tp_axis)
        return y @ _deq(bp["w2"]) + bp["b2"]

    def prefill(self, variables, tokens, cache, lengths=None):
        """Fill cache positions [0, S_p) from a right-padded prompt
        batch tokens (B, S_p) and return (logits (B, V) of each row's
        LAST REAL token, cache). `lengths` (B,) int32 — real prompt
        lengths (default: all S_p). Causal attention makes positions
        < length independent of the padding after them; the garbage
        keys/values the pad positions write are never read (decode
        masks beyond the row clock, then overwrites them in place)."""
        from bigdl_tpu.ops.flash_attention import flash_attention
        from bigdl_tpu.ops.kv_cache import write_prefill

        self._serving_guard()
        c = self.cfg
        p = variables["params"] if "params" in variables else variables
        bsz, s = tokens.shape
        if lengths is None:
            lengths = jnp.full((bsz,), s, jnp.int32)
        d = self.head_dim
        x = p["embed"][tokens] + p["pos"][:s]

        new_cache = []
        for bp, lc in zip(self._layer_blocks(p), cache):
            y = self._ln(x, bp["ln1_g"], bp["ln1_b"])
            q = (y @ bp["wq"] + bp["bq"]).reshape(
                bsz, s, c.num_heads, d).transpose(0, 2, 1, 3)
            k = (y @ bp["wk"] + bp["bk"]).reshape(
                bsz, s, c.num_heads, d).transpose(0, 2, 1, 3)
            v = (y @ bp["wv"] + bp["bv"]).reshape(
                bsz, s, c.num_heads, d).transpose(0, 2, 1, 3)
            new_cache.append(dict(zip(
                ("k", "v"), write_prefill(lc["k"], lc["v"], k, v))))
            a = flash_attention(q, k, v, causal=True, impl=self.attn_impl)
            a = a.transpose(0, 2, 1, 3).reshape(bsz, s, c.num_heads * d)
            x = x + a @ bp["wo"] + bp["bo"]
            x = x + self._dense_ffn(
                self._ln(x, bp["ln2_g"], bp["ln2_b"]), bp)

        h = self._ln(x, p["lnf_g"], p["lnf_b"])
        last = jnp.take_along_axis(
            h, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return last @ self.head({"params": p}), tuple(new_cache)

    # ------------------------------------------------- paged KV (ISSUE 8)
    # The serving engine's cache spine: per-layer block POOLS plus a
    # per-slot block TABLE instead of contiguous per-slot buffers
    # (ops/kv_cache.py paged primitives; allocator in
    # serving/kv_pool.py, radix prefix reuse in serving/prefix_cache
    # .py). Same compile contract as the dense path — one suffix
    # prefill executable per bucket + one decode executable — and the
    # full-table attention extent makes every KV row's value bitwise
    # independent of which bucket (or which request) computed it.

    def init_block_pool(self, num_blocks: int, block_size: int,
                        dtype=jnp.float32):
        """Per-layer paged KV pools: a TUPLE of L dicts {'k','v'},
        each (num_blocks, H, block_size, D). Per-layer (not stacked)
        for the same reason as init_cache; block 0 is the reserved
        scratch block (ops/kv_cache.py)."""
        from bigdl_tpu.ops.kv_cache import init_block_pool

        self._serving_guard(tp_ok=True)
        c = self.cfg
        return tuple(
            dict(zip(("k", "v"), init_block_pool(
                num_blocks, c.num_heads, block_size, self.head_dim,
                dtype)))
            for _ in range(c.num_layers))

    def prefill_paged(self, variables, tokens, pools, table, block_ids,
                      start):
        """Prefill ONE request's SUFFIX into the paged pools: tokens
        (1, bucket) right-padded suffix tokens at global positions
        [start, start+bucket); `table` (1, max_blocks) the slot's full
        block table (reused prefix blocks + the fresh `block_ids`
        (nb,) this call writes); `start` a traced int32 scalar — the
        block-aligned cached-prefix length (0 = cold prefill, the same
        executable). Returns the updated pools; the engine takes its
        first token by re-decoding the last prompt token, so no logits
        head runs here.

        Suffix queries attend through the gathered table — prefix keys
        included — over the FULL table extent with mask j <= start+i,
        which is what makes the written KV bitwise identical whether a
        position is computed cold (start=0, one big bucket) or warm
        (nonzero start, a small suffix bucket): all reductions keep
        the same shape (ops/kv_cache.py module docstring).

        Tensor parallelism (ISSUE 10, inside shard_map via
        serving/tp.py): wq/wk/wv arrive column-sharded by HEAD and the
        pools head-sharded, so each shard prefills its own heads'
        blocks — the attention reductions are per-head (a pure batch
        split, bitwise invariant) and the block table is a replicated
        host-side operand, identical on every shard. tp_shard_gather
        then rebuilds the full attention output so the wo gemm keeps
        its full contraction extent (bitwise == unsharded)."""
        from bigdl_tpu.ops.kv_cache import (block_attention,
                                            gather_block_cache,
                                            write_prompt_blocks)

        self._serving_guard(tp_ok=True)
        p = variables["params"] if "params" in variables else variables
        bsz, s = tokens.shape
        if bsz != 1:
            raise ValueError("prefill_paged fills one request (batch "
                             f"1), got batch {bsz}")
        d = self.head_dim
        start = jnp.asarray(start, jnp.int32)
        x = _embed_rows(p["embed"], tokens) \
            + lax.dynamic_slice_in_dim(p["pos"], start, s, axis=0)

        new_pools = []
        visible = valid = None
        for bp, pl in zip(self._layer_blocks(p), pools):
            h = bp["wq"].shape[-1] // d     # local heads (= H/tp)
            y = self._ln(x, bp["ln1_g"], bp["ln1_b"])
            q = (y @ _deq(bp["wq"]) + bp["bq"]).reshape(
                bsz, s, h, d).transpose(0, 2, 1, 3)
            k = (y @ _deq(bp["wk"]) + bp["bk"]).reshape(
                bsz, s, h, d).transpose(0, 2, 1, 3)
            v = (y @ _deq(bp["wv"]) + bp["bv"]).reshape(
                bsz, s, h, d).transpose(0, 2, 1, 3)
            kp, vp = write_prompt_blocks(pl["k"], pl["v"], k, v,
                                         block_ids)
            new_pools.append({"k": kp, "v": vp})
            kc = gather_block_cache(kp, table)      # (1, H, S_tab, D)
            vc = gather_block_cache(vp, table)
            if visible is None:                     # same every layer
                jpos = jnp.arange(kc.shape[-2])
                ipos = start + jnp.arange(s)
                visible = (jpos[None, None, :]
                           <= ipos[None, :, None])  # (1, s, S_tab)
                valid = (jpos[None, :] < start + s)  # (1, S_tab)
            a = block_attention(q, kc, vc, visible, valid)
            a = a.transpose(0, 2, 1, 3).reshape(bsz, s, h * d)
            if self.tp_axis is not None:
                a = tp_shard_gather(a, self.tp_axis)
            x = x + a @ _deq(bp["wo"]) + bp["bo"]
            x = x + self._dense_ffn(
                self._ln(x, bp["ln2_g"], bp["ln2_b"]), bp)
        return tuple(new_pools)

    def decode_step_paged(self, variables, tokens, pos, pools, table,
                          attn_impl: str = "xla"):
        """One incremental step over the paged pools: tokens/pos (B,)
        as decode_step, `table` (B, max_blocks) int32 block tables.
        Writes each row's k/v at (table[pos // bs], pos % bs) — always
        an exclusive block (copy-on-write: the engine never points a
        row's write position at a shared block) — then attends through
        the gathered table. Same per-ROW isolation contract as
        decode_step: a non-finite row contaminates only its own logits
        and its own exclusive blocks.

        Tensor parallelism (ISSUE 10): same construction as
        prefill_paged — head-sharded pools and head-column-sharded qkv
        make the attention a pure per-head batch split over a
        REPLICATED host-side block table; tp_shard_gather rebuilds the
        full attention output (and _dense_ffn the full mlp hidden) so
        every downstream contraction keeps its unsharded extent and
        the logits come out replicated AND bitwise identical to
        tp=1.

        Speculative verify (ISSUE 15): this step doubles as the
        target's k+1-position scoring entry — serving/speculative.py
        batches a slot's chain positions pos..pos+k as k+1 ROWS of
        one call, every row pointing at the SAME slot's table. Each
        layer writes all rows' k/v (write_decode_blocks, distinct
        (block, offset) destinations) before any row's attention
        gathers the pool, so row j SEES rows < j's writes — and
        because every op here is per-row with the full-table
        attention extent, a verify row's logits are BITWISE the
        logits the sequential one-row call computes for that position
        (per-row bits are batch-extent-independent on this backend;
        verified at the tiny and 43M shapes). Scoring positions as
        Q=1 rows rather than as a Q=k+1 prefill is deliberate: Q=1
        and Q>=2 gemms lower to different kernels (ops/kv_cache.py),
        so a prefill-shaped verify would score in the wrong regime
        and the spec-vs-target-only token identity would be luck, not
        construction.

        `attn_impl` (ISSUE 17, STATIC under jit — the engine threads
        it as a static argnum): "xla" = the gather-then-attend oracle
        (ops/kv_cache.paged_attention, the default and the bitwise
        reference everywhere off-TPU); "pallas"/"interpret" = the
        one-launch table-routed kernel (ops/paged_decode.py), fp32
        interpret output bitwise == "xla". Because this step is also
        the speculative verify entry, one knob covers plain decode,
        draft decode, and the k+1-row verify with the same
        executable-per-impl."""
        from bigdl_tpu.ops.kv_cache import write_decode_blocks
        from bigdl_tpu.ops.paged_decode import paged_decode_attention

        self._serving_guard(tp_ok=True)
        p = variables["params"] if "params" in variables else variables
        bsz = tokens.shape[0]
        d = self.head_dim
        bs = pools[0]["k"].shape[2]
        rows = jnp.arange(bsz)
        block_ids = table[rows, pos // bs]          # (B,)
        offsets = pos % bs
        x = _embed_rows(p["embed"], tokens) + p["pos"][pos]  # (B, E)

        new_pools = []
        for bp, pl in zip(self._layer_blocks(p), pools):
            h = bp["wq"].shape[-1] // d     # local heads (= H/tp)
            y = self._ln(x, bp["ln1_g"], bp["ln1_b"])
            q = (y @ _deq(bp["wq"]) + bp["bq"]).reshape(
                bsz, 1, h, d).transpose(0, 2, 1, 3)
            k = (y @ _deq(bp["wk"]) + bp["bk"]).reshape(
                bsz, 1, h, d).transpose(0, 2, 1, 3)
            v = (y @ _deq(bp["wv"]) + bp["bv"]).reshape(
                bsz, 1, h, d).transpose(0, 2, 1, 3)
            kp, vp = write_decode_blocks(pl["k"], pl["v"], k, v,
                                         block_ids, offsets)
            new_pools.append({"k": kp, "v": vp})
            a = paged_decode_attention(q, kp, vp, table, pos,
                                       impl=attn_impl)  # (B, h, 1, D)
            a = a.transpose(0, 2, 1, 3).reshape(bsz, h * d)
            if self.tp_axis is not None:
                a = tp_shard_gather(a, self.tp_axis)
            x = x + a @ _deq(bp["wo"]) + bp["bo"]
            x = x + self._dense_ffn(
                self._ln(x, bp["ln2_g"], bp["ln2_b"]), bp)

        h = self._ln(x, p["lnf_g"], p["lnf_b"])
        return h @ self.head({"params": p}), tuple(new_pools)

    def decode_step(self, variables, tokens, pos, cache):
        """One incremental step: tokens (B,) int32 — the current token
        per row — written at per-row clock `pos` (B,) int32, attended
        against the cache. Returns (logits (B, V) predicting the NEXT
        token, cache). O(S) per token; compiles once for a given cache
        shape (the layer loop unrolls at trace time).

        Reliability contract (serving/engine.py poison isolation):
        every op in this step is per-ROW — embedding lookup, LN,
        per-row cache write, masked cached_attention, gemv — so a
        non-finite row contaminates only its own logits and cache
        rows. The serving engine reduces the returned logits to a (B,)
        finite flag inside its jitted wrapper (utils/anomaly
        .rows_finite) and evicts only the poisoned request; masked
        stale rows in a recycled slot cannot leak because
        cached_attention nan-scrubs invisible value rows."""
        from bigdl_tpu.ops.kv_cache import cached_attention, update_cache

        self._serving_guard()
        c = self.cfg
        p = variables["params"] if "params" in variables else variables
        bsz = tokens.shape[0]
        d = self.head_dim
        x = p["embed"][tokens] + p["pos"][pos]    # (B, E)

        new_cache = []
        for bp, lc in zip(self._layer_blocks(p), cache):
            y = self._ln(x, bp["ln1_g"], bp["ln1_b"])
            q = (y @ bp["wq"] + bp["bq"]).reshape(
                bsz, 1, c.num_heads, d).transpose(0, 2, 1, 3)
            k = (y @ bp["wk"] + bp["bk"]).reshape(
                bsz, 1, c.num_heads, d).transpose(0, 2, 1, 3)
            v = (y @ bp["wv"] + bp["bv"]).reshape(
                bsz, 1, c.num_heads, d).transpose(0, 2, 1, 3)
            kc, vc = update_cache(lc["k"], lc["v"], k, v, pos)
            new_cache.append({"k": kc, "v": vc})
            a = cached_attention(q, kc, vc, pos)  # (B, H, 1, D)
            a = a.transpose(0, 2, 1, 3).reshape(bsz, c.num_heads * d)
            x = x + a @ bp["wo"] + bp["bo"]
            x = x + self._dense_ffn(
                self._ln(x, bp["ln2_g"], bp["ln2_b"]), bp)

        h = self._ln(x, p["lnf_g"], p["lnf_b"])
        return h @ self.head({"params": p}), tuple(new_cache)


def build_lm(vocab_size: int = 256, dim: int = 128, num_heads: int = 4,
             num_layers: int = 2, max_len: int = 512,
             **kw) -> TransformerLM:
    return TransformerLM(TransformerConfig(
        vocab_size=vocab_size, dim=dim, num_heads=num_heads,
        num_layers=num_layers, max_len=max_len), **kw)


def lm_train_matmul_flops_per_token(cfg: TransformerConfig,
                                    ) -> float:
    """Training (fwd+bwd = 3x fwd) matmul FLOPs per token — the
    analytic model-flops count behind every LM MFU number (bench.py,
    scripts/profile_lm.py). Remat recompute is NOT credited (standard
    MFU convention).

    Per layer fwd: qkv+o projections 4*2*e^2, mlp 2*2*e*4e -> 24*e^2;
    attention scores+values 2*2*S*e (halved when causal);
    head 2*e*V. Embedding gather is not a matmul (excluded).
    """
    e, L, S, V = cfg.dim, cfg.num_layers, cfg.max_len, cfg.vocab_size
    per_layer = 24 * e * e + (2 * 2 * S * e) * (0.5 if cfg.causal else 1)
    head = 2 * e * V
    return 3 * (L * per_layer + head)
