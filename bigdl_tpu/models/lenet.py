"""LeNet-5.

Reference parity: models/lenet/LeNet5.scala#LeNet5.apply —
conv(1→6,5x5) → tanh → maxpool2 → conv(6→12,5x5) → tanh → maxpool2 →
flatten → linear(12*4*4→100) → tanh → linear(100→classNum) → logsoftmax.
Input here is NHWC (28, 28, 1).
"""

from __future__ import annotations

from bigdl_tpu import nn


def build(class_num: int = 10) -> nn.Sequential:
    return nn.Sequential(
        nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([12 * 4 * 4]),
        nn.Linear(12 * 4 * 4, 100).set_name("fc_1"),
        nn.Tanh(),
        nn.Linear(100, class_num).set_name("score"),
        nn.LogSoftMax(),
    )


LeNet5 = build


def graph(class_num: int = 10) -> "nn.Graph":
    """Same network as an explicit Graph (reference: LeNet5.graph)."""
    x = nn.Input()
    h = nn.SpatialConvolution(1, 6, 5, 5)(x)
    h = nn.Tanh()(h)
    h = nn.SpatialMaxPooling(2, 2, 2, 2)(h)
    h = nn.SpatialConvolution(6, 12, 5, 5)(h)
    h = nn.Tanh()(h)
    h = nn.SpatialMaxPooling(2, 2, 2, 2)(h)
    h = nn.Reshape([12 * 4 * 4])(h)
    h = nn.Linear(12 * 4 * 4, 100)(h)
    h = nn.Tanh()(h)
    h = nn.Linear(100, class_num)(h)
    y = nn.LogSoftMax()(h)
    return nn.Graph(x, y)
