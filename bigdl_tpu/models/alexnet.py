"""AlexNet (OWT single-tower variant).

Reference parity: models/alexnet/AlexNet.scala (AlexNet_OWT: the
one-weird-trick single-GPU layout the reference ships).
"""

from __future__ import annotations

from bigdl_tpu import nn


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    m = nn.Sequential(
        nn.SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2).set_name("conv1"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"),
        nn.SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2).set_name("conv2"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"),
        nn.SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"),
        nn.ReLU(),
        nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1).set_name("conv4"),
        nn.ReLU(),
        nn.SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1).set_name("conv5"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"),
        nn.Reshape([256 * 6 * 6]),
        nn.Linear(256 * 6 * 6, 4096).set_name("fc6"),
        nn.ReLU(),
    )
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096).set_name("fc7"))
    m.add(nn.ReLU())
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num).set_name("fc8"))
    m.add(nn.LogSoftMax())
    return m


AlexNet = build
