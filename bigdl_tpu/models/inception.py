"""Inception v1 (GoogLeNet).

Reference parity: models/inception/Inception_v1.scala —
`Inception_Layer_v1` (4-branch module: 1x1 / 1x1→3x3 / 1x1→5x5 /
pool→1x1, concat over channels) and the full `Inception_v1_NoAuxClassifier`
graph; config tables match the reference's channel numbers.

TPU note: the 4 branches are independent convs XLA schedules in parallel
on the MXU; `nn.Concat` along the channel axis is the NHWC-native concat.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import Xavier


def _conv(n_in, n_out, k, stride=1, pad=0, name=""):
    return nn.Sequential(
        nn.SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad,
                              w_init=Xavier()).set_name(name + f"conv{k}x{k}"),
        nn.ReLU(),
    )


def inception_layer_v1(n_in, config, prefix=""):
    """(reference: Inception_v1.scala#Inception_Layer_v1)
    config = ((c1,), (c3r, c3), (c5r, c5), (pp,))"""
    (c1,), (c3r, c3), (c5r, c5), (pp,) = config
    return nn.Concat(
        4,  # channel axis in NHWC (1-based dim 4)
        _conv(n_in, c1, 1, name=prefix + "1x1/"),
        nn.Sequential(
            _conv(n_in, c3r, 1, name=prefix + "3x3r/"),
            _conv(c3r, c3, 3, pad=1, name=prefix + "3x3/")),
        nn.Sequential(
            _conv(n_in, c5r, 1, name=prefix + "5x5r/"),
            _conv(c5r, c5, 5, pad=2, name=prefix + "5x5/")),
        nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil(),
            _conv(n_in, pp, 1, name=prefix + "pool/")),
    )


def inception_layer_v1_fused(n_in, config, prefix=""):
    """Branch-fused variant of `inception_layer_v1` (VERDICT r4 item 2):
    the three REDUCE 1x1 convs (1x1 branch, 3x3 reduce, 5x5 reduce) all
    read the same input, so they merge into ONE conv with c1+c3r+c5r
    output channels — one large M=B·H·W gemm instead of three small
    ones whose padded-to-128 output lanes waste the MXU (e.g. layer 3a:
    64/96/16 lanes → three pads vs one 176-wide gemm). ReLU commutes
    with the channel slice, so slicing after the merged conv+ReLU is
    numerically identical to the per-branch form. The pool-projection
    1x1 reads the pooled input and stays separate."""
    (c1,), (c3r, c3), (c5r, c5), (pp,) = config
    x = nn.Input()
    merged = nn.Sequential(
        nn.SpatialConvolution(n_in, c1 + c3r + c5r, 1, 1, 1, 1, 0, 0,
                              w_init=Xavier()
                              ).set_name(prefix + "reduce_merged/conv1x1"),
        nn.ReLU(),
    )(x)
    b1 = nn.Narrow(4, 1, c1)(merged)
    b3 = _conv(c3r, c3, 3, pad=1, name=prefix + "3x3/")(
        nn.Narrow(4, 1 + c1, c3r)(merged))
    b5 = _conv(c5r, c5, 5, pad=2, name=prefix + "5x5/")(
        nn.Narrow(4, 1 + c1 + c3r, c5r)(merged))
    bp = nn.Sequential(
        nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil(),
        _conv(n_in, pp, 1, name=prefix + "pool/"),
    )(x)
    out = nn.JoinTable(4)(b1, b3, b5, bp)
    return nn.Graph(x, out)


def build(class_num: int = 1000, has_dropout: bool = True,
          fused_branches: bool = False) -> nn.Sequential:
    """(reference: Inception_v1.scala#Inception_v1_NoAuxClassifier)

    fused_branches=True swaps each inception layer for the
    reduce-merged variant (identical math, fewer/larger gemms —
    see inception_layer_v1_fused)."""
    layer = inception_layer_v1_fused if fused_branches \
        else inception_layer_v1
    m = nn.Sequential(
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                              w_init=Xavier()).set_name("conv1/7x7_s2"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        _conv(64, 64, 1, name="conv2/3x3_reduce/"),
        _conv(64, 192, 3, pad=1, name="conv2/3x3/"),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        layer(192, ((64,), (96, 128), (16, 32), (32,)), "3a/"),
        layer(256, ((128,), (128, 192), (32, 96), (64,)), "3b/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        layer(480, ((192,), (96, 208), (16, 48), (64,)), "4a/"),
        layer(512, ((160,), (112, 224), (24, 64), (64,)), "4b/"),
        layer(512, ((128,), (128, 256), (24, 64), (64,)), "4c/"),
        layer(512, ((112,), (144, 288), (32, 64), (64,)), "4d/"),
        layer(528, ((256,), (160, 320), (32, 128), (128,)), "4e/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        layer(832, ((256,), (160, 320), (32, 128), (128,)), "5a/"),
        layer(832, ((384,), (192, 384), (48, 128), (128,)), "5b/"),
        nn.SpatialAveragePooling(7, 7, 1, 1),
    )
    if has_dropout:
        m.add(nn.Dropout(0.4))
    m.add(nn.Reshape([1024]))
    m.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


Inception_v1 = build


# --------------------------------------------------------------- Inception v2

def _conv_bn(n_in, n_out, k, stride=1, pad=0, name=""):
    """conv + SpatialBatchNormalization + ReLU — the v2 building block
    (reference: Inception_v2.scala — every conv is followed by
    SpatialBatchNormalization(nOut, 1e-3) + ReLU(true))."""
    return nn.Sequential(
        nn.SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad,
                              w_init=Xavier()).set_name(name + f"conv{k}x{k}"),
        nn.SpatialBatchNormalization(n_out, eps=1e-3).set_name(name + "bn"),
        nn.ReLU(),
    )


def inception_layer_v2(n_in, config, prefix=""):
    """(reference: Inception_v2.scala#Inception_Layer_v2)

    config = ((c1,), (c3r, c3), (d3r, d3), (pool_kind, pp)) with the v2
    branch set: 1x1 / 1x1->3x3 / 1x1->3x3->3x3 (double-3x3 replaces v1's
    5x5) / pool->proj. ``c1 == 0`` selects the stride-2 ("pass-through")
    variant: the 1x1 branch disappears, both conv branches stride 2, the
    pool branch max-pools stride 2 with no projection.
    """
    (c1,), (c3r, c3), (d3r, d3), (pool_kind, pp) = config
    stride = 2 if c1 == 0 else 1
    branches = []
    if c1 > 0:
        branches.append(_conv_bn(n_in, c1, 1, name=prefix + "1x1/"))
    branches.append(nn.Sequential(
        _conv_bn(n_in, c3r, 1, name=prefix + "3x3r/"),
        _conv_bn(c3r, c3, 3, stride=stride, pad=1, name=prefix + "3x3/")))
    branches.append(nn.Sequential(
        _conv_bn(n_in, d3r, 1, name=prefix + "d3x3r/"),
        _conv_bn(d3r, d3, 3, pad=1, name=prefix + "d3x3a/"),
        _conv_bn(d3, d3, 3, stride=stride, pad=1, name=prefix + "d3x3b/")))
    if pool_kind == "max":
        pool = nn.SpatialMaxPooling(3, 3, stride, stride,
                                    *(() if stride == 2 else (1, 1))).ceil()
    else:
        pool = nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil()
    if pp > 0:
        branches.append(nn.Sequential(
            pool, _conv_bn(n_in, pp, 1, name=prefix + "pool/")))
    else:
        branches.append(pool)
    return nn.Concat(4, *branches)


def build_v2(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """BN-Inception (reference: models/inception/Inception_v2.scala —
    channel configs per inception_3a..5b of that graph)."""
    m = nn.Sequential(
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                              w_init=Xavier()).set_name("conv1/7x7_s2"),
        nn.SpatialBatchNormalization(64, eps=1e-3),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        _conv_bn(64, 64, 1, name="conv2/3x3_reduce/"),
        _conv_bn(64, 192, 3, pad=1, name="conv2/3x3/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_layer_v2(192, ((64,), (64, 64), (64, 96), ("avg", 32)), "3a/"),
        inception_layer_v2(256, ((64,), (64, 96), (64, 96), ("avg", 64)), "3b/"),
        inception_layer_v2(320, ((0,), (128, 160), (64, 96), ("max", 0)), "3c/"),
        inception_layer_v2(576, ((224,), (64, 96), (96, 128), ("avg", 128)), "4a/"),
        inception_layer_v2(576, ((192,), (96, 128), (96, 128), ("avg", 128)), "4b/"),
        inception_layer_v2(576, ((160,), (128, 160), (128, 160), ("avg", 96)), "4c/"),
        inception_layer_v2(576, ((96,), (128, 192), (160, 192), ("avg", 96)), "4d/"),
        inception_layer_v2(576, ((0,), (128, 192), (192, 256), ("max", 0)), "4e/"),
        inception_layer_v2(1024, ((352,), (192, 320), (160, 224), ("avg", 128)), "5a/"),
        inception_layer_v2(1024, ((352,), (192, 320), (192, 224), ("max", 128)), "5b/"),
        nn.SpatialAveragePooling(7, 7, 1, 1),
    )
    if has_dropout:
        m.add(nn.Dropout(0.4))
    m.add(nn.Reshape([1024]))
    m.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


Inception_v2 = build_v2
