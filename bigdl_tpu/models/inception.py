"""Inception v1 (GoogLeNet).

Reference parity: models/inception/Inception_v1.scala —
`Inception_Layer_v1` (4-branch module: 1x1 / 1x1→3x3 / 1x1→5x5 /
pool→1x1, concat over channels) and the full `Inception_v1_NoAuxClassifier`
graph; config tables match the reference's channel numbers.

TPU note: the 4 branches are independent convs XLA schedules in parallel
on the MXU; `nn.Concat` along the channel axis is the NHWC-native concat.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import Xavier


def _conv(n_in, n_out, k, stride=1, pad=0, name=""):
    return nn.Sequential(
        nn.SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad,
                              w_init=Xavier()).set_name(name + f"conv{k}x{k}"),
        nn.ReLU(),
    )


def inception_layer_v1(n_in, config, prefix=""):
    """(reference: Inception_v1.scala#Inception_Layer_v1)
    config = ((c1,), (c3r, c3), (c5r, c5), (pp,))"""
    (c1,), (c3r, c3), (c5r, c5), (pp,) = config
    return nn.Concat(
        4,  # channel axis in NHWC (1-based dim 4)
        _conv(n_in, c1, 1, name=prefix + "1x1/"),
        nn.Sequential(
            _conv(n_in, c3r, 1, name=prefix + "3x3r/"),
            _conv(c3r, c3, 3, pad=1, name=prefix + "3x3/")),
        nn.Sequential(
            _conv(n_in, c5r, 1, name=prefix + "5x5r/"),
            _conv(c5r, c5, 5, pad=2, name=prefix + "5x5/")),
        nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil(),
            _conv(n_in, pp, 1, name=prefix + "pool/")),
    )


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """(reference: Inception_v1.scala#Inception_v1_NoAuxClassifier)"""
    m = nn.Sequential(
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                              w_init=Xavier()).set_name("conv1/7x7_s2"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        _conv(64, 64, 1, name="conv2/3x3_reduce/"),
        _conv(64, 192, 3, pad=1, name="conv2/3x3/"),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_layer_v1(192, ((64,), (96, 128), (16, 32), (32,)), "3a/"),
        inception_layer_v1(256, ((128,), (128, 192), (32, 96), (64,)), "3b/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_layer_v1(480, ((192,), (96, 208), (16, 48), (64,)), "4a/"),
        inception_layer_v1(512, ((160,), (112, 224), (24, 64), (64,)), "4b/"),
        inception_layer_v1(512, ((128,), (128, 256), (24, 64), (64,)), "4c/"),
        inception_layer_v1(512, ((112,), (144, 288), (32, 64), (64,)), "4d/"),
        inception_layer_v1(528, ((256,), (160, 320), (32, 128), (128,)), "4e/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_layer_v1(832, ((256,), (160, 320), (32, 128), (128,)), "5a/"),
        inception_layer_v1(832, ((384,), (192, 384), (48, 128), (128,)), "5b/"),
        nn.SpatialAveragePooling(7, 7, 1, 1),
    )
    if has_dropout:
        m.add(nn.Dropout(0.4))
    m.add(nn.Reshape([1024]))
    m.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


Inception_v1 = build
