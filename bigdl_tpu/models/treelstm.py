"""TreeLSTM for sentiment over constituency trees.

Reference parity: the reference's BinaryTreeLSTM (example/treeLSTM /
nn/BinaryTreeLSTM.scala): child-sum/binary tree LSTM over SST-style
binary parse trees, per-node sentiment classification, evaluated with
TreeNNAccuracy on the root.

TPU-first redesign (SURVEY.md §7 "hard parts"): the reference recurses
per-sample over dynamic tree topologies — impossible under jit. Trees are
LINEARIZED to fixed-length post-order schedules:

    for each node slot t in post-order:
        h_t = leaf_cell(x_t)                     if leaf
        h_t = compose(h[left_t], h[right_t])     if internal
        (masked select; padded slots are no-ops)

and the whole schedule runs as ONE `lax.scan` over node slots with
`dynamic_index` gathers into the node-state buffer — static shapes,
batched across trees, MXU-friendly fused gate matmuls.

WAVEFRONT schedule (the default when the encoding carries node levels
and `max_levels` is set): the slot scan above is `max_nodes` SEQUENTIAL
steps of tiny (B, ·) gemms — the per-step dispatch/latency floor, not
the MXU, binds (PROFILE_r04 roofline, same floor as the BiLSTM scan).
But composition only depends on tree DEPTH: all leaves are ready at
once, and every node whose children are done can compose together. So:
leaves run as ONE hoisted (B·T, d) gemm, then a `lax.scan` over depth
LEVELS (leaf=0, internal = 1+max(child levels)) composes every level-ℓ
node of every tree in one batched (B·T, 2h) gemm + masked select —
O(tree depth) sequential steps instead of O(max_nodes), each a full-
width MXU matmul. Per-level flops rise (all slots compose, most are
masked), but the recurrent path is latency-bound, not flop-bound — the
trade is the point.

Tree encoding per sample (all int32 arrays of length `max_nodes`):
    word    — token id for leaves, 0 for internal/pad
    left    — post-order index of left child (internal), -1 otherwise
    right   — likewise for the right child
    is_leaf — 1/0/;  mask — 1 for real nodes, 0 for padding
    level   — wavefront depth: 0 for leaves, 1+max(children) internal
Root is the LAST real node in post-order.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module


class BinaryTreeLSTM(Module):
    """(reference: nn/BinaryTreeLSTM.scala — binary composer variant)

    `max_levels`: static wavefront-schedule depth bound. When set AND
    the input batch carries a `level` array (6th input — emitted by
    `encode_from_nested`), evaluation is level-batched: one hoisted
    leaf gemm, then `max_levels - 1` compose steps (vs `max_nodes`
    serial slot steps). Trees deeper than `max_levels - 1` levels are
    NOT supported on that path — `encode_from_nested(...,
    max_levels=...)` enforces the bound at encode time. Without
    `max_levels` or without `level` input, the legacy serial-slot scan
    runs (always correct, any depth)."""

    def __init__(self, vocab_size: int, embed_dim: int, hidden_size: int,
                 class_num: int, *, max_levels: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.hidden_size = hidden_size
        self.class_num = class_num
        self.max_levels = max_levels

    def init_params(self, rng):
        ks = jax.random.split(rng, 4)
        h, d = self.hidden_size, self.embed_dim
        lim_e = 0.5

        def dense(k, i, o):
            lim = (6.0 / (i + o)) ** 0.5  # Xavier, the reference default
            return {"weight": jax.random.uniform(k, (i, o), minval=-lim, maxval=lim),
                    "bias": jnp.zeros((o,))}

        return {
            "embedding": jax.random.uniform(ks[0], (self.vocab_size, d),
                                            minval=-lim_e, maxval=lim_e),
            # leaf: x -> (i, o, u) gates (no forget at leaves)
            "leaf": dense(ks[1], d, 3 * h),
            # composer: [h_l, h_r] -> (i, fl, fr, o, u)
            "compose": dense(ks[2], 2 * h, 5 * h),
            "cls": dense(ks[3], h, self.class_num),
        }

    def _leaf_step(self, p, x_emb):
        z = x_emb @ p["leaf"]["weight"] + p["leaf"]["bias"]
        i, o, u = jnp.split(z, 3, axis=-1)
        c = jax.nn.sigmoid(i) * jnp.tanh(u)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, c

    def _compose_step(self, p, hl, cl, hr, cr):
        z = jnp.concatenate([hl, hr], -1) @ p["compose"]["weight"] \
            + p["compose"]["bias"]
        i, fl, fr, o, u = jnp.split(z, 5, axis=-1)
        c = (jax.nn.sigmoid(fl) * cl + jax.nn.sigmoid(fr) * cr
             + jax.nn.sigmoid(i) * jnp.tanh(u))
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, c

    def apply(self, variables, inputs, training=False, rng=None):
        """inputs: dict/Table with word (N,T), left (N,T), right (N,T),
        is_leaf (N,T), mask (N,T) and optionally level (N,T) — or the
        same arrays as a 5/6-tuple in that order. Returns per-node
        log-probs (N, T, C) in ROOT-FIRST order: node 0 is the tree root
        (TreeNNAccuracy's convention), node t is the t-th node of
        REVERSED post-order; padding at the end. Targets must use the
        same order (see roots_first)."""
        p = variables["params"]
        level = None
        if isinstance(inputs, dict):
            word = inputs["word"]
            left = inputs["left"]
            right = inputs["right"]
            is_leaf = inputs["is_leaf"]
            mask = inputs["mask"]
            level = inputs.get("level")
        elif len(inputs) == 6:
            word, left, right, is_leaf, mask, level = inputs
        else:
            word, left, right, is_leaf, mask = inputs

        if level is not None and self.max_levels is not None:
            h_buf = self._wavefront(p, word, left, right, is_leaf, mask,
                                    level)
        else:
            h_buf = self._slot_scan(p, word, left, right, is_leaf, mask)
        return self._emit_logits(p, h_buf, mask), variables["state"]

    def _slot_scan(self, p, word, left, right, is_leaf, mask):
        """Legacy schedule: one serial `lax.scan` step per post-order
        node slot (any depth; the latency-floor-bound path)."""
        n_batch, t_nodes = word.shape
        h_dim = self.hidden_size

        emb = jnp.take(p["embedding"], word.astype(jnp.int32), axis=0)
        batch_idx = jnp.arange(n_batch)

        def body(carry, t):
            h_buf, c_buf = carry  # (N, T, H) node-state buffers
            x_t = emb[:, t]
            leaf_h, leaf_c = self._leaf_step(p, x_t)
            li = jnp.clip(left[:, t], 0, t_nodes - 1).astype(jnp.int32)
            ri = jnp.clip(right[:, t], 0, t_nodes - 1).astype(jnp.int32)
            hl, cl = h_buf[batch_idx, li], c_buf[batch_idx, li]
            hr, cr = h_buf[batch_idx, ri], c_buf[batch_idx, ri]
            comp_h, comp_c = self._compose_step(p, hl, cl, hr, cr)
            leaf_flag = is_leaf[:, t][:, None].astype(jnp.float32)
            h_t = leaf_flag * leaf_h + (1 - leaf_flag) * comp_h
            c_t = leaf_flag * leaf_c + (1 - leaf_flag) * comp_c
            m = mask[:, t][:, None].astype(jnp.float32)
            h_t, c_t = h_t * m, c_t * m
            h_buf = h_buf.at[:, t].set(h_t)
            c_buf = c_buf.at[:, t].set(c_t)
            return (h_buf, c_buf), None

        h0 = jnp.zeros((n_batch, t_nodes, h_dim))
        (h_buf, _), _ = lax.scan(body, (h0, h0), jnp.arange(t_nodes))
        return h_buf

    def _wavefront(self, p, word, left, right, is_leaf, mask, level):
        """Wavefront schedule: all leaves in one hoisted gemm, then one
        batched compose step per depth level — `max_levels - 1` serial
        steps instead of `max_nodes`. Every slot runs the compose gemm
        each level (full-width MXU matmul); the masked select keeps only
        the slots whose level matches, so math is identical to the slot
        scan (the equivalence test oracles one against the other)."""
        n_batch, t_nodes = word.shape

        emb = jnp.take(p["embedding"], word.astype(jnp.int32), axis=0)
        leaf_h, leaf_c = self._leaf_step(p, emb)          # (N, T, H)
        leaf_on = (is_leaf * mask).astype(bool)[..., None]
        h_buf = jnp.where(leaf_on, leaf_h, 0.0)
        c_buf = jnp.where(leaf_on, leaf_c, 0.0)

        batch_idx = jnp.arange(n_batch)[:, None]
        li = jnp.clip(left, 0, t_nodes - 1).astype(jnp.int32)
        ri = jnp.clip(right, 0, t_nodes - 1).astype(jnp.int32)
        compose_on = ((1 - is_leaf) * mask).astype(bool)

        def body(carry, lvl):
            h_buf, c_buf = carry
            hl, cl = h_buf[batch_idx, li], c_buf[batch_idx, li]
            hr, cr = h_buf[batch_idx, ri], c_buf[batch_idx, ri]
            comp_h, comp_c = self._compose_step(p, hl, cl, hr, cr)
            upd = (compose_on & (level == lvl))[..., None]
            return (jnp.where(upd, comp_h, h_buf),
                    jnp.where(upd, comp_c, c_buf)), None

        (h_buf, _), _ = lax.scan(body, (h_buf, c_buf),
                                 jnp.arange(1, self.max_levels))
        # a tree deeper than the static bound would silently emit the
        # zero-init h for every never-composed node (confidently wrong
        # log-probs). Poison the whole buffer with NaN instead — the
        # anomaly guard / loss checks catch NaN loudly, and
        # encode_from_nested(max_levels=...) prevents it at encode time.
        too_deep = jnp.any((level >= self.max_levels) & (mask == 1))
        return jnp.where(too_deep, jnp.nan, h_buf)

    def _emit_logits(self, p, h_buf, mask):
        n_batch, t_nodes = mask.shape
        batch_idx = jnp.arange(n_batch)
        # reorder to root-first (reversed post-order, padding at the end):
        # node 0 of the output is the root, matching TreeNNAccuracy
        n_nodes = jnp.sum(mask.astype(jnp.int32), axis=1)  # (N,)
        t_range = jnp.arange(t_nodes)[None, :]
        gather_idx = jnp.clip(n_nodes[:, None] - 1 - t_range, 0, t_nodes - 1)
        h_out = h_buf[batch_idx[:, None], gather_idx]
        out_mask = (t_range < n_nodes[:, None]).astype(jnp.float32)[..., None]
        h_out = h_out * out_mask

        # mask logits too: padded slots otherwise emit log_softmax(bias)
        # and (with labels padded to class 0) would push the classifier
        # bias toward class 0 on every padding slot. Masked logits give a
        # constant uniform distribution with ZERO gradient to the params.
        logits = (h_out @ p["cls"]["weight"] + p["cls"]["bias"]) * out_mask
        return jax.nn.log_softmax(logits, axis=-1)


# ----------------------------------------------------------- tree encoding
def roots_first(per_node: np.ndarray, n_nodes: int, pad=0) -> np.ndarray:
    """Reorder a post-order per-node array (e.g. labels) into the
    root-first order BinaryTreeLSTM emits its outputs in."""
    out = np.full_like(per_node, pad)
    out[:n_nodes] = per_node[:n_nodes][::-1]
    return out


def encode_from_nested(tree, max_nodes: int, word2id=None,
                       max_levels: Optional[int] = None):
    """Encode a nested-list binary tree, e.g. ((("a", "b"), "c")) where
    leaves are tokens (str or int). Returns dict of int32 arrays of length
    max_nodes: word/left/right/is_leaf/mask/level, plus n_nodes and
    n_levels (root level + 1 — the wavefront step count). `max_levels`
    (optional) enforces the model's static wavefront bound at encode
    time: a tree needing more levels raises here rather than silently
    mis-evaluating on the level-batched path."""
    word, left, right, is_leaf, level = [], [], [], [], []

    def rec(node):
        if not isinstance(node, (tuple, list)):
            tok = word2id(node) if word2id else int(node)
            word.append(tok)
            left.append(-1)
            right.append(-1)
            is_leaf.append(1)
            level.append(0)
            return len(word) - 1
        l_idx = rec(node[0])
        r_idx = rec(node[1])
        word.append(0)
        left.append(l_idx)
        right.append(r_idx)
        is_leaf.append(0)
        level.append(1 + max(level[l_idx], level[r_idx]))
        return len(word) - 1

    rec(tree)
    n = len(word)
    if n > max_nodes:
        raise ValueError(f"tree has {n} nodes > max_nodes {max_nodes}")
    n_levels = max(level) + 1
    if max_levels is not None and n_levels > max_levels:
        raise ValueError(
            f"tree needs {n_levels} levels > max_levels {max_levels}")

    def pad(a, v=0):
        return np.asarray(a + [v] * (max_nodes - n), np.int32)

    return {
        "word": pad(word), "left": pad(left, -1), "right": pad(right, -1),
        "is_leaf": pad(is_leaf), "mask": pad([1] * n),
        "level": pad(level),
        "n_nodes": n, "n_levels": n_levels,
    }
