"""Synthetic-data throughput harness.

Reference parity: models/utils/LocalOptimizerPerf.scala and
DistriOptimizerPerf.scala — per-model synthetic benchmark binaries
(SURVEY.md §5.1). CLI:

    python -m bigdl_tpu.models.perf --model resnet50 -b 64 -i 20
    python -m bigdl_tpu.models.perf --model lenet --mesh data=8
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
from typing import Optional

import numpy as np

from bigdl_tpu import obs


def _build_model(name: str, class_num: int):
    from bigdl_tpu.models import alexnet, inception, lenet, resnet, vgg

    name = name.lower()
    table = {
        "lenet": (lambda: lenet.build(10), (28, 28, 1), 10),
        "resnet50": (lambda: resnet.build_imagenet(50, class_num), (224, 224, 3), class_num),
        "resnet18": (lambda: resnet.build_imagenet(18, class_num), (224, 224, 3), class_num),
        "resnet20-cifar": (lambda: resnet.build_cifar(20, 10), (32, 32, 3), 10),
        "inception-v1": (lambda: inception.build(class_num), (224, 224, 3), class_num),
        "inception-v2": (lambda: inception.build_v2(class_num), (224, 224, 3), class_num),
        "vgg16": (lambda: vgg.build(16, class_num), (224, 224, 3), class_num),
        "alexnet": (lambda: alexnet.build(class_num), (224, 224, 3), class_num),
    }
    if name not in table:
        raise SystemExit(f"unknown model {name!r}; choices: {sorted(table)}")
    build, shape, classes = table[name]
    return build(), shape, classes


def run_perf(model_name: str = "resnet50", batch_size: int = 32,
             iterations: int = 10, mesh_axes: Optional[str] = None,
             optimizer: str = "sgd", class_num: int = 1000,
             precision: Optional[str] = None) -> dict:
    """Steady-state throughput of the jitted train step: one warmup step
    (compile), then `iterations` timed steps. Timing is fenced by a real
    device-to-host fetch of the final loss — the last step depends on
    every prior step's params, and plain block_until_ready can be
    optimistic through remote-device transports (SURVEY.md §5.1;
    see also bench.py). `precision="bf16"` runs the mixed-precision
    configuration (bf16 compute, fp32 master weights)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.optim import Adam, SGD

    from bigdl_tpu.utils.precision import DEFAULT_MIXED

    policy = DEFAULT_MIXED if precision in ("bf16", "mixed") else None
    model, shape, classes = _build_model(model_name, class_num)
    variables = model.init(jax.random.PRNGKey(0))
    method = (SGD(learningrate=0.01, momentum=0.9, dampening=0.0)
              if optimizer == "sgd" else Adam(1e-3))
    criterion = nn.ClassNLLCriterion()
    rng = np.random.RandomState(0)
    bx_np = rng.rand(batch_size, *shape).astype(np.float32)
    by_np = rng.randint(0, classes, batch_size).astype(np.int32)

    if mesh_axes:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bigdl_tpu.parallel import (
            FlatParamSpec, make_dp_train_step, make_mesh, parse_axes,
        )

        axes = parse_axes(mesh_axes)
        if "data" not in axes:
            raise SystemExit(
                f"--mesh {mesh_axes!r} has no 'data' axis; the perf "
                "harness benchmarks data-parallel training (e.g. "
                "--mesh data=8)")
        mesh = make_mesh(axes)
        n = mesh.shape["data"]
        spec = FlatParamSpec(variables["params"], n)
        step = make_dp_train_step(model, criterion, method, mesh, spec,
                                  precision=policy)
        repl = NamedSharding(mesh, P())
        w = jax.device_put(spec.flatten(variables["params"]), repl)
        slots = jax.tree_util.tree_map(
            lambda s: jax.device_put(s, NamedSharding(mesh, P("data"))),
            method.init_slots(jnp.zeros((spec.padded,), jnp.float32)))
        state = jax.device_put(variables["state"], repl)
        bx = jax.device_put(bx_np, NamedSharding(
            mesh, P("data", *([None] * len(shape)))))
        by = jax.device_put(by_np, NamedSharding(mesh, P("data")))
        args = lambda i: (w, slots, state, bx, by,
                          jnp.asarray(0.01, jnp.float32),
                          jnp.asarray(i, jnp.int32),
                          jax.random.fold_in(jax.random.PRNGKey(7), i))

        def run_one(i):
            nonlocal w, slots, state
            w, slots, state, loss = step(*args(i))
            return loss
    else:
        slots = method.init_slots(variables["params"])
        params, state = variables["params"], variables["state"]
        bx, by = jnp.asarray(bx_np), jnp.asarray(by_np)

        from bigdl_tpu.ops.losses import build_train_loss

        loss_call = build_train_loss(model, criterion, policy)

        @jax.jit
        def step(params, state, slots, i):
            rng = jax.random.fold_in(jax.random.PRNGKey(7), i)
            (loss, new_state), grads = jax.value_and_grad(
                lambda p: loss_call(p, state, bx, by, rng),
                has_aux=True)(params)
            new_params, new_slots = method.update(
                grads, params, slots, jnp.asarray(0.01), i)
            return new_params, new_state, new_slots, loss

        def run_one(i):
            nonlocal params, state, slots
            params, state, slots, loss = step(params, state, slots,
                                              jnp.asarray(i, jnp.int32))
            return loss

    t0 = time.perf_counter()
    float(run_one(0))  # warmup + compile; host fetch = honest fence
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    loss = None
    for i in range(1, iterations + 1):
        loss = run_one(i)
    float(loss)  # final loss depends on every step: fences the chain
    steady = time.perf_counter() - t0

    return {
        "model": model_name,
        "batch_size": batch_size,
        "iterations": iterations,
        "compile_s": round(compile_s, 3),
        "steady_wall_s": round(steady, 3),
        "images_per_sec": round(iterations * batch_size / steady, 2),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("-b", "--batch-size", type=int, default=32)
    ap.add_argument("-i", "--iterations", type=int, default=10)
    ap.add_argument("--mesh", default=None,
                    help="e.g. data=8 to benchmark the DP path")
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
    ap.add_argument("--class-num", type=int, default=1000)
    ap.add_argument("--precision", default=None,
                    choices=[None, "bf16", "mixed", "fp32"],
                    help="bf16 → mixed precision (fp32 master weights)")
    args = ap.parse_args(argv)
    result = run_perf(args.model, args.batch_size, args.iterations,
                      args.mesh, args.optimizer, args.class_num,
                      args.precision)
    # telemetry convention: results go through the obs plane + logger,
    # never print (graftlint telemetry-bypass). The handler is pinned
    # to STDOUT (basicConfig defaults to stderr) so the machine-read
    # `... | jq .` contract of the old print() survives; force=True
    # wins even if an import already configured the root logger
    obs.emit_event("perf_result", plane="training", **result)
    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    logging.getLogger("bigdl_tpu.models").info(json.dumps(result))


if __name__ == "__main__":
    main()
