"""VGG.

Reference parity: models/vgg/Vgg_16.scala / Vgg_19.scala (ImageNet) and
the CIFAR VggForCifar10 variant (conv-bn-relu stacks).
"""

from __future__ import annotations

from bigdl_tpu import nn

_CFG = {
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def build(depth: int = 16, class_num: int = 1000,
          with_bn: bool = False, image_size: int = 224) -> nn.Sequential:
    """(reference: models/vgg/Vgg_16.scala#Vgg_16.apply)"""
    m = nn.Sequential()
    n_in = 3
    for v in _CFG[depth]:
        if v == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            m.add(nn.SpatialConvolution(n_in, v, 3, 3, 1, 1, 1, 1))
            if with_bn:
                m.add(nn.SpatialBatchNormalization(v))
            m.add(nn.ReLU())
            n_in = v
    feat = image_size // 32
    m.add(nn.Reshape([512 * feat * feat]))
    m.add(nn.Linear(512 * feat * feat, 4096))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m


def build_cifar(class_num: int = 10) -> nn.Sequential:
    """(reference: models/vgg/VggForCifar10.scala) conv-bn-relu stacks with
    512-unit head."""
    m = nn.Sequential()
    n_in = 3
    for v in [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"]:
        if v == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            m.add(nn.SpatialConvolution(n_in, v, 3, 3, 1, 1, 1, 1))
            m.add(nn.SpatialBatchNormalization(v))
            m.add(nn.ReLU())
            n_in = v
    m.add(nn.Reshape([512]))
    m.add(nn.Linear(512, 512))
    m.add(nn.BatchNormalization(512))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(512, class_num))
    m.add(nn.LogSoftMax())
    return m


Vgg_16 = lambda class_num=1000: build(16, class_num)
Vgg_19 = lambda class_num=1000: build(19, class_num)
