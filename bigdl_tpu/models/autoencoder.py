"""Fully-connected autoencoder.

Reference parity: models/autoencoder/Autoencoder.scala — 784→32→784 MLP
with sigmoid output trained with MSE on MNIST.
"""

from __future__ import annotations

from bigdl_tpu import nn


def build(class_num: int = 32, input_size: int = 784) -> nn.Sequential:
    return nn.Sequential(
        nn.Reshape([input_size]),
        nn.Linear(input_size, class_num).set_name("encoder"),
        nn.ReLU(),
        nn.Linear(class_num, input_size).set_name("decoder"),
        nn.Sigmoid(),
    )


Autoencoder = build
