"""Recurrent models: PTB-style language model and BiLSTM sentiment.

Reference parity: models/rnn/SimpleRNN.scala (LookupTable→Recurrent(RnnCell)
→TimeDistributed(Linear)→LogSoftMax over time) and the BiLSTM sentiment
configuration from the reference's example/ (BiRecurrent(LSTM) → pooled
classifier), trained with TimeDistributedCriterion(ClassNLLCriterion) /
CrossEntropy respectively (SURVEY.md §2.5 model zoo, BASELINE.md config 4).
"""

from __future__ import annotations

from bigdl_tpu import nn


def simple_rnn(vocab_size: int, hidden_size: int = 40,
               output_size: int = None, embed_dim: int = None) -> nn.Sequential:
    """(reference: models/rnn/SimpleRNN.scala) word-level LM."""
    output_size = output_size or vocab_size
    embed_dim = embed_dim or hidden_size
    return nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim).set_name("embedding"),
        nn.Recurrent(nn.RnnCell(embed_dim, hidden_size)).set_name("rnn"),
        nn.TimeDistributed(nn.Linear(hidden_size, output_size)).set_name("proj"),
        nn.TimeDistributed(nn.LogSoftMax()),
    )


def lstm_lm(vocab_size: int, embed_dim: int = 128, hidden_size: int = 128,
            num_layers: int = 1, dropout: float = 0.0) -> nn.Sequential:
    """LSTM language model (reference: example/languagemodel PTB config)."""
    m = nn.Sequential(nn.LookupTable(vocab_size, embed_dim).set_name("embedding"))
    in_size = embed_dim
    for i in range(num_layers):
        m.add(nn.Recurrent(nn.LSTM(in_size, hidden_size)).set_name(f"lstm{i}"))
        if dropout > 0:
            m.add(nn.Dropout(dropout))
        in_size = hidden_size
    m.add(nn.TimeDistributed(nn.Linear(hidden_size, vocab_size)).set_name("proj"))
    m.add(nn.TimeDistributed(nn.LogSoftMax()))
    return m


class _MeanOverTime(nn.Module):
    """Mean-pool over the time axis of (N, T, D)."""

    def apply(self, variables, x, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.mean(x, axis=1), variables["state"]


def bilstm_sentiment(vocab_size: int, embed_dim: int = 128,
                     hidden_size: int = 128, class_num: int = 2,
                     fused=None) -> nn.Sequential:
    """BiLSTM text classifier (reference: example/ sentiment BiRecurrent
    config; BASELINE.md config 4). `fused` forwards to BiRecurrent —
    None auto-selects the one-launch persistent Pallas scan on TPU
    (ops/fused_rnn.py), False keeps the lax.scan path."""
    return nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim).set_name("embedding"),
        nn.BiRecurrent(nn.LSTM(embed_dim, hidden_size),
                       fused=fused).set_name("bilstm"),
        _MeanOverTime(),
        nn.Linear(2 * hidden_size, class_num).set_name("cls"),
        nn.LogSoftMax(),
    )
