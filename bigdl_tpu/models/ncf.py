"""Neural Collaborative Filtering (NCF / NeuralCF).

Reference parity: the BigDL paper's headline recommendation benchmark
(arXiv 1804.05839 §evaluation, NCF vs GPU comparison; model shape per the
reference line's `NeuralCF` — GMF + MLP towers over user/item embeddings,
evaluated with HitRatio/NDCG which live in `bigdl_tpu.optim.validation`).

Input is an int array (batch, 2) of [user_id, item_id] pairs (0-based);
output is log-probabilities over `class_num` rating classes, trained with
`ClassNLLCriterion` like the reference. The two embedding towers are pure
gathers + an MLP — everything XLA fuses into a handful of MXU matmuls.
"""

from __future__ import annotations

from typing import Optional, Sequence

from bigdl_tpu import nn


def build(user_count: int, item_count: int, class_num: int = 5,
          user_embed: int = 20, item_embed: int = 20,
          hidden_layers: Sequence[int] = (40, 20, 10),
          include_mf: bool = True, mf_embed: int = 20) -> "nn.Graph":
    """GMF ⊙ + MLP concat tower, mirroring NeuralCF's constructor shape."""
    pair = nn.Input()
    user = nn.Select(2, 1)(pair)   # (B,) user ids
    item = nn.Select(2, 2)(pair)   # (B,) item ids

    # MLP tower: concat(user_emb, item_emb) -> hidden ReLU stack
    u_mlp = nn.LookupTable(user_count, user_embed)(user)
    i_mlp = nn.LookupTable(item_count, item_embed)(item)
    h = nn.JoinTable(2)(u_mlp, i_mlp)
    in_dim = user_embed + item_embed
    for out_dim in hidden_layers:
        h = nn.Linear(in_dim, out_dim)(h)
        h = nn.ReLU()(h)
        in_dim = out_dim

    if include_mf:
        # GMF tower: elementwise product of dedicated MF embeddings
        u_mf = nn.LookupTable(user_count, mf_embed)(user)
        i_mf = nn.LookupTable(item_count, mf_embed)(item)
        gmf = nn.CMulTable()(u_mf, i_mf)
        h = nn.JoinTable(2)(gmf, h)
        in_dim = in_dim + mf_embed

    score = nn.Linear(in_dim, class_num)(h)
    out = nn.LogSoftMax()(score)
    return nn.Graph(pair, out)


NeuralCF = build
