"""ResNet.

Reference parity: models/resnet/ResNet.scala — `ResNet.apply(classNum,
opt)` with `depth`, `shortcutType` (A: identity+zero-pad, B: 1x1 conv
projection on dim change, C: always projection), `dataSet` (CIFAR-10 basic
blocks / ImageNet bottleneck), and the iChannels bookkeeping; also the
reference's MSRA init convention (MsraFiller) and zero-init of the last BN
gamma per block ("optnet"-era trick kept by the reference's init).

TPU-first: NHWC, bn-relu fusion left to XLA, residual add via
ConcatTable+CAddTable (the reference's exact idiom).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu import nn
from bigdl_tpu.nn.initialization import MsraFiller, Zeros


def _conv(n_in, n_out, k, stride=1, pad=0):
    return nn.SpatialConvolution(
        n_in, n_out, k, k, stride, stride, pad, pad, with_bias=False,
        w_init=MsraFiller(variance_norm_average=False))


def _bn(n, zero_gamma=False):
    bn = nn.SpatialBatchNormalization(n)
    if zero_gamma:
        orig = bn.init_params

        def patched(rng):
            p = orig(rng)
            p["weight"] = p["weight"] * 0.0
            return p

        bn.init_params = patched
    return bn


def _shortcut(n_in, n_out, stride, shortcut_type="B"):
    use_conv = (shortcut_type == "C"
                or (shortcut_type == "B" and (n_in != n_out or stride != 1)))
    if use_conv:
        return nn.Sequential(_conv(n_in, n_out, 1, stride), _bn(n_out))
    if n_in != n_out or stride != 1:
        # type A: strided identity + zero-pad channels
        return nn.Sequential(
            nn.SpatialAveragePooling(1, 1, stride, stride),
            _ChannelPad(n_out - n_in),
        )
    return nn.Identity()


class _ChannelPad(nn.Module):
    def __init__(self, extra: int, name=None):
        super().__init__(name=name)
        self.extra = extra

    def apply(self, variables, x, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, self.extra))), variables["state"]


def basic_block(n_in, n_out, stride=1, shortcut_type="B"):
    """3x3+3x3 block (reference: ResNet.scala#basicBlock)."""
    main = nn.Sequential(
        _conv(n_in, n_out, 3, stride, 1), _bn(n_out), nn.ReLU(),
        _conv(n_out, n_out, 3, 1, 1), _bn(n_out, zero_gamma=True),
    )
    return nn.Sequential(
        nn.ConcatTable(main, _shortcut(n_in, n_out, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


def bottleneck(n_in, planes, stride=1, shortcut_type="B", expansion=4):
    """1x1-3x3-1x1 block (reference: ResNet.scala#bottleneck)."""
    n_out = planes * expansion
    main = nn.Sequential(
        _conv(n_in, planes, 1), _bn(planes), nn.ReLU(),
        _conv(planes, planes, 3, stride, 1), _bn(planes), nn.ReLU(),
        _conv(planes, n_out, 1), _bn(n_out, zero_gamma=True),
    )
    return nn.Sequential(
        nn.ConcatTable(main, _shortcut(n_in, n_out, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


def build_cifar(depth: int = 20, class_num: int = 10,
                shortcut_type: str = "A") -> nn.Sequential:
    """CIFAR-10 ResNet (reference: ResNet.apply cifar10 branch; depth =
    6n+2 with n blocks per stage)."""
    assert (depth - 2) % 6 == 0, "cifar depth must be 6n+2"
    n = (depth - 2) // 6
    model = nn.Sequential(
        _conv(3, 16, 3, 1, 1), _bn(16), nn.ReLU(),
    )
    n_in = 16
    for stage, (planes, stride) in enumerate([(16, 1), (32, 2), (64, 2)]):
        for b in range(n):
            model.add(basic_block(n_in, planes, stride if b == 0 else 1,
                                  shortcut_type))
            n_in = planes
    model.add(nn.SpatialAveragePooling(8, 8, 1, 1))
    model.add(nn.Reshape([64]))
    model.add(nn.Linear(64, class_num).set_name("fc"))
    model.add(nn.LogSoftMax())
    return model


def build_imagenet(depth: int = 50, class_num: int = 1000,
                   shortcut_type: str = "B",
                   stem: str = "conv7") -> nn.Sequential:
    """ImageNet ResNet (reference: ResNet.apply imagenet branch).

    stem="s2d": SpaceToDepth(2) + 4x4/stride-1 conv over 12 channels —
    function-space superset of the reference 7x7/stride-2 stem (same
    stride-2 geometry; the 4x4 kernel on the s2d grid covers an 8x8>=7x7
    receptive field) that contracts over 12 channels instead of 3, the
    TPU MXU stem idiom (MLPerf-era; PROFILE_r04 measured the conv7 stem
    at 6% of peak).
    """
    cfgs = {
        18: (basic_block, [2, 2, 2, 2], 1),
        34: (basic_block, [3, 4, 6, 3], 1),
        50: (bottleneck, [3, 4, 6, 3], 4),
        101: (bottleneck, [3, 4, 23, 3], 4),
        152: (bottleneck, [3, 8, 36, 3], 4),
    }
    block, layers, expansion = cfgs[depth]
    if stem == "s2d":
        model = nn.Sequential(nn.SpaceToDepth(2),
                              _conv(12, 64, 4, 1, (2, 1)).set_name("conv1"))
    elif stem == "conv7":
        model = nn.Sequential(_conv(3, 64, 7, 2, 3).set_name("conv1"))
    else:
        raise ValueError(f"unknown stem {stem!r} (conv7 | s2d)")
    model.add(_bn(64)).add(nn.ReLU())
    model.add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1))
    n_in = 64
    for stage, (planes, stride) in enumerate([(64, 1), (128, 2), (256, 2),
                                              (512, 2)]):
        for b in range(layers[stage]):
            if block is bottleneck:
                model.add(bottleneck(n_in, planes, stride if b == 0 else 1,
                                     shortcut_type, expansion))
                n_in = planes * expansion
            else:
                model.add(basic_block(n_in, planes, stride if b == 0 else 1,
                                      shortcut_type))
                n_in = planes
    model.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    model.add(nn.Reshape([n_in]))
    model.add(nn.Linear(n_in, class_num).set_name("fc"))
    model.add(nn.LogSoftMax())
    return model


def build(depth: int = 50, class_num: int = 1000, dataset: str = "imagenet",
          shortcut_type: Optional[str] = None) -> nn.Sequential:
    if dataset == "cifar10":
        return build_cifar(depth, class_num, shortcut_type or "A")
    return build_imagenet(depth, class_num, shortcut_type or "B")


ResNet = build
