"""Unified training CLI for the model zoo.

Reference parity: the per-model `Train.scala`/`Test.scala`/`Utils.scala`
scopt CLIs (models/lenet/Train.scala, models/resnet/Train.scala, ...).
One CLI covers the zoo; flags mirror the reference's option names
(-f dataFolder, -b batchSize, --learningRate, --maxEpoch, --checkpoint).

    python -m bigdl_tpu.models.train --model lenet -f /data/mnist -b 128 \
        --maxEpoch 5 --checkpoint /tmp/ck --mesh data=8
    python -m bigdl_tpu.models.train --model resnet20-cifar -f /data/cifar \
        --synthetic  # no dataset on disk: synthetic stand-in
"""

from __future__ import annotations

import argparse
import logging


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="lenet",
                    help="lenet | resnet20-cifar | resnet50 | resnet18 | "
                         "inception-v1 | vgg16 | alexnet | "
                         "textclassifier | ncf | bilstm | transformer")
    ap.add_argument("-f", "--dataFolder", default=None)
    ap.add_argument("-b", "--batchSize", type=int, default=128)
    ap.add_argument("--learningRate", type=float, default=0.01)
    ap.add_argument("--maxEpoch", type=int, default=5)
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--weightDecay", type=float, default=0.0)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--summary", default=None, help="TensorBoard log dir")
    ap.add_argument("--mesh", default=None, help="e.g. data=8")
    ap.add_argument("--synthetic", action="store_true",
                    help="use synthetic data (no dataset folder needed)")
    ap.add_argument("--records", default=None, metavar="DIR|GLOB",
                    help="train from disk-resident BDLS record shards "
                         "through the native dataplane (any vision "
                         "model; see bigdl_tpu.dataset.records)")
    ap.add_argument("--recordsMean", default="127.5",
                    help="comma per-channel mean for --records")
    ap.add_argument("--recordsStd", default="127.5",
                    help="comma per-channel std for --records")
    ap.add_argument("--recordsAug", default="",
                    help="comma subset of: hflip,pad<N> (e.g. hflip,pad4)")
    ap.add_argument("--moeExperts", type=int, default=0,
                    help="transformer only: Switch/GShard-MoE FFN with "
                         "this many experts (0 = dense)")
    ap.add_argument("--moeTopK", type=int, default=1, choices=[1, 2])
    ap.add_argument("--moeRouting", default="top_k",
                    choices=["top_k", "expert_choice"])
    ap.add_argument("--tfrecords", default=None, metavar="DIR|GLOB",
                    help="train a vision model from TFRecord shards of "
                         "tf.train.Examples (image/shape/label layout; "
                         "see bigdl_tpu.dataset.tfrecord)")
    ap.add_argument("--precision", default=None,
                    choices=["bf16", "mixed", "fp32"],
                    help="bf16 → mixed-precision training")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")

    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.optim import (
        Adam, Optimizer, SGD, Top1Accuracy, Trigger,
    )
    from bigdl_tpu.visualization import TrainSummary, ValidationSummary

    # ---- data + model
    if args.model == "lenet":
        from bigdl_tpu.dataset.mnist import load_mnist, synthetic_mnist
        from bigdl_tpu.models import lenet

        model = lenet.build(10)
        if args.synthetic or not args.dataFolder:
            train, val = synthetic_mnist(4096), synthetic_mnist(512, seed=9)
        else:
            train = load_mnist(args.dataFolder, train=True)
            val = load_mnist(args.dataFolder, train=False)
    elif args.model == "resnet20-cifar":
        from bigdl_tpu.dataset.cifar import load_cifar10, synthetic_cifar10
        from bigdl_tpu.models import resnet

        model = resnet.build_cifar(20, 10)
        if args.synthetic or not args.dataFolder:
            train, val = synthetic_cifar10(2048), synthetic_cifar10(256, seed=9)
        else:
            train = load_cifar10(args.dataFolder, train=True)
            val = load_cifar10(args.dataFolder, train=False)
    elif args.model in ("textclassifier", "ncf", "bilstm"):
        import numpy as np
        from bigdl_tpu.dataset import Sample

        rng = np.random.RandomState(0)
        n = args.batchSize * 4
        if args.model == "textclassifier":
            from bigdl_tpu.models import textclassifier

            model = textclassifier.build(class_num=4, vocab_size=200,
                                         sequence_len=200)
            ys = rng.randint(0, 4, n)
            train = [Sample(rng.randint(y * 50, y * 50 + 50,
                                        200).astype(np.int32), int(y))
                     for y in ys]
        elif args.model == "ncf":
            from bigdl_tpu.models import ncf

            model = ncf.build(64, 128, class_num=5)
            train = [Sample(np.asarray(
                [rng.randint(64), rng.randint(128)], np.int32),
                np.int32(rng.randint(5))) for _ in range(n)]
        else:  # bilstm sentiment
            from bigdl_tpu.models import rnn

            model = rnn.bilstm_sentiment(100, embed_dim=32, hidden_size=32)
            ys = rng.randint(0, 2, n)
            train = [Sample(rng.randint(y * 40, y * 40 + 40,
                                        24).astype(np.int32), int(y))
                     for y in ys]
        val = train[:args.batchSize]
    elif args.model == "transformer":
        from bigdl_tpu.dataset.text import synthetic_next_token
        from bigdl_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM)

        seq = 32
        model = TransformerLM(TransformerConfig(
            vocab_size=64, dim=128, num_heads=4, num_layers=2,
            max_len=seq, moe_experts=args.moeExperts,
            moe_top_k=args.moeTopK, moe_routing=args.moeRouting))
        train = synthetic_next_token(args.batchSize * 4, 64, seq)
        val = train[:args.batchSize]
    else:
        from bigdl_tpu.models.perf import _build_model
        import numpy as np
        from bigdl_tpu.dataset import Sample

        if args.dataFolder:
            raise SystemExit(
                f"--dataFolder is not supported for model {args.model!r} "
                "(only lenet / resnet20-cifar have dataset loaders); drop "
                "-f to train on synthetic data")
        model, shape, classes = _build_model(args.model, 1000)
        if args.records or args.tfrecords:
            train, val = [], []  # disk shards replace the synthetic pool
        else:
            rng = np.random.RandomState(0)
            train = [Sample(rng.rand(*shape).astype(np.float32),
                            np.int32(rng.randint(classes)))
                     for _ in range(args.batchSize * 4)]
            val = train[:args.batchSize]

    model.build(jax.random.PRNGKey(42))

    method = (SGD(learningrate=args.learningRate, momentum=args.momentum,
                  dampening=0.0, weightdecay=args.weightDecay)
              if args.optimizer == "sgd" else Adam(args.learningRate))

    if args.model == "transformer":
        # LM path: the fused chunked criterion keeps the (B, S, V)
        # log-prob tensor off the training step entirely
        criterion = nn.ChunkedSoftmaxCE()
        from bigdl_tpu.optim import Loss
        val_methods = [Loss(criterion)]
    else:
        criterion = nn.ClassNLLCriterion()
        val_methods = [Top1Accuracy()]

    if args.records and args.tfrecords:
        raise SystemExit("--records and --tfrecords are exclusive")
    if (args.records or args.tfrecords) and args.model in (
            "transformer", "textclassifier", "ncf", "bilstm"):
        raise SystemExit(
            f"record shards hold images; model {args.model!r} takes "
            "token inputs (use a vision model)")
    if args.tfrecords:
        import numpy as np

        from bigdl_tpu.dataset import Sample, TFRecordDataSet
        from bigdl_tpu.dataset.tfrecord import default_image_parser

        if args.recordsAug:
            raise SystemExit(
                "--recordsAug applies to --records (native-plane "
                "augmentation); TFRecord training is unaugmented")
        mean = np.asarray([float(v) for v in args.recordsMean.split(",")],
                          np.float32)
        std = np.asarray([float(v) for v in args.recordsStd.split(",")],
                         np.float32)

        def parser(example):
            s = default_image_parser(example)
            return Sample((s.feature - mean) / std, s.label)

        train_ds = TFRecordDataSet(args.tfrecords, parser=parser)
        logging.getLogger("bigdl_tpu").info(
            "tfrecords: %d samples from %d shards (mean=%s std=%s)",
            train_ds.size(), len(train_ds.paths), mean, std)
        val_ds = train_ds
    elif args.records:
        # disk-resident path: BDLS shards → native mmap prefetcher
        # (reference: the Spark-executor-fed ImageNet pipeline,
        # SURVEY.md §2.4/§7; dataset/records.py)
        from bigdl_tpu.dataset import RecordFileDataSet, resolve_shards
        from bigdl_tpu.dataset.records import read_header

        _, _, _, chans = read_header(resolve_shards(args.records)[0])

        def _per_channel(spec):
            vals = [float(v) for v in spec.split(",")]
            return vals * chans if len(vals) == 1 else vals

        pad, hflip = 0, False
        for tok in filter(None, args.recordsAug.split(",")):
            if tok == "hflip":
                hflip = True
            elif tok.startswith("pad"):
                pad = int(tok[3:])
            else:
                raise SystemExit(f"unknown --recordsAug token {tok!r}")
        train_ds = RecordFileDataSet(
            args.records, args.batchSize, mean=_per_channel(args.recordsMean),
            std=_per_channel(args.recordsStd), pad=pad, hflip=hflip)
        logging.getLogger("bigdl_tpu").info(
            "records: %d samples %s from %d shards (native=%s)",
            train_ds.size(), train_ds.shape, len(train_ds.paths),
            train_ds.native)
        val_ds = train_ds  # eval iterates the shards once, unaugmented
    else:
        train_ds = DataSet.array(train)
        val_ds = DataSet.array(val)

    opt = (Optimizer(model, train_ds, criterion,
                     batch_size=args.batchSize)
           .set_optim_method(method)
           .set_end_when(Trigger.max_epoch(args.maxEpoch))
           .set_validation(Trigger.every_epoch(), val_ds,
                           val_methods, args.batchSize))
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, Trigger.every_epoch())
        if args.resume:
            opt.resume_from_checkpoint()
    if args.summary:
        opt.set_train_summary(TrainSummary(args.summary, args.model))
        opt.set_validation_summary(ValidationSummary(args.summary, args.model))
    if args.precision and args.precision != "fp32":
        opt.set_precision("bf16")
    if args.mesh:
        from bigdl_tpu.parallel import make_mesh, parse_axes

        opt.set_mesh(make_mesh(parse_axes(args.mesh)))

    opt.optimize()


if __name__ == "__main__":
    main()
