"""Model zoo (reference: bigdl/models/)."""

from bigdl_tpu.models import (
    alexnet, autoencoder, inception, lenet, ncf, resnet, rnn,
    textclassifier, vgg,
)
