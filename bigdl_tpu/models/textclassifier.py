"""Text-classification CNN (news20-style).

Reference parity: example/textclassification (TextClassifier.scala) — GloVe
embeddings → temporal conv(128, k=5) → ReLU → temporal max-pool(5) ×2 →
global pool → linear(128) → linear(classNum) → logsoftmax.

Here the embedding is a trainable `LookupTable` (optionally initialised from
pretrained vectors via `set_embedding`); input is int token ids
(batch, seq_len). The temporal convs lower onto the MXU (see
nn.TemporalConvolution).
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu import nn


def build(class_num: int = 20, vocab_size: int = 20000,
          sequence_len: int = 500, embedding_dim: int = 100,
          filters: int = 128) -> nn.Sequential:
    pooled = sequence_len
    model = nn.Sequential(
        nn.LookupTable(vocab_size, embedding_dim).set_name("embedding"),
    )
    in_dim = embedding_dim
    for i in range(2):
        model.add(nn.TemporalConvolution(in_dim, filters, 5)
                  .set_name(f"conv{i + 1}"))
        model.add(nn.ReLU())
        model.add(nn.TemporalMaxPooling(5, 5))
        pooled = (pooled - 5 + 1) // 5
        in_dim = filters
    model.add(nn.TemporalConvolution(in_dim, filters, 5).set_name("conv3"))
    model.add(nn.ReLU())
    model.add(nn.TemporalMaxPooling(-1))  # global max over time
    model.add(nn.Reshape([filters]))
    model.add(nn.Linear(filters, 100).set_name("fc1"))
    model.add(nn.ReLU())
    model.add(nn.Linear(100, class_num).set_name("score"))
    model.add(nn.LogSoftMax())
    return model


def set_embedding(variables: dict, vectors: np.ndarray) -> dict:
    """Install pretrained embedding vectors (e.g. GloVe) into `variables`
    (the reference bakes GloVe weights into the LookupTable the same way)."""
    params = dict(variables["params"])
    key = next(k for k in params if k.endswith("_embedding"))
    emb = dict(params[key])
    assert emb["weight"].shape == vectors.shape, (
        f"{emb['weight'].shape} vs {vectors.shape}")
    emb["weight"] = vectors.astype(np.float32)
    params[key] = emb
    return {**variables, "params": params}


TextClassifier = build
