"""Sample and MiniBatch.

Reference parity: dataset/Sample.scala (feature+label tensor pair),
dataset/MiniBatch.scala (batched samples; `slice` for per-thread splits),
dataset/SampleToMiniBatch (the batcher lives in transformer.py).

Host-side data is numpy (cheap mutation, no device traffic); conversion to
device arrays happens once per step at the jit boundary.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


class Sample:
    """One training example: feature(s) + label(s)
    (reference: dataset/Sample.scala#Sample)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label=None):
        self.feature = np.asarray(feature) if not isinstance(feature, (tuple, list)) \
            else tuple(np.asarray(f) for f in feature)
        if label is None:
            self.label = None
        elif isinstance(label, (tuple, list)):
            self.label = tuple(np.asarray(l) for l in label)
        else:
            self.label = np.asarray(label)

    def feature_size(self):
        if isinstance(self.feature, tuple):
            return tuple(f.shape for f in self.feature)
        return self.feature.shape

    def label_size(self):
        if self.label is None:
            return None
        if isinstance(self.label, tuple):
            return tuple(l.shape for l in self.label)
        return self.label.shape

    def __repr__(self):
        return f"Sample(feature={self.feature_size()}, label={self.label_size()})"


class MiniBatch:
    """A batch of stacked samples (reference: dataset/MiniBatch.scala).

    `input`/`target` are numpy arrays (or tuples of arrays for multi-IO).
    `slice(offset, length)` mirrors the reference's per-thread split API.
    """

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     pad_to: Optional[int] = None) -> "MiniBatch":
        """Stack samples; optionally right-pad the batch dim to `pad_to` by
        repeating the last sample (keeps jit shapes static for the final
        partial batch — the reference instead drops or shrinks)."""
        n = len(samples)
        if pad_to is not None and n < pad_to:
            samples = list(samples) + [samples[-1]] * (pad_to - n)

        def stack(get):
            first = get(samples[0])
            if first is None:
                return None
            if isinstance(first, tuple):
                return tuple(np.stack([get(s)[i] for s in samples])
                             for i in range(len(first)))
            return np.stack([get(s) for s in samples])

        mb = MiniBatch(stack(lambda s: s.feature), stack(lambda s: s.label))
        mb.real_size = n
        return mb

    @property
    def size(self) -> int:
        first = self.input[0] if isinstance(self.input, tuple) else self.input
        return first.shape[0]

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """0-based slice along batch (reference MiniBatch.slice is 1-based)."""

        def cut(x):
            if x is None:
                return None
            if isinstance(x, tuple):
                return tuple(e[offset:offset + length] for e in x)
            return x[offset:offset + length]

        return MiniBatch(cut(self.input), cut(self.target))

    def __repr__(self):
        shp = (tuple(i.shape for i in self.input)
               if isinstance(self.input, tuple) else self.input.shape)
        return f"MiniBatch(input={shp}, size={self.size})"
