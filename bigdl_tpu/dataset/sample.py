"""Sample and MiniBatch.

Reference parity: dataset/Sample.scala (feature+label tensor pair),
dataset/MiniBatch.scala (batched samples; `slice` for per-thread splits),
dataset/SampleToMiniBatch (the batcher lives in transformer.py).

Host-side data is numpy (cheap mutation, no device traffic); conversion to
device arrays happens once per step at the jit boundary.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


class Sample:
    """One training example: feature(s) + label(s)
    (reference: dataset/Sample.scala#Sample)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label=None):
        self.feature = np.asarray(feature) if not isinstance(feature, (tuple, list)) \
            else tuple(np.asarray(f) for f in feature)
        if label is None:
            self.label = None
        elif isinstance(label, (tuple, list)):
            self.label = tuple(np.asarray(l) for l in label)
        else:
            self.label = np.asarray(label)

    def feature_size(self):
        if isinstance(self.feature, tuple):
            return tuple(f.shape for f in self.feature)
        return self.feature.shape

    def label_size(self):
        if self.label is None:
            return None
        if isinstance(self.label, tuple):
            return tuple(l.shape for l in self.label)
        return self.label.shape

    def __repr__(self):
        return f"Sample(feature={self.feature_size()}, label={self.label_size()})"


def _stack_padded(arrays, pad_value, target_len=None):
    """np.stack, right-padding each array's first axis with `pad_value`
    to the common (or `target_len`) length when pad_value is given."""
    if pad_value is None:
        return np.stack(arrays)
    arrays = [np.asarray(a) for a in arrays]
    if arrays[0].ndim == 0:
        return np.stack(arrays)
    length = target_len if target_len is not None \
        else max(a.shape[0] for a in arrays)

    def pad(a):
        if a.shape[0] > length:
            raise ValueError(
                f"sample length {a.shape[0]} exceeds padding_length "
                f"{length}")
        if a.shape[0] == length:
            return a
        widths = [(0, length - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths, constant_values=pad_value)

    return np.stack([pad(a) for a in arrays])


class MiniBatch:
    """A batch of stacked samples (reference: dataset/MiniBatch.scala).

    `input`/`target` are numpy arrays (or tuples of arrays for multi-IO).
    `slice(offset, length)` mirrors the reference's per-thread split API.
    """

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     pad_to: Optional[int] = None,
                     feature_padding: Optional[float] = None,
                     label_padding: Optional[float] = None,
                     padding_length: Optional[int] = None) -> "MiniBatch":
        """Stack samples; optionally right-pad the batch dim to `pad_to` by
        repeating the last sample (keeps jit shapes static for the final
        partial batch — the reference instead drops or shrinks).

        `feature_padding`/`label_padding` enable variable-length stacking
        (reference: dataset/PaddingParam.scala via SampleToMiniBatch):
        each array is right-padded along its first axis with the given
        value to the batch max — or to `padding_length` when set (fixed
        length keeps jit shapes static across batches)."""
        n = len(samples)
        if padding_length is not None and feature_padding is None \
                and label_padding is None:
            raise ValueError(
                "padding_length needs feature_padding and/or "
                "label_padding to supply the pad value")
        if pad_to is not None and n < pad_to:
            samples = list(samples) + [samples[-1]] * (pad_to - n)

        def stack(get, pad_value):
            first = get(samples[0])
            if first is None:
                return None
            if isinstance(first, tuple):
                return tuple(
                    _stack_padded([get(s)[i] for s in samples], pad_value,
                                  padding_length)
                    for i in range(len(first)))
            return _stack_padded([get(s) for s in samples], pad_value,
                                 padding_length)

        mb = MiniBatch(stack(lambda s: s.feature, feature_padding),
                       stack(lambda s: s.label, label_padding))
        mb.real_size = n
        return mb

    @property
    def size(self) -> int:
        first = self.input[0] if isinstance(self.input, tuple) else self.input
        return first.shape[0]

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """0-based slice along batch (reference MiniBatch.slice is 1-based)."""

        def cut(x):
            if x is None:
                return None
            if isinstance(x, tuple):
                return tuple(e[offset:offset + length] for e in x)
            return x[offset:offset + length]

        return MiniBatch(cut(self.input), cut(self.target))

    def __repr__(self):
        shp = (tuple(i.shape for i in self.input)
               if isinstance(self.input, tuple) else self.input.shape)
        return f"MiniBatch(input={shp}, size={self.size})"
