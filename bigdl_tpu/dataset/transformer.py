"""Transformer — composable preprocessing over iterators.

Reference parity: dataset/Transformer.scala (`Transformer[A,B]` applied to
an Iterator, chained with `->`) and dataset/SampleToMiniBatch.scala.

Python has no `->` operator; chaining uses `>>` (and `chain(a, b, c)`).
Each transformer is `Iterator[A] -> Iterator[B]`, exactly the reference's
contract, so transforms stay streaming and O(1) in memory.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch, Sample


class Transformer:
    """Iterator→iterator transform (reference: dataset/Transformer.scala)."""

    def apply(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it: Iterable) -> Iterator:
        return self.apply(iter(it))

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """`a >> b` — the reference's `a -> b`."""
        return ChainedTransformer(self, other)


class ChainedTransformer(Transformer):
    def __init__(self, *stages: Transformer):
        flat: List[Transformer] = []
        for s in stages:
            if isinstance(s, ChainedTransformer):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat

    def apply(self, it: Iterator) -> Iterator:
        for s in self.stages:
            it = s.apply(it)
        return it


def chain(*stages: Transformer) -> ChainedTransformer:
    return ChainedTransformer(*stages)


class MapTransformer(Transformer):
    """Lift a per-element function (helper; reference builds these ad hoc)."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, it):
        return map(self.fn, it)


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches
    (reference: dataset/SampleToMiniBatch.scala).

    partial="pad" keeps the trailing partial batch, padded to full size
    with `real_size` recorded (static shapes under jit);
    partial="drop" mirrors dropping it.
    """

    def __init__(self, batch_size: int, partial: str = "pad",
                 feature_padding=None, label_padding=None,
                 padding_length=None):
        """`feature_padding`/`label_padding`/`padding_length` stack
        variable-length samples by right-padding their first axis
        (reference: SampleToMiniBatch's featurePaddingParam /
        labelPaddingParam, dataset/PaddingParam.scala)."""
        assert partial in ("pad", "drop")
        self.batch_size = batch_size
        self.partial = partial
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.padding_length = padding_length

    def apply(self, it):
        while True:
            group = list(itertools.islice(it, self.batch_size))
            if not group:
                return
            if len(group) < self.batch_size and self.partial == "drop":
                return
            yield MiniBatch.from_samples(
                group, pad_to=self.batch_size,
                feature_padding=self.feature_padding,
                label_padding=self.label_padding,
                padding_length=self.padding_length)
