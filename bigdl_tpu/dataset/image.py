"""Image pipeline transforms.

Reference parity: dataset/image/ — `BytesToGreyImg`, `GreyImgNormalizer`,
`GreyImgToSample`, `BGRImgNormalizer`, `BGRImgCropper`, `HFlip`,
`ColorJitter`, `Lighting`, `BGRImgRdmCropper`, `BGRImgToSample`.

All transforms operate on `Sample`s whose feature is an HWC float numpy
array (TPU-first: channels-last throughout; the reference is HWC on the
wire and CHW at the tensor layer).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class GreyImgNormalizer(Transformer):
    """(x - mean) / std on single-channel images
    (reference: dataset/image/GreyImgNormalizer.scala)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = float(mean), float(std)

    def apply(self, it):
        for s in it:
            yield Sample((s.feature - self.mean) / self.std, s.label)


class BGRImgNormalizer(Transformer):
    """Per-channel normalize (reference: dataset/image/BGRImgNormalizer.scala)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, it):
        for s in it:
            yield Sample((s.feature - self.mean) / self.std, s.label)


class HFlip(Transformer):
    """Random horizontal flip (reference: dataset/image/HFlip.scala)."""

    def __init__(self, threshold: float = 0.5, seed: int = 1):
        self.threshold = threshold
        self._rng = np.random.RandomState(seed)

    def apply(self, it):
        for s in it:
            if self._rng.rand() < self.threshold:
                yield Sample(np.ascontiguousarray(s.feature[:, ::-1]), s.label)
            else:
                yield s


class CenterCrop(Transformer):
    """Deterministic center crop (reference: BGRImgCropper CropCenter)."""

    def __init__(self, crop_h: int, crop_w: int):
        self.crop_h, self.crop_w = crop_h, crop_w

    def apply(self, it):
        for s in it:
            h, w = s.feature.shape[:2]
            y0 = (h - self.crop_h) // 2
            x0 = (w - self.crop_w) // 2
            yield Sample(s.feature[y0:y0 + self.crop_h, x0:x0 + self.crop_w],
                         s.label)


class RandomCrop(Transformer):
    """Random crop, optional zero padding first
    (reference: BGRImgRdmCropper; CIFAR recipe pads 4 then crops 32)."""

    def __init__(self, crop_h: int, crop_w: int, padding: int = 0, seed: int = 1):
        self.crop_h, self.crop_w, self.padding = crop_h, crop_w, padding
        self._rng = np.random.RandomState(seed)

    def apply(self, it):
        for s in it:
            img = s.feature
            if self.padding:
                p = self.padding
                img = np.pad(img, ((p, p), (p, p), (0, 0)))
            h, w = img.shape[:2]
            y0 = self._rng.randint(0, h - self.crop_h + 1)
            x0 = self._rng.randint(0, w - self.crop_w + 1)
            yield Sample(img[y0:y0 + self.crop_h, x0:x0 + self.crop_w], s.label)


class RandomResizedCrop(Transformer):
    """Scale-and-aspect-jittered crop resized to a fixed size — the
    reference's Inception/ResNet ImageNet augmentation
    (dataset/image/BGRImgRdmCropper + resize)."""

    def __init__(self, size: int, min_area: float = 0.08, seed: int = 1):
        self.size = size
        self.min_area = min_area
        self._rng = np.random.RandomState(seed)

    def _resize(self, img, size):
        # nearest-neighbor resize in pure numpy (no cv2 in the image)
        h, w = img.shape[:2]
        ys = (np.arange(size) * (h / size)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(size) * (w / size)).astype(np.int64).clip(0, w - 1)
        return img[ys][:, xs]

    def apply(self, it):
        for s in it:
            img = s.feature
            h, w = img.shape[:2]
            area = h * w
            for _ in range(10):
                target = self._rng.uniform(self.min_area, 1.0) * area
                ratio = self._rng.uniform(3.0 / 4.0, 4.0 / 3.0)
                ch = int(round(np.sqrt(target / ratio)))
                cw = int(round(np.sqrt(target * ratio)))
                if ch <= h and cw <= w:
                    y0 = self._rng.randint(0, h - ch + 1)
                    x0 = self._rng.randint(0, w - cw + 1)
                    crop = img[y0:y0 + ch, x0:x0 + cw]
                    break
            else:
                m = min(h, w)
                crop = img[(h - m) // 2:(h + m) // 2, (w - m) // 2:(w + m) // 2]
            yield Sample(self._resize(crop, self.size), s.label)


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (reference: dataset/image/ColorJitter.scala)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 1):
        self.brightness, self.contrast, self.saturation = brightness, contrast, saturation
        self._rng = np.random.RandomState(seed)

    def _jitter(self, img):
        ops = []
        if self.brightness:
            a = 1.0 + self._rng.uniform(-self.brightness, self.brightness)
            ops.append(lambda x: x * a)
        if self.contrast:
            c = 1.0 + self._rng.uniform(-self.contrast, self.contrast)
            ops.append(lambda x: (x - x.mean()) * c + x.mean())
        if self.saturation:
            sa = 1.0 + self._rng.uniform(-self.saturation, self.saturation)

            def sat(x, sa=sa):
                grey = x.mean(axis=-1, keepdims=True)
                return grey + (x - grey) * sa

            ops.append(sat)
        self._rng.shuffle(ops)
        for op in ops:
            img = op(img)
        return img

    def apply(self, it):
        for s in it:
            yield Sample(self._jitter(s.feature.astype(np.float32)), s.label)


class Lighting(Transformer):
    """AlexNet-style PCA lighting noise (reference: dataset/image/Lighting.scala).
    Eigen-decomposition values are the standard ImageNet RGB ones."""

    EIGVAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.asarray([
        [-0.5675, 0.7192, 0.4009],
        [-0.5808, -0.0045, -0.8140],
        [-0.5836, -0.6948, 0.4203],
    ], np.float32)

    def __init__(self, alphastd: float = 0.1, seed: int = 1):
        self.alphastd = alphastd
        self._rng = np.random.RandomState(seed)

    def apply(self, it):
        for s in it:
            alpha = self._rng.normal(0, self.alphastd, 3).astype(np.float32)
            shift = (self.EIGVEC * alpha * self.EIGVAL).sum(axis=1)
            yield Sample(s.feature + shift, s.label)
