"""Vision image pipeline — ImageFrame / ImageFeature.

Reference parity: transform/vision/image/ — `ImageFrame` (collection
abstraction over images), `ImageFeature` (per-image dict of image +
metadata + label), `FeatureTransformer` (per-image transform with error
isolation), and the OpenCV-backed augmentation set (`Resize`,
`CenterCrop`, `RandomCrop`, `HFlip`/`RandomTransformer`, `Brightness`,
`Contrast`, `Saturation`, `ChannelNormalize`, `MatToTensor`,
`ImageFrameToSample`).

TPU-first redesign: images are numpy `float32` HWC arrays on the host
(no OpenCV `Mat` — numpy *is* the host tensor type here), transforms are
pure per-feature functions lifted over iterators, and the terminal
`ImageFrameToSample` hands off to the same `Sample`/`MiniBatch` batcher
the rest of the data plane uses, so device transfer happens once per
batch at the jit boundary.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class ImageFeature(dict):
    """Per-image record (reference: transform/vision/image/ImageFeature.scala).

    Keys mirror the reference's conventions: ``image`` (HWC float32),
    ``bytes`` (raw encoded/packed bytes), ``label``, ``uri``,
    ``original_size``, ``is_valid``.
    """

    IMAGE = "image"
    BYTES = "bytes"
    LABEL = "label"
    URI = "uri"
    ORIGINAL_SIZE = "original_size"
    VALID = "is_valid"

    def __init__(self, image=None, label=None, uri: Optional[str] = None,
                 **kw):
        super().__init__(**kw)
        if image is not None:
            img = np.asarray(image)
            self[self.IMAGE] = img
            self[self.ORIGINAL_SIZE] = img.shape
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri
        self.setdefault(self.VALID, True)

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @property
    def is_valid(self) -> bool:
        return bool(self.get(self.VALID, False))

    def get_size(self):
        """(height, width, channels) of the current image."""
        return self[self.IMAGE].shape

    def to_sample(self) -> Sample:
        return Sample(self[self.IMAGE], self.get(self.LABEL))


class FeatureTransformer(Transformer):
    """Per-image transform with error isolation
    (reference: vision/image/FeatureTransformer.scala — a failing
    transform marks the feature invalid instead of killing the job).

    Subclasses override ``transform_image`` (ndarray → ndarray) or, for
    transforms touching metadata, ``transform_feature``.
    """

    def transform_image(self, img: np.ndarray) -> np.ndarray:
        return img

    def transform_feature(self, feature: ImageFeature) -> ImageFeature:
        feature[ImageFeature.IMAGE] = self.transform_image(feature.image)
        return feature

    def apply(self, it: Iterator) -> Iterator:
        for feature in it:
            try:
                yield self.transform_feature(feature)
            except Exception as e:
                # isolate the bad feature but leave a trail — a systematic
                # misconfiguration would otherwise silently empty the set
                # (reference: FeatureTransformer logs on invalidation)
                feature[ImageFeature.VALID] = False
                feature["error"] = f"{type(self).__name__}: {e}"
                logging.getLogger(__name__).warning(
                    "%s failed on feature %s: %s", type(self).__name__,
                    feature.get(ImageFeature.URI, "<in-memory>"), e)
                yield feature

    # `a -> b` composition of the reference keeps working via `>>`
    # (inherited from Transformer).


# ------------------------------------------------------------- geometric

def _bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize, align_corners=False convention — native (C++)
    when the dataplane is available (12x the numpy path per core),
    numpy otherwise; both produce identical values."""
    h, w = img.shape[:2]
    if img.ndim == 2:
        img = img[:, :, None]
    if (h, w) == (out_h, out_w):
        return img.astype(np.float32, copy=False)
    from bigdl_tpu.dataset import native as _native

    fast = _native.resize_bilinear(img, out_h, out_w)
    if fast is not None:
        return fast
    ys = (np.arange(out_h, dtype=np.float32) + 0.5) * (h / out_h) - 0.5
    xs = (np.arange(out_w, dtype=np.float32) + 0.5) * (w / out_w) - 0.5
    y0 = np.clip(np.floor(ys), 0, h - 1).astype(np.int64)
    x0 = np.clip(np.floor(xs), 0, w - 1).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    img = img.astype(np.float32, copy=False)
    row0, row1 = img[y0], img[y1]
    top = row0[:, x0] * (1 - wx) + row0[:, x1] * wx
    bot = row1[:, x0] * (1 - wx) + row1[:, x1] * wx
    return top * (1 - wy) + bot * wy


class Resize(FeatureTransformer):
    """(reference: vision/image/augmentation/Resize.scala)"""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform_image(self, img):
        return _bilinear_resize(img, self.h, self.w)


class AspectScale(FeatureTransformer):
    """Scale the short side to `min_size`, cap the long side
    (reference: vision/image/augmentation/AspectScale.scala)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size, self.max_size = min_size, max_size

    def transform_image(self, img):
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        scale = min(self.min_size / short, self.max_size / long)
        return _bilinear_resize(img, int(round(h * scale)),
                                int(round(w * scale)))


class CenterCrop(FeatureTransformer):
    """(reference: vision/image/augmentation/CenterCrop.scala)"""

    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = crop_h, crop_w

    def transform_image(self, img):
        h, w = img.shape[:2]
        if h < self.h or w < self.w:
            raise ValueError(
                f"CenterCrop({self.h}, {self.w}): image {h}x{w} is smaller "
                f"than the crop — Resize/AspectScale first")
        y = (h - self.h) // 2
        x = (w - self.w) // 2
        return img[y:y + self.h, x:x + self.w]


class RandomCrop(FeatureTransformer):
    """(reference: vision/image/augmentation/RandomCrop.scala)"""

    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.h, self.w = crop_h, crop_w
        self.rng = np.random.default_rng(seed)

    def transform_image(self, img):
        h, w = img.shape[:2]
        if h < self.h or w < self.w:
            raise ValueError(
                f"RandomCrop({self.h}, {self.w}): image {h}x{w} is smaller "
                f"than the crop — Resize/AspectScale first")
        y = int(self.rng.integers(0, h - self.h + 1))
        x = int(self.rng.integers(0, w - self.w + 1))
        return img[y:y + self.h, x:x + self.w]


class HFlip(FeatureTransformer):
    """(reference: vision/image/augmentation/HFlip.scala)"""

    def transform_image(self, img):
        return img[:, ::-1]


class RandomTransformer(FeatureTransformer):
    """Apply `inner` with probability p
    (reference: vision/image/augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 seed: Optional[int] = None):
        self.inner, self.prob = inner, prob
        self.rng = np.random.default_rng(seed)

    def transform_feature(self, feature):
        if self.rng.random() < self.prob:
            return self.inner.transform_feature(feature)
        return feature


# ------------------------------------------------------------ photometric

class Brightness(FeatureTransformer):
    """Add a uniform delta (reference: augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform_image(self, img):
        return img + np.float32(self.rng.uniform(self.low, self.high))


class Contrast(FeatureTransformer):
    """Scale around zero (reference: augmentation/Contrast.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform_image(self, img):
        return img * np.float32(self.rng.uniform(self.low, self.high))


class Saturation(FeatureTransformer):
    """Blend with per-pixel grey (reference: augmentation/Saturation.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.default_rng(seed)

    def transform_image(self, img):
        alpha = np.float32(self.rng.uniform(self.low, self.high))
        grey = img.mean(axis=2, keepdims=True)
        return grey + alpha * (img - grey)


class ChannelNormalize(FeatureTransformer):
    """(reference: augmentation/ChannelNormalize.scala)"""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform_image(self, img):
        return (img - self.mean) / self.std


class PixelNormalizer(FeatureTransformer):
    """Subtract a full per-pixel mean image
    (reference: augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform_image(self, img):
        return img - self.means.reshape(img.shape)


class MatToTensor(FeatureTransformer):
    """Finalize dtype/layout (reference: vision/image/MatToTensor.scala —
    there it converts OpenCV Mat → Tensor; here it pins float32 HWC,
    optionally transposing to CHW for torch-convention consumers)."""

    def __init__(self, to_chw: bool = False):
        self.to_chw = to_chw

    def transform_image(self, img):
        img = np.ascontiguousarray(img, np.float32)
        return img.transpose(2, 0, 1) if self.to_chw else img


class ImageFrameToSample(Transformer):
    """Terminal stage: ImageFeature → Sample, dropping invalid features
    (reference: vision/image/ImageFrameToSample.scala)."""

    def apply(self, it: Iterator) -> Iterator:
        for feature in it:
            if feature.is_valid:
                yield feature.to_sample()


# -------------------------------------------------------------- ImageFrame

class ImageFrame:
    """Collection of ImageFeatures
    (reference: transform/vision/image/ImageFrame.scala).

    `LocalImageFrame` holds a list; the distributed variant of the
    reference (RDD-backed) maps to per-host sharded loading in this
    framework (dataset/dataset.py DistributedDataSet) — an ImageFrame is
    always process-local, the mesh dimension lives in the DataSet layer.
    """

    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    # reference: ImageFrame.read(path, ...)
    @staticmethod
    def read(path: str, with_label: bool = False) -> "ImageFrame":
        """Read `.npy` images (and `<name>.label` ints) from a directory.

        The reference reads JPEGs via OpenCV; this image has no JPEG
        codec, so the on-disk interchange format is npy (the native data
        plane covers IDX/CIFAR binary formats)."""
        feats = []
        for fname in sorted(os.listdir(path)):
            if not fname.endswith(".npy"):
                continue
            img = np.load(os.path.join(path, fname)).astype(np.float32)
            label = None
            lpath = os.path.join(path, fname[:-4] + ".label")
            if with_label and os.path.exists(lpath):
                with open(lpath) as f:
                    label = int(f.read().strip())
            feats.append(ImageFeature(img, label=label, uri=fname))
        return ImageFrame(feats)

    @staticmethod
    def from_arrays(images: np.ndarray,
                    labels: Optional[np.ndarray] = None) -> "ImageFrame":
        feats = [ImageFeature(images[i],
                              label=None if labels is None else labels[i])
                 for i in range(len(images))]
        return ImageFrame(feats)

    def transform(self, transformer: Transformer) -> "ImageFrame":
        """Apply a (chain of) FeatureTransformer(s), materialized
        (reference: ImageFrame.transform)."""
        out = list(transformer(iter(self.features)))
        return ImageFrame(out)

    # reference alias: frame -> transformer
    __rshift__ = transform

    def to_samples(self) -> List[Sample]:
        return [f.to_sample() for f in self.features if f.is_valid]

    def __len__(self):
        return len(self.features)

    def __iter__(self):
        return iter(self.features)
