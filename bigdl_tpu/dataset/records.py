"""BDLS sharded record files — the disk-resident image dataset path.

Reference parity: the reference feeds ImageNet-scale training from
Hadoop sequence files partitioned across Spark executors
(dataset/image/ tooling; SURVEY.md §2.4 + §7 "input pipeline
throughput"). The TPU-era equivalent is sharded fixed-record files on
local disk / network storage, mmap()ed and streamed by the native
dataplane's worker threads (native/dataplane.cpp) so the host keeps the
chip fed without materializing the dataset in RAM.

Format (one shard): 32-byte header
    magic "BDLS" | u32 version=1 | u64 n | u32 h | u32 w | u32 c | u32 0
then n records of [label i32 LE][h*w*c u8 HWC image].

Shards are written `{prefix}-{i:05d}-of-{k:05d}.bdls`; readers accept a
directory, a glob, or an explicit list.
"""

from __future__ import annotations

import glob as _glob
import os
import struct
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch

_HDR = struct.Struct("<4sIQIIII")
MAGIC = b"BDLS"
VERSION = 1


def write_shards(images: np.ndarray, labels: np.ndarray, out_dir: str,
                 num_shards: int = 1, prefix: str = "data") -> List[str]:
    """Write (n,h,w,c) u8 images + int labels into BDLS shards."""
    images = np.ascontiguousarray(images, np.uint8)
    if images.ndim == 3:
        images = images[..., None]
    labels = np.asarray(labels, np.int32)
    n, h, w, c = images.shape
    assert len(labels) == n, (len(labels), n)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    bounds = np.linspace(0, n, num_shards + 1).astype(np.int64)
    for s in range(num_shards):
        lo, hi = int(bounds[s]), int(bounds[s + 1])
        path = os.path.join(
            out_dir, f"{prefix}-{s:05d}-of-{num_shards:05d}.bdls")
        with open(path, "wb") as f:
            f.write(_HDR.pack(MAGIC, VERSION, hi - lo, h, w, c, 0))
            # interleave labels+images in one contiguous buffer per
            # shard (records are fixed-size; one write syscall)
            rec = np.zeros((hi - lo, 4 + h * w * c), np.uint8)
            rec[:, :4] = labels[lo:hi].astype("<i4").view(np.uint8) \
                .reshape(hi - lo, 4)
            rec[:, 4:] = images[lo:hi].reshape(hi - lo, -1)
            f.write(rec.tobytes())
        paths.append(path)
    return paths


def read_header(path: str) -> Tuple[int, int, int, int]:
    """(n, h, w, c) of one shard."""
    with open(path, "rb") as f:
        raw = f.read(_HDR.size)
    magic, version, n, h, w, c, _ = _HDR.unpack(raw)
    if magic != MAGIC or version != VERSION:
        raise ValueError(f"{path}: not a BDLS v{VERSION} shard")
    return int(n), int(h), int(w), int(c)


def resolve_shards(spec, pattern: str = "*.bdls") -> List[str]:
    """Directory | glob | list of paths → sorted shard list (shared by
    the BDLS and TFRecord datasets; `pattern` is the in-directory
    glob)."""
    if isinstance(spec, (list, tuple)):
        paths = [os.fspath(p) for p in spec]
    elif os.path.isdir(spec):
        paths = _glob.glob(os.path.join(spec, pattern))
    else:
        paths = _glob.glob(spec)
    if not paths:
        raise FileNotFoundError(f"no {pattern} shards match {spec!r}")
    return sorted(paths)


class RecordFileDataSet(AbstractDataSet):
    """Disk-resident dataset streaming BDLS shards through the native
    dataplane (C++ mmap + worker threads; Python mmap fallback).

    train=True yields augmented, normalized MiniBatches forever (epoch
    reshuffles inside the workers); train=False maps shards once, in
    order, normalized only.
    """

    def __init__(self, shards, batch_size: int, mean, std, pad: int = 0,
                 hflip: bool = False, n_threads: int = 4,
                 capacity: int = 3, seed: int = 0):
        from bigdl_tpu.dataset import native

        self.paths = resolve_shards(shards)
        self.batch_size = batch_size
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self._prefetcher = native.FilePrefetcher(
            self.paths, batch_size, mean, std, pad=pad, hflip=hflip,
            n_threads=n_threads, capacity=capacity, seed=seed)
        self.n = self._prefetcher.n
        self.shape = self._prefetcher.shape

    @property
    def native(self) -> bool:
        return self._prefetcher.native

    def size(self) -> int:
        return self.n

    def data(self, train: bool) -> Iterator:
        if train:
            def forever():
                while True:
                    img, lbl = self._prefetcher.next()
                    yield MiniBatch(img, lbl)
            return forever()

        def once():
            for path in self.paths:
                n, h, w, c = read_header(path)
                rec = 4 + h * w * c
                mm = np.memmap(path, np.uint8, mode="r",
                               offset=_HDR.size).reshape(n, rec)
                for i in range(0, n, self.batch_size):
                    chunk = np.asarray(mm[i:i + self.batch_size])
                    lbl = chunk[:, :4].copy().view("<i4")[:, 0]
                    img = chunk[:, 4:].reshape(-1, h, w, c)
                    yield MiniBatch(
                        (img.astype(np.float32) - self.mean) / self.std,
                        lbl.astype(np.int32))
        return once()

    def close(self) -> None:
        self._prefetcher.close()
