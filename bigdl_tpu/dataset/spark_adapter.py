"""Optional Spark adapter.

Reference parity: the reference's entire L0 substrate is Spark — RDDs
carry the data, BlockManager carries the gradients (SURVEY.md §1). Here
Spark is deliberately OUT of the core (the TPU data plane is per-host
host-RAM + ICI collectives); this adapter is the bridge for users whose
data already lives in Spark: pull an RDD/DataFrame of (feature, label)
into this framework's `DataSet`, sharded per host.

pyspark is NOT a dependency — everything is duck-typed against the RDD
surface (`collect`, optionally `getNumPartitions`/`glom`) so plain lists
of rows and test fakes work identically.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet, LocalDataSet
from bigdl_tpu.dataset.sample import Sample

__all__ = ["rdd_to_dataset", "dataframe_to_dataset"]


def _to_sample(row: Any) -> Sample:
    if isinstance(row, Sample):
        return row
    if isinstance(row, dict):
        return Sample(np.asarray(row["features"]),
                      np.asarray(row["label"]))
    feature, label = row
    return Sample(np.asarray(feature), np.asarray(label))


def rdd_to_dataset(rdd: Any, process_id: Optional[int] = None,
                   num_processes: Optional[int] = None) -> LocalDataSet:
    """Materialize an RDD of (feature, label) rows / dicts / Samples into
    a LocalDataSet. In a multi-host job, pass this host's
    `jax.process_index()`/`jax.process_count()` (defaulted when jax is
    initialized) and each host keeps only its shard — mirroring the
    reference's partition-per-executor layout without Spark executors
    doing the training."""
    rows = rdd.collect() if hasattr(rdd, "collect") else list(rdd)
    if (process_id is None) != (num_processes is None):
        raise ValueError(
            "pass process_id and num_processes together (or neither, to "
            "read them from the jax process group)")
    if process_id is None:
        try:
            import jax

            process_id = jax.process_index()
            num_processes = jax.process_count()
        except Exception:
            process_id, num_processes = 0, 1
    if num_processes > 1:
        rows = rows[process_id::num_processes]
    return DataSet.array([_to_sample(r) for r in rows])


def dataframe_to_dataset(df: Any, features_col: str = "features",
                         label_col: str = "label", **kw) -> LocalDataSet:
    """Spark DataFrame → DataSet via its RDD of Rows (duck-typed: any
    object with `.select(...).rdd` or dict-like rows)."""
    if hasattr(df, "select"):
        rdd = df.select(features_col, label_col).rdd
        return rdd_to_dataset(rdd, **kw)
    # plain dict-of-columns (the estimator API's DataFrame stand-in)
    rows = list(zip(df[features_col], df[label_col]))
    return rdd_to_dataset(rows, **kw)
