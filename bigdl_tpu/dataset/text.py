"""Text pipeline.

Reference parity: dataset/text/ — `Dictionary`, `SentenceTokenizer`,
`SentenceBiPadding` (SENTENCESTART/SENTENCEEND markers),
`TextToLabeledSentence`, `LabeledSentenceToSample`, `LabeledSentence`.
Used by the reference's PTB language model and sentiment examples
(models/rnn/, example/languagemodel).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

SENTENCE_START = "SENTENCESTART"
SENTENCE_END = "SENTENCEEND"


class Dictionary:
    """Word ↔ index vocabulary (reference: dataset/text/Dictionary.scala).

    Keeps the `vocab_size` most frequent words; everything else maps to the
    unknown token (index = vocab_size, i.e. last).
    """

    def __init__(self, sentences: Optional[Sequence[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = Counter(w for s in sentences for w in s)
            if vocab_size is not None:
                common = counts.most_common(vocab_size)
            else:
                common = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
            for w, _ in common:
                self.add_word(w)

    @property
    def unk_index(self) -> int:
        """Index of the unknown-word bucket — always one past the known
        words, so it stays valid after later add_word() calls."""
        return len(self.index2word)

    def add_word(self, word: str) -> int:
        if word not in self.word2index:
            self.word2index[word] = len(self.index2word)
            self.index2word.append(word)
        return self.word2index[word]

    def index(self, word: str) -> int:
        return self.word2index.get(word, self.unk_index)

    def vocab_size(self) -> int:
        """Vocabulary size INCLUDING the unk bucket."""
        return len(self.index2word) + 1

    def __len__(self):
        return len(self.index2word)


class SentenceTokenizer(Transformer):
    """Lowercase word tokenizer (reference: dataset/text/SentenceTokenizer.scala)."""

    PATTERN = re.compile(r"[A-Za-z']+|[0-9]+|[^\sA-Za-z0-9]")

    def apply(self, it):
        for text in it:
            yield self.PATTERN.findall(text.lower())


class SentenceBiPadding(Transformer):
    """Wrap sentences with start/end markers
    (reference: dataset/text/SentenceBiPadding.scala)."""

    def apply(self, it):
        for words in it:
            yield [SENTENCE_START] + list(words) + [SENTENCE_END]


class TextToLabeledSentence(Transformer):
    """words → (input ids, next-word label ids) for LM training
    (reference: dataset/text/TextToLabeledSentence.scala)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply(self, it):
        for words in it:
            ids = np.asarray([self.dictionary.index(w) for w in words], np.int32)
            yield (ids[:-1], ids[1:])


class LabeledSentenceToSample(Transformer):
    """(data ids, label ids) → fixed-length Sample
    (reference: dataset/text/LabeledSentenceToSample.scala).

    Pads/truncates to `fixed_length` so shapes stay static under jit;
    padded label positions get `pad_label` (mask in the criterion).
    """

    def __init__(self, fixed_length: int, pad_data: int = 0, pad_label: int = 0):
        self.fixed_length = fixed_length
        self.pad_data = pad_data
        self.pad_label = pad_label

    def _fix(self, ids, pad):
        out = np.full((self.fixed_length,), pad, np.int32)
        n = min(len(ids), self.fixed_length)
        out[:n] = ids[:n]
        return out

    def apply(self, it):
        for data, label in it:
            yield Sample(self._fix(data, self.pad_data),
                         self._fix(label, self.pad_label))


def synthetic_next_token(n: int, vocab: int, seq: int, seed: int = 0):
    """Synthetic next-token LM Samples on a cyclic grammar: each sequence
    is (start + arange) % vocab, target is the input shifted by one —
    the stand-in for PTB used by the LM examples, the train CLI, and the
    LM tests (reference: example/languagemodel synthetic mode)."""
    import numpy as np

    from bigdl_tpu.dataset.sample import Sample

    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        start = rng.randint(0, vocab)
        s = (start + np.arange(seq + 1)) % vocab
        out.append(Sample(s[:-1].astype(np.int32), s[1:].astype(np.int32)))
    return out
