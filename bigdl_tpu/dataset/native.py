"""ctypes bindings for the native (C++) host data plane.

Reference parity: the reference backs its hot paths with native code
behind JNI (BigDL-core mkl/mkldnn/bigquant shared objects, SURVEY.md
§2.1). On TPU the device math belongs to XLA, so our native layer lives
where native still pays: the host input pipeline (native/dataplane.cpp —
threaded decode/augment/normalize + a prefetching ring buffer that keeps
the chips fed, SURVEY.md §7).

The library is compiled on first use with g++ (no pybind11 — plain C ABI
via ctypes) and cached under native/build/. Every entry point has a
pure-Python fallback so the package works without a toolchain:
`available()` reports which plane is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "dataplane.cpp")
_SO = os.path.join(_ROOT, "native", "build", "libbigdl_dataplane.so")

_lib = None
_lib_lock = threading.Lock()


def _build() -> Optional[str]:
    if not os.path.exists(_SRC):
        # prebuilt library without source (installed layout) — use as-is
        return _SO if os.path.exists(_SO) else None
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-pthread",
           "-shared", "-o", _SO, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return _SO


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.bdl_normalize_u8.argtypes = [u8p, f32p, ctypes.c_int64,
                                         ctypes.c_int, f32p, f32p,
                                         ctypes.c_int]
        lib.bdl_hflip.argtypes = [f32p, u8p] + [ctypes.c_int] * 4
        lib.bdl_shift_crop.argtypes = [f32p, f32p,
                                       ctypes.POINTER(ctypes.c_int),
                                       ctypes.POINTER(ctypes.c_int)] + \
            [ctypes.c_int] * 4
        lib.bdl_decode_idx_images.argtypes = [u8p, ctypes.c_int64, u8p,
                                              i64p, i64p, i64p]
        lib.bdl_decode_idx_images.restype = ctypes.c_int
        lib.bdl_decode_idx_labels.argtypes = [u8p, ctypes.c_int64, u8p,
                                              i64p]
        lib.bdl_decode_idx_labels.restype = ctypes.c_int
        lib.bdl_decode_cifar10.argtypes = [u8p, ctypes.c_int64, u8p, u8p,
                                           i64p]
        lib.bdl_decode_cifar10.restype = ctypes.c_int
        lib.bdl_prefetcher_create.argtypes = [
            u8p, i32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int, f32p, f32p]
        lib.bdl_prefetcher_create.restype = ctypes.c_void_p
        lib.bdl_prefetcher_next.argtypes = [ctypes.c_void_p, f32p, i32p]
        lib.bdl_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        try:
            lib.bdl_resize_bilinear.argtypes = [f32p, f32p] + \
                [ctypes.c_int] * 6
            lib._has_resize = True
        except AttributeError:
            lib._has_resize = False
        try:
            # newer symbols — a prebuilt .so from an older source tree
            # may lack them; the rest of the native plane still works
            lib.bdl_file_prefetcher_create.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, f32p, f32p, i64p,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.bdl_file_prefetcher_create.restype = ctypes.c_void_p
            lib.bdl_prefetcher_next_u8.argtypes = [ctypes.c_void_p, u8p,
                                                   i32p]
            lib._has_file_prefetcher = True
        except AttributeError:
            lib._has_file_prefetcher = False
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is (or can be) loaded."""
    return _load() is not None


def _u8(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f32(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _per_channel(vals, c, what) -> np.ndarray:
    """Validate/broadcast a per-channel vector to exactly c entries —
    the C++ side reads exactly c floats, so a short array would be an
    out-of-bounds read, not a broadcast."""
    arr = np.asarray(vals, np.float32).reshape(-1)
    if arr.size == 1:
        arr = np.full((c,), float(arr[0]), np.float32)
    if arr.size != c:
        raise ValueError(
            f"{what} has {arr.size} entries for {c} channels")
    return np.ascontiguousarray(arr)


def normalize_u8(images: np.ndarray, mean: Sequence[float],
                 std: Sequence[float], n_threads: int = 4) -> np.ndarray:
    """u8 (..., C) → f32 (x - mean[c]) / std[c]; native when possible."""
    images = np.ascontiguousarray(images, np.uint8)
    c = images.shape[-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    lib = _load()
    if lib is None:
        return (images.astype(np.float32) - mean) / std
    out = np.empty(images.shape, np.float32)
    lib.bdl_normalize_u8(_u8(images), _f32(out),
                         images.size // c, c, _f32(mean), _f32(std),
                         n_threads)
    return out


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int,
                    n_threads: int = 1) -> Optional[np.ndarray]:
    """f32 HWC bilinear resize (align_corners=False) in C++, or None
    when the native plane is unavailable (caller falls back to numpy —
    measured 12x slower per core for 256→224, PROFILE_r04)."""
    lib = _load()
    if lib is None or not getattr(lib, "_has_resize", False):
        return None
    img = np.ascontiguousarray(img, np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    out = np.empty((out_h, out_w, c), np.float32)
    lib.bdl_resize_bilinear(_f32(img), _f32(out), h, w, c, out_h, out_w,
                            n_threads)
    return out


def decode_idx_images(raw: bytes) -> np.ndarray:
    lib = _load()
    buf = np.frombuffer(raw, np.uint8)
    if lib is None:
        import struct
        magic, n, rows, cols = struct.unpack(">IIII", raw[:16])
        if magic != 2051:
            raise ValueError(f"bad IDX magic {magic}")
        return buf[16:16 + n * rows * cols].reshape(n, rows, cols).copy()
    n = ctypes.c_int64()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.bdl_decode_idx_images(_u8(buf), len(raw), None,
                                   ctypes.byref(n), ctypes.byref(rows),
                                   ctypes.byref(cols))
    if rc:
        raise ValueError(f"IDX image decode failed ({rc})")
    out = np.empty((n.value, rows.value, cols.value), np.uint8)
    lib.bdl_decode_idx_images(_u8(buf), len(raw), _u8(out),
                              ctypes.byref(n), ctypes.byref(rows),
                              ctypes.byref(cols))
    return out


def decode_idx_labels(raw: bytes) -> np.ndarray:
    lib = _load()
    buf = np.frombuffer(raw, np.uint8)
    if lib is None:
        import struct
        magic, n = struct.unpack(">II", raw[:8])
        if magic != 2049:
            raise ValueError(f"bad IDX magic {magic}")
        return buf[8:8 + n].copy()
    n = ctypes.c_int64()
    rc = lib.bdl_decode_idx_labels(_u8(buf), len(raw), None,
                                   ctypes.byref(n))
    if rc:
        raise ValueError(f"IDX label decode failed ({rc})")
    out = np.empty((n.value,), np.uint8)
    lib.bdl_decode_idx_labels(_u8(buf), len(raw), _u8(out),
                              ctypes.byref(n))
    return out


def decode_cifar10(raw: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 binary records → (images u8 NHWC, labels u8)."""
    lib = _load()
    buf = np.frombuffer(raw, np.uint8)
    rec = 1 + 3072
    if len(raw) % rec:
        raise ValueError(
            f"CIFAR decode failed: {len(raw)} bytes is not a whole "
            f"number of {rec}-byte records")
    if lib is None:
        n = len(raw) // rec
        recs = buf.reshape(n, rec)
        labels = recs[:, 0].copy()
        chw = recs[:, 1:].reshape(n, 3, 32, 32)
        return chw.transpose(0, 2, 3, 1).copy(), labels
    n = ctypes.c_int64()
    rc = lib.bdl_decode_cifar10(_u8(buf), len(raw), None, None,
                                ctypes.byref(n))
    if rc:
        raise ValueError(f"CIFAR decode failed ({rc})")
    images = np.empty((n.value, 32, 32, 3), np.uint8)
    labels = np.empty((n.value,), np.uint8)
    lib.bdl_decode_cifar10(_u8(buf), len(raw), _u8(images), _u8(labels),
                           ctypes.byref(n))
    return images, labels


class Prefetcher:
    """Multithreaded native batch producer over an in-memory u8 dataset.

    Yields (images f32 (B,H,W,C), labels i32 (B,)) batches: shuffled
    every epoch, normalized, optionally shift-crop/hflip augmented —
    produced by C++ worker threads into a bounded ring buffer. Falls
    back to a Python thread if the native library is unavailable
    (`.native` tells which plane is running).
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mean: Sequence[float],
                 std: Sequence[float], pad: int = 0, hflip: bool = False,
                 n_threads: int = 2, capacity: int = 4, seed: int = 0):
        self.images = np.ascontiguousarray(images, np.uint8)
        if self.images.ndim == 3:  # greyscale → add channel dim
            self.images = self.images[..., None]
        self.labels = np.ascontiguousarray(labels, np.int32)
        self.batch_size = batch_size
        n, h, w, c = self.images.shape
        self.shape = (h, w, c)
        self.mean = _per_channel(mean, c, "mean")
        self.std = _per_channel(std, c, "std")
        self.pad, self.hflip = pad, hflip
        self._lib = _load()
        self.native = self._lib is not None
        if self.native:
            self._handle = self._lib.bdl_prefetcher_create(
                _u8(self.images), _i32(self.labels), n, h, w, c,
                batch_size, capacity, n_threads, seed, pad,
                1 if hflip else 0, _f32(self.mean), _f32(self.std))
        else:
            import queue

            self._q = queue.Queue(maxsize=capacity)
            self._stop = threading.Event()
            self._rng = np.random.RandomState(seed)
            self._t = threading.Thread(target=self._py_worker, daemon=True)
            self._t.start()

    # ---- python fallback -------------------------------------------------
    def _py_worker(self):
        n = len(self.labels)
        h, w, c = self.shape
        while not self._stop.is_set():
            order = self._rng.permutation(n)
            for i in range(0, n - self.batch_size + 1, self.batch_size):
                if self._stop.is_set():
                    return
                idx = order[i:i + self.batch_size]
                img = (self.images[idx].astype(np.float32) - self.mean) \
                    / self.std
                if self.pad:
                    out = np.zeros_like(img)
                    for j in range(len(idx)):
                        dy, dx = self._rng.randint(-self.pad, self.pad + 1,
                                                   2)
                        y0, y1 = max(0, dy), min(h, h + dy)
                        x0, x1 = max(0, dx), min(w, w + dx)
                        out[j, y0:y1, x0:x1] = \
                            img[j, y0 - dy:y1 - dy, x0 - dx:x1 - dx]
                    img = out
                if self.hflip:
                    flips = self._rng.rand(len(idx)) < 0.5
                    img[flips] = img[flips, :, ::-1]
                self._q.put((img, self.labels[idx].copy()))

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        h, w, c = self.shape
        if self.native:
            if getattr(self, "_handle", None) is None:
                raise RuntimeError("Prefetcher used after close()")
            img = np.empty((self.batch_size, h, w, c), np.float32)
            lbl = np.empty((self.batch_size,), np.int32)
            self._lib.bdl_prefetcher_next(self._handle, _f32(img),
                                          _i32(lbl))
            return img, lbl
        return self._q.get()

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self.native:
            if getattr(self, "_handle", None):
                self._lib.bdl_prefetcher_destroy(self._handle)
                self._handle = None
        else:
            self._stop.set()
            try:
                while True:
                    self._q.get_nowait()
            except Exception:
                pass

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass


class FilePrefetcher:
    """Disk-resident batch producer over BDLS shard files
    (dataset/records.py format). The native plane mmap()s every shard
    and streams records through C++ worker threads — datasets larger
    than RAM ride the OS page cache. Python fallback uses np.memmap
    with one producer thread (`.native` tells which plane runs)."""

    def __init__(self, paths, batch_size: int, mean: Sequence[float],
                 std: Sequence[float], pad: int = 0, hflip: bool = False,
                 n_threads: int = 4, capacity: int = 3, seed: int = 0,
                 out_dtype: str = "f32"):
        """out_dtype="u8" skips host normalization and yields raw u8
        batches — 4x less host->device wire; normalize on device (the
        TPU-idiomatic split: bytes over the wire, elementwise math on
        the chip where it is free)."""
        from bigdl_tpu.dataset.records import read_header

        self.paths = [os.fspath(p) for p in paths]
        self.batch_size = batch_size
        # channel count from the first shard header (Python-side read;
        # the native create would read exactly c floats of mean/std, so
        # validation must happen first)
        _, _, _, chans = read_header(self.paths[0])
        self.mean = _per_channel(mean, chans, "mean")
        self.std = _per_channel(std, chans, "std")
        self.pad, self.hflip = pad, hflip
        assert out_dtype in ("f32", "u8"), out_dtype
        self.out_dtype = out_dtype
        self._lib = _load()
        self.native = (self._lib is not None and
                       getattr(self._lib, "_has_file_prefetcher", False))
        if self.native:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths])
            n = ctypes.c_int64()
            h = ctypes.c_int()
            w = ctypes.c_int()
            c = ctypes.c_int()
            self._handle = self._lib.bdl_file_prefetcher_create(
                arr, len(self.paths), batch_size, capacity, n_threads,
                seed, pad, 1 if hflip else 0,
                1 if out_dtype == "u8" else 0, _f32(self.mean),
                _f32(self.std), ctypes.byref(n), ctypes.byref(h),
                ctypes.byref(w), ctypes.byref(c))
            if not self._handle:
                raise ValueError(
                    f"native shard open failed (bad/missing BDLS files "
                    f"or mismatched shapes): {self.paths[:3]}...")
            self.n = n.value
            self.shape = (h.value, w.value, c.value)
        else:
            from bigdl_tpu.dataset.records import read_header

            import queue

            metas = [read_header(p) for p in self.paths]
            if len({m[1:] for m in metas}) != 1:
                raise ValueError("shards disagree on (h, w, c)")
            self.n = sum(m[0] for m in metas)
            self.shape = metas[0][1:]
            h, w, c = self.shape
            rec = 4 + h * w * c
            self._maps = []
            self._starts = [0]
            for p, m in zip(self.paths, metas):
                self._maps.append(np.memmap(p, np.uint8, mode="r",
                                            offset=32).reshape(m[0], rec))
                self._starts.append(self._starts[-1] + m[0])
            self._q = queue.Queue(maxsize=capacity)
            self._stop = threading.Event()
            self._rng = np.random.RandomState(seed)
            self._t = threading.Thread(target=self._py_worker, daemon=True)
            self._t.start()

    # ---- python fallback ------------------------------------------------
    def _record_batch(self, idx):
        h, w, c = self.shape
        starts = np.asarray(self._starts)
        out = np.empty((len(idx), 4 + h * w * c), np.uint8)
        for j, i in enumerate(idx):
            s = int(np.searchsorted(starts, i, side="right")) - 1
            out[j] = self._maps[s][i - starts[s]]
        lbl = out[:, :4].copy().view("<i4")[:, 0].astype(np.int32)
        img = out[:, 4:].reshape(len(idx), h, w, c)
        return img, lbl

    def _py_worker(self):
        h, w, c = self.shape
        while not self._stop.is_set():
            order = self._rng.permutation(self.n)
            for i in range(0, self.n - self.batch_size + 1,
                           self.batch_size):
                if self._stop.is_set():
                    return
                raw, lbl = self._record_batch(order[i:i + self.batch_size])
                img = raw.copy() if self.out_dtype == "u8" else \
                    (raw.astype(np.float32) - self.mean) / self.std
                if self.pad:
                    if self.out_dtype == "u8":
                        # mean-byte fill: borders normalize to 0.0 on
                        # device, matching the f32 plane's zero-fill
                        shifted = np.empty_like(img)
                        shifted[:] = np.clip(self.mean + 0.5, 0,
                                             255).astype(np.uint8)
                    else:
                        shifted = np.zeros_like(img)
                    for j in range(len(img)):
                        dy, dx = self._rng.randint(-self.pad,
                                                   self.pad + 1, 2)
                        y0, y1 = max(0, dy), min(h, h + dy)
                        x0, x1 = max(0, dx), min(w, w + dx)
                        shifted[j, y0:y1, x0:x1] = \
                            img[j, y0 - dy:y1 - dy, x0 - dx:x1 - dx]
                    img = shifted
                if self.hflip:
                    flips = self._rng.rand(len(img)) < 0.5
                    img[flips] = img[flips, :, ::-1]
                self._q.put((img, lbl))

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        h, w, c = self.shape
        if self.native:
            if getattr(self, "_handle", None) is None:
                raise RuntimeError("FilePrefetcher used after close()")
            lbl = np.empty((self.batch_size,), np.int32)
            if self.out_dtype == "u8":
                img = np.empty((self.batch_size, h, w, c), np.uint8)
                self._lib.bdl_prefetcher_next_u8(self._handle, _u8(img),
                                                 _i32(lbl))
            else:
                img = np.empty((self.batch_size, h, w, c), np.float32)
                self._lib.bdl_prefetcher_next(self._handle, _f32(img),
                                              _i32(lbl))
            return img, lbl
        if self._stop.is_set():
            # mirror the native-path guard; without it get() would
            # block forever on a queue whose producer has exited
            raise RuntimeError("FilePrefetcher used after close()")
        return self._q.get()

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self.native:
            if getattr(self, "_handle", None):
                self._lib.bdl_prefetcher_destroy(self._handle)
                self._handle = None
        else:
            self._stop.set()
            try:
                while True:
                    self._q.get_nowait()
            except Exception:
                pass

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
