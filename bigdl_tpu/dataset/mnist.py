"""MNIST loader.

Reference parity: models/lenet/Utils.scala `load` (IDX ubyte format:
big-endian magic 2051/2049, train-images-idx3-ubyte etc.) and the
`BytesToGreyImg >> GreyImgNormalizer >> GreyImgToSample` chain
(models/lenet/Train.scala).

`load_mnist(path)` reads the standard IDX files if present; tests and the
perf harness use `synthetic_mnist` (no network in this environment).
"""

from __future__ import annotations

import gzip
import os
from typing import List, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN = 0.13066047740239436 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255


def _open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    """Decode via the native (C++) data plane when available
    (bigdl_tpu/dataset/native.py; pure-Python fallback inside)."""
    from bigdl_tpu.dataset import native

    with _open(path) as f:
        return native.decode_idx_images(f.read())


def read_idx_labels(path: str) -> np.ndarray:
    from bigdl_tpu.dataset import native

    with _open(path) as f:
        return native.decode_idx_labels(f.read())


def _find(folder: str, stem: str) -> str:
    for suffix in ("", ".gz"):
        p = os.path.join(folder, stem + suffix)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(f"{stem} not found under {folder}")


def load_mnist(folder: str, train: bool = True) -> List[Sample]:
    """Load IDX MNIST into normalized HWC float Samples with int labels."""
    stem = "train" if train else "t10k"
    images = read_idx_images(_find(folder, f"{stem}-images-idx3-ubyte"))
    labels = read_idx_labels(_find(folder, f"{stem}-labels-idx1-ubyte"))
    mean, std = (TRAIN_MEAN, TRAIN_STD) if train else (TEST_MEAN, TEST_STD)
    feats = (images.astype(np.float32) - mean) / std
    return [Sample(feats[i][..., None], np.int32(labels[i]))
            for i in range(len(labels))]


def synthetic_mnist(n: int = 512, seed: int = 0,
                    separable: bool = True) -> List[Sample]:
    """Synthetic stand-in with class-dependent structure so models can
    actually learn (each class gets a distinct bright patch pattern)."""
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n):
        label = rng.randint(0, 10)
        img = rng.randn(28, 28).astype(np.float32) * 0.25
        if separable:
            r, c = divmod(label, 4)
            img[4 + r * 7:11 + r * 7, 2 + c * 6:9 + c * 6] += 2.0
        samples.append(Sample(img[..., None], np.int32(label)))
    return samples
