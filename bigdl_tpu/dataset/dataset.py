"""DataSet abstractions.

Reference parity: dataset/DataSet.scala — `LocalDataSet` (in-memory array,
`data(train=)` iterator contract, per-epoch shuffle), `DataSet.array(...)`
factories; `CachedDistriDataSet`'s role (partitioned, cached, per-partition
shuffle) maps to `ShardedDataSet`: deterministic per-host sharding for
multi-host TPU training — each process owns `indices[process_id::count]`,
mirroring "Spark only partitions data" (SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import Transformer


class AbstractDataSet:
    """`data(train)` iterator + `size()` (reference: dataset/DataSet.scala)."""

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    # NOTE: no shuffle() method — the reference's shuffle-before-epoch
    # contract is inherent in data(train=True), which derives each
    # epoch's permutation from (seed, epoch) statelessly so checkpoint
    # resume can replay the schedule exactly.

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        """Attach a transformer chain (the reference's `dataset -> transformer`)."""
        return TransformedDataSet(self, transformer)

    def __rshift__(self, transformer: Transformer) -> "TransformedDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """In-memory dataset (reference: dataset/LocalArrayDataSet).

    train=True iterates forever over reshuffled epochs (the reference's
    looped iterator contract); train=False iterates once in order.
    """

    def __init__(self, elements: Sequence, seed: int = 1):
        self.elements = list(elements)
        self.seed = seed

    def size(self) -> int:
        return len(self.elements)

    def data(self, train: bool) -> Iterator:
        if not train:
            yield from self.elements
            return
        # Stateless replay: every data(train=True) call restarts the
        # identical epoch sequence — each epoch's permutation is derived
        # from (seed, epoch) with an iterator-local epoch counter, never
        # from instance state. This is what makes checkpoint resume's
        # fast-forward (skip=neval batches) land on the same data even
        # after a previous iterator already consumed epochs in-process
        # (DistriOptimizer retry path).
        epoch = 0
        while True:
            perm = np.random.RandomState(
                self.seed + epoch).permutation(len(self.elements))
            for i in perm:
                yield self.elements[i]
            epoch += 1


class ShardedDataSet(AbstractDataSet):
    """Deterministic per-process shard of a dataset for multi-host training.

    Reference parity: dataset/DataSet.scala#CachedDistriDataSet — there
    Spark partitions the RDD and each executor iterates its cached
    partition with a local shuffle. Here each TPU host process takes the
    strided shard `indices[pid::nproc]` of a common permutation derived
    from a shared seed + epoch, so hosts stay in lockstep without any
    coordination traffic.
    """

    def __init__(self, elements: Sequence, process_id: Optional[int] = None,
                 process_count: Optional[int] = None, seed: int = 1):
        import jax

        self.elements = list(elements)
        self.pid = jax.process_index() if process_id is None else process_id
        self.nproc = jax.process_count() if process_count is None else process_count
        self.seed = seed

    def size(self) -> int:
        # per-shard size (the reference reports partition-local counts too)
        return len(range(self.pid, len(self.elements), self.nproc))

    def total_size(self) -> int:
        return len(self.elements)

    def data(self, train: bool) -> Iterator:
        if not train:
            for i in range(self.pid, len(self.elements), self.nproc):
                yield self.elements[i]
            return
        # iterator-local epoch: every data(train=True) call replays the
        # identical schedule (same rationale as LocalDataSet.data) — and
        # the permutation stays host-independent, so hosts remain in
        # lockstep after any host's in-process retry.
        epoch = 0
        while True:
            # same permutation on every host: seed ⊕ epoch
            perm = np.random.RandomState(self.seed + epoch).permutation(
                len(self.elements))
            shard = perm[self.pid::self.nproc]
            for i in shard:
                yield self.elements[i]
            epoch += 1


class TransformedDataSet(AbstractDataSet):
    """A dataset with a transformer chain attached."""

    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        from bigdl_tpu.dataset.transformer import ChainedTransformer

        return TransformedDataSet(
            self.base, ChainedTransformer(self.transformer, transformer))

    def data(self, train: bool) -> Iterator:
        return self.transformer(self.base.data(train))


class DataSet:
    """Factory namespace (reference: dataset/DataSet object)."""

    @staticmethod
    def array(elements: Sequence, seed: int = 1) -> LocalDataSet:
        return LocalDataSet(elements, seed=seed)

    @staticmethod
    def sharded(elements: Sequence, **kw) -> ShardedDataSet:
        return ShardedDataSet(elements, **kw)


class PrefetchDataSet(AbstractDataSet):
    """Dataset backed by the native (C++) prefetcher.

    Wraps `bigdl_tpu.dataset.native.Prefetcher` — worker threads decode,
    augment, and normalize batches into a ring buffer off the training
    thread, the TPU-era counterpart of the reference's Spark executors
    feeding partitions (SURVEY.md §2.4 TPU equivalent / §7 input-pipeline
    hard part). train=True streams forever (epoch reshuffles happen in
    the workers); train=False iterates the raw arrays once, unaugmented.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mean, std, pad: int = 0,
                 hflip: bool = False, n_threads: int = 2,
                 capacity: int = 4, seed: int = 0):
        from bigdl_tpu.dataset import native

        self._prefetcher = native.Prefetcher(
            images, labels, batch_size, mean, std, pad=pad, hflip=hflip,
            n_threads=n_threads, capacity=capacity, seed=seed)
        self.images = self._prefetcher.images
        self.labels = self._prefetcher.labels
        self.batch_size = batch_size
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    @property
    def native(self) -> bool:
        return self._prefetcher.native

    def size(self) -> int:
        return len(self.labels)

    def data(self, train: bool) -> Iterator:
        if train:
            def forever():
                while True:
                    img, lbl = self._prefetcher.next()
                    yield MiniBatch(img, lbl)
            return forever()

        def once():
            n = len(self.labels)
            for i in range(0, n, self.batch_size):
                img = self.images[i:i + self.batch_size]
                yield MiniBatch(
                    (img.astype(np.float32) - self.mean) / self.std,
                    self.labels[i:i + self.batch_size].copy())
        return once()

    def close(self) -> None:
        self._prefetcher.close()
