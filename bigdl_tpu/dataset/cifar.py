"""CIFAR-10 loader.

Reference parity: models/resnet/Utils.scala `loadTrain`/`loadTest` (the
python-pickle-free binary version: each record is 1 label byte + 3072
pixel bytes, data_batch_{1..5}.bin / test_batch.bin) and the
reference's CIFAR normalization constants.
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from bigdl_tpu.dataset.sample import Sample

# reference models/resnet/Utils.scala: trainMean/trainStd (RGB order)
TRAIN_MEAN = np.asarray([125.30691805, 122.95039414, 113.86538318], np.float32)
TRAIN_STD = np.asarray([62.99321928, 62.08870764, 66.70489964], np.float32)


def _read_bin(path: str):
    """Decode CHW records → HWC via the native (C++) data plane when
    available (bigdl_tpu/dataset/native.py; Python fallback inside)."""
    from bigdl_tpu.dataset import native

    with open(path, "rb") as f:
        imgs, labels = native.decode_cifar10(f.read())
    return imgs, labels.astype(np.int32)


def load_cifar10(folder: str, train: bool = True) -> List[Sample]:
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    samples: List[Sample] = []
    for fname in files:
        imgs, labels = _read_bin(os.path.join(folder, fname))
        feats = (imgs.astype(np.float32) - TRAIN_MEAN) / TRAIN_STD
        samples.extend(Sample(feats[i], labels[i]) for i in range(len(labels)))
    return samples


def synthetic_cifar10(n: int = 256, seed: int = 0) -> List[Sample]:
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        label = rng.randint(0, 10)
        img = rng.randn(32, 32, 3).astype(np.float32) * 0.3
        img[:, :, label % 3] += 0.5 + 0.2 * label
        out.append(Sample(img, np.int32(label)))
    return out
