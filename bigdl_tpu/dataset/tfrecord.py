"""TFRecord dataset interop — read/write tf.train.Example records.

Reference parity: the reference ingests Hadoop sequence files; the
TPU-era ecosystem's equivalent record container is TFRecord. The frame
format (length + masked-CRC32C) is shared with our TensorBoard event
writer (visualization/tensorboard.py — same from-scratch codec, no
tensorflow import on the core path); the tf.train.Example message is
hand-decoded from protobuf wire format here:

    Example        = 1: Features
    Features       = 1: map<string, Feature>   (wire: repeated entry)
    Feature        = oneof 1: BytesList | 2: FloatList | 3: Int64List
    BytesList      = 1: repeated bytes
    FloatList      = 1: repeated float   (packed)
    Int64List      = 1: repeated varint  (packed)

`TFRecordDataSet` streams shards into Samples via a parser; the default
parser expects the conventional "image"/"label" keys with raw u8 HWC
image bytes + a "shape" int64 list.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.visualization.tensorboard import masked_crc32c

# ------------------------------------------------------------ wire codec


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, i: int):
    v, shift = 0, 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_example(features: Dict[str, Any]) -> bytes:
    """dict of {name: bytes | str | ints | floats | ndarray} →
    serialized tf.train.Example."""
    entries = b""
    for name, value in features.items():
        if isinstance(value, bytes):
            lst = _len_delim(1, _len_delim(1, value))              # BytesList
        elif isinstance(value, str):
            lst = _len_delim(1, _len_delim(1, value.encode()))
        else:
            arr = np.asarray(value)
            if arr.dtype.kind in "iub":
                payload = b"".join(
                    _varint(int(x) & 0xFFFFFFFFFFFFFFFF)
                    for x in arr.reshape(-1))
                lst = _len_delim(3, _len_delim(1, payload))        # Int64List
            elif arr.dtype.kind == "f":
                payload = arr.reshape(-1).astype("<f4").tobytes()
                lst = _len_delim(2, _len_delim(1, payload))        # FloatList
            else:
                raise TypeError(
                    f"feature {name!r}: unsupported dtype {arr.dtype}")
        entry = _len_delim(1, name.encode()) + _len_delim(2, lst)
        entries += _len_delim(1, entry)                            # map entry
    return _len_delim(1, entries)                                  # Features


def decode_example(raw: bytes) -> Dict[str, Any]:
    """serialized tf.train.Example → {name: bytes | np.ndarray}."""

    def fields(buf):
        i = 0
        while i < len(buf):
            key, i = _read_varint(buf, i)
            field, wire = key >> 3, key & 7
            if wire == 2:
                n, i = _read_varint(buf, i)
                yield field, buf[i:i + n]
                i += n
            elif wire == 0:
                v, i = _read_varint(buf, i)
                yield field, v
            elif wire == 5:
                yield field, buf[i:i + 4]
                i += 4
            elif wire == 1:
                yield field, buf[i:i + 8]
                i += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    def parse_feature(buf):
        for field, val in fields(buf):
            if field == 1:      # BytesList
                items = [v for f, v in fields(val) if f == 1]
                return items[0] if len(items) == 1 else items
            if field == 2:      # FloatList (packed or repeated)
                packed = b"".join(v for f, v in fields(val) if f == 1)
                return np.frombuffer(packed, "<f4").copy()
            if field == 3:      # Int64List
                out = []
                for f, v in fields(val):
                    if f != 1:
                        continue
                    if isinstance(v, int):
                        out.append(v)
                    else:  # packed varints
                        i = 0
                        while i < len(v):
                            x, i = _read_varint(v, i)
                            out.append(x)
                return np.asarray(
                    [x - (1 << 64) if x >= (1 << 63) else x
                     for x in out], np.int64)
        return None

    out: Dict[str, Any] = {}
    for field, feats in fields(raw):
        if field != 1:
            continue
        for f2, entry in fields(feats):
            if f2 != 1:
                continue
            name, feat = None, None
            for f3, v in fields(entry):
                if f3 == 1:
                    name = v.decode()
                elif f3 == 2:
                    feat = parse_feature(v)
            if name is not None:
                out[name] = feat
    return out


# ------------------------------------------------------------ file frame

def write_tfrecords(path: str, payloads: Sequence[bytes]) -> None:
    """Frame serialized messages into a TFRecord file (masked CRC32C)."""
    with open(path, "wb") as f:
        for data in payloads:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc32c(data)))


def read_tfrecords(path: str) -> Iterator[bytes]:
    """Stream the framed records of one file, verifying both CRCs."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError(f"{path}: truncated record header")
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != masked_crc32c(header):
                raise ValueError(f"{path}: header CRC mismatch")
            (n,) = struct.unpack("<Q", header)
            data = f.read(n)
            if len(data) < n:
                raise ValueError(f"{path}: truncated record body")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != masked_crc32c(data):
                raise ValueError(f"{path}: record CRC mismatch")
            yield data


# ------------------------------------------------------------ dataset

def default_image_parser(example: Dict[str, Any]) -> Sample:
    """The conventional layout: 'image' raw u8 bytes + 'shape' int64
    HWC dims + 'label' int64."""
    shape = tuple(int(d) for d in example["shape"])
    img = np.frombuffer(example["image"], np.uint8).reshape(shape)
    label = np.int32(int(example["label"][0]))
    return Sample(img.astype(np.float32), label)


def count_tfrecords(path: str) -> int:
    """Record count of one shard by seeking over the framing (length
    header → skip body), no CRC work and no body reads — O(records)
    seeks instead of a full decode. A sidecar `<path>.count` file
    holding the integer short-circuits even that (write one when
    producing ImageNet-scale shards)."""
    sidecar = path + ".count"
    # trust the sidecar only if it's at least as new as the shard — a
    # regenerated shard with a stale sidecar must fall back to the scan
    if (os.path.exists(sidecar)
            and os.path.getmtime(sidecar) >= os.path.getmtime(path)):
        with open(sidecar) as f:
            return int(f.read().strip())
    n = 0
    total = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return n
            if len(header) < 8:
                raise ValueError(f"{path}: truncated record header")
            (ln,) = struct.unpack("<Q", header)
            f.seek(4 + ln + 4, 1)  # header crc + body + body crc
            if f.tell() > total:   # seek past EOF succeeds silently —
                # raise the same error the reading iterator would
                raise ValueError(f"{path}: truncated record body")
            n += 1


class TFRecordDataSet(AbstractDataSet):
    """Dataset over TFRecord shards of tf.train.Example records.

    `parser`: Example dict → Sample (default: image/shape/label keys).
    train=True shuffles shard order and in-shard record order per epoch
    (statelessly, like every dataset here — resume fast-forward safe);
    train=False streams in order once.

    Memory note: the train iterator materializes ONE shard at a time to
    shuffle in-shard order — size shards accordingly (the conventional
    100–200 MB TFRecord shard is fine; don't write one giant shard).
    `size()` counts by framing seeks (or a `<shard>.count` sidecar),
    not a full CRC decode.
    """

    def __init__(self, paths, parser: Callable[[Dict[str, Any]], Sample]
                 = default_image_parser, seed: int = 1):
        from bigdl_tpu.dataset.records import resolve_shards

        self.paths = [p for p in resolve_shards(paths,
                                                pattern="*.tfrecord*")
                      if not p.endswith(".count")]  # count sidecars
        self.parser = parser
        self.seed = seed
        self._n: Optional[int] = None

    def size(self) -> int:
        if self._n is None:
            self._n = sum(count_tfrecords(p) for p in self.paths)
        return self._n

    def data(self, train: bool) -> Iterator:
        if not train:
            def once():
                for p in self.paths:
                    for raw in read_tfrecords(p):
                        yield self.parser(decode_example(raw))
            return once()

        def forever():
            epoch = 0
            while True:
                rng = np.random.RandomState(self.seed + epoch)
                for pi in rng.permutation(len(self.paths)):
                    records = list(read_tfrecords(self.paths[pi]))
                    for ri in rng.permutation(len(records)):
                        yield self.parser(decode_example(records[ri]))
                epoch += 1
        return forever()


def write_image_examples(path: str, images: np.ndarray,
                         labels: Sequence[int]) -> None:
    """Convenience: (n,h,w,c) u8 images + labels → one TFRecord shard
    in the default_image_parser layout."""
    images = np.ascontiguousarray(images, np.uint8)
    payloads = [encode_example({
        "image": images[i].tobytes(),
        "shape": np.asarray(images[i].shape, np.int64),
        "label": np.asarray([int(labels[i])], np.int64),
    }) for i in range(len(images))]
    write_tfrecords(path, payloads)
