"""bigdl_tpu.dataset — host-side data plane (reference: bigdl/dataset/)."""

from bigdl_tpu.dataset.sample import Sample, MiniBatch
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, chain, MapTransformer, SampleToMiniBatch,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, PrefetchDataSet, ShardedDataSet,
    TransformedDataSet, DataSet,
)
from bigdl_tpu.dataset import image, native, text, mnist, cifar, vision
from bigdl_tpu.dataset.records import (
    RecordFileDataSet, read_header, resolve_shards, write_shards,
)
from bigdl_tpu.dataset.tfrecord import (
    TFRecordDataSet, decode_example, encode_example, read_tfrecords,
    write_image_examples, write_tfrecords,
)
from bigdl_tpu.dataset.vision import ImageFeature, ImageFrame
