"""Keras layer wrappers, tranche 2: 3-D conv/pool, upsampling, global
max-pool, recurrent variants (reference parity: the nn/keras layer set)."""

from __future__ import annotations

from typing import Optional

from bigdl_tpu import nn
from bigdl_tpu.keras.layers import KerasLayer, activation_module


class Conv3D(KerasLayer):
    """3-D conv over (D, H, W, C) input."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1, 1),
                 padding: str = "valid", activation: Optional[str] = None,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.filters = filters
        self.kernel = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.strides = (strides,) * 3 if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding
        self.activation = activation

    def build(self, input_shape):
        d, h, w, c = input_shape
        pad = -1 if self.padding == "same" else 0
        m = self._named(nn.VolumetricConvolution(
            c, self.filters, self.kernel[0], self.kernel[2], self.kernel[1],
            self.strides[0], self.strides[2], self.strides[1],
            pad_t=pad, pad_w=pad, pad_h=pad))
        out = self._infer_out(m, input_shape)
        act = activation_module(self.activation)
        if act is not None:
            m = nn.Sequential(m, act)
        return m, out


class MaxPooling3D(KerasLayer):
    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool = (pool_size,) * 3 if isinstance(pool_size, int) \
            else tuple(pool_size)
        if strides is None:
            self.strides = self.pool
        else:
            self.strides = (strides,) * 3 if isinstance(strides, int) \
                else tuple(strides)

    def build(self, input_shape):
        m = self._named(nn.VolumetricMaxPooling(
            self.pool[0], self.pool[2], self.pool[1],
            self.strides[0], self.strides[2], self.strides[1]))
        return m, self._infer_out(m, input_shape)


class UpSampling2D(KerasLayer):
    def __init__(self, size=2, interpolation: str = "nearest",
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        if isinstance(size, (tuple, list)):  # keras's (2, 2) form
            if len(set(size)) != 1:
                raise NotImplementedError(
                    "UpSampling2D needs a uniform scale, got "
                    f"size={tuple(size)}")
            size = size[0]
        self.size = int(size)
        self.interpolation = interpolation

    def build(self, input_shape):
        if self.interpolation == "nearest":
            m = nn.SpatialUpSamplingNearest(self.size)
        else:
            m = nn.SpatialUpSamplingBilinear(self.size,
                                             align_corners=False)
        h, w, c = input_shape
        return self._named(m), (h * self.size, w * self.size, c)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        m = self._named(nn.Sequential(
            nn.Max(dimension=2, squeeze=True),
            nn.Max(dimension=2, squeeze=True)))
        return m, (input_shape[-1],)


class SimpleRNN(KerasLayer):
    def __init__(self, units: int, return_sequences: bool = False,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.units = units
        self.return_sequences = return_sequences

    def _cell(self, feat):
        return nn.RnnCell(feat, self.units)

    def build(self, input_shape):
        seq_len, feat = input_shape
        m = nn.Recurrent(self._cell(feat))
        if not self.return_sequences:
            m = nn.Sequential(m, nn.Select(2, -1))
            return self._named(m), (self.units,)
        return self._named(m), (seq_len, self.units)


class GRU(SimpleRNN):
    def _cell(self, feat):
        return nn.GRU(feat, self.units)


class _BiLastState(nn.Module):
    """Keras 'last state' of a concat-merged BiRecurrent output
    (reference: nn/keras/Bidirectional.scala with returnSequences=false,
    over nn/BiRecurrent.scala output).

    (N, T, 2H) → (N, 2H): forward half at t=-1, backward half at t=0.
    BiRecurrent re-flips the backward stream to input order, so the
    backward RNN's FINAL step (all frames seen) sits at input position
    0 — Select(2, -1) on the joint output would take the backward
    RNN's first step instead, which is not Keras semantics."""

    def __init__(self, units: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.units = units

    def apply(self, variables, x, training=False, rng=None):
        import jax.numpy as jnp

        h = self.units
        out = jnp.concatenate([x[:, -1, :h], x[:, 0, h:]], axis=-1)
        return out, variables["state"]


class Bidirectional(KerasLayer):
    """Wrap an LSTM/GRU/SimpleRNN layer config to run both directions
    (concat merge, like the reference's BiRecurrent)."""

    def __init__(self, layer, input_shape=None, name=None):
        super().__init__(input_shape or layer.input_shape, name)
        self.layer = layer

    def build(self, input_shape):
        seq_len, feat = input_shape
        units = self.layer.units
        if isinstance(self.layer, GRU):
            cell = lambda: nn.GRU(feat, units)
        elif isinstance(self.layer, SimpleRNN):
            cell = lambda: nn.RnnCell(feat, units)
        else:  # keras.LSTM config from layers.py
            cell = lambda: nn.LSTM(feat, units)
        m = nn.BiRecurrent(cell(), cell())
        if not getattr(self.layer, "return_sequences", False):
            m = nn.Sequential(m, _BiLastState(units))
            return self._named(m), (2 * units,)
        return self._named(m), (seq_len, 2 * units)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape, name)
        if isinstance(padding, int):
            padding = (padding, padding)
        self.padding = tuple(padding)  # (pad_h, pad_w)

    def build(self, input_shape):
        h, w, c = input_shape
        ph, pw = self.padding
        m = self._named(nn.SpatialZeroPadding(pw, pw, ph, ph))
        return m, (h + 2 * ph, w + 2 * pw, c)


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1)), input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        if isinstance(cropping, int):
            cropping = ((cropping, cropping), (cropping, cropping))
        self.cropping = tuple(tuple(c) for c in cropping)

    def build(self, input_shape):
        h, w, c = input_shape
        (t, b), (l, r) = self.cropping
        m = self._named(nn.Sequential(
            nn.Narrow(2, t + 1, h - t - b),
            nn.Narrow(3, l + 1, w - l - r)))
        return m, (h - t - b, w - l - r, c)


class Permute(KerasLayer):
    """Permute non-batch dims, keras-style 1-based `dims`."""

    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.dims = tuple(dims)

    def build(self, input_shape):
        # decompose the permutation into swaps for nn.Transpose
        # (1-based over full tensor: +1 for the batch dim)
        perm = [d - 1 for d in self.dims]   # 0-based over features
        cur = list(range(len(perm)))
        swaps = []
        for i, want in enumerate(perm):
            j = cur.index(want)
            if j != i:
                swaps.append((i + 2, j + 2))  # 1-based incl. batch
                cur[i], cur[j] = cur[j], cur[i]
        m = self._named(nn.Transpose(swaps)) if swaps else None
        out = tuple(input_shape[d - 1] for d in self.dims)
        return m, out


class RepeatVector(KerasLayer):
    """(B, F) → (B, n, F)."""

    def __init__(self, n: int, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.n = n

    def build(self, input_shape):
        m = self._named(nn.Replicate(self.n, dim=2))
        return m, (self.n,) + tuple(input_shape)
