"""Keras-1-shaped layer wrappers (reference parity: nn/keras/ layer
classes — each holds its config, infers its input shape from the previous
layer at build time, and lowers to a core `bigdl_tpu.nn` module)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu import nn

_ACTIVATIONS = {
    "relu": nn.ReLU, "relu6": nn.ReLU6, "tanh": nn.Tanh,
    "sigmoid": nn.Sigmoid, "softmax": nn.SoftMax,
    "log_softmax": nn.LogSoftMax, "elu": nn.ELU, "gelu": nn.GELU,
    "softplus": nn.SoftPlus, "softsign": nn.SoftSign, "linear": None,
    None: None,
}


def activation_module(name):
    if name not in _ACTIVATIONS:
        raise ValueError(f"unknown activation {name!r}")
    cls = _ACTIVATIONS[name]
    return cls() if cls is not None else None


class KerasLayer:
    """A layer config: `build(input_shape)` → (nn.Module, output_shape).
    input/output shapes EXCLUDE the batch dim, as in Keras."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def build(self, input_shape: Tuple[int, ...]
              ) -> Tuple[Optional[nn.Module], Tuple[int, ...]]:
        raise NotImplementedError

    def __call__(self, tensor):
        """Functional-API wiring: `layer(tensor)` on a KTensor from
        `keras.Input` (see keras/functional.py)."""
        from bigdl_tpu.keras.functional import call_layer

        return call_layer(self, tensor)

    @staticmethod
    def _infer_out(module: nn.Module, input_shape: Tuple[int, ...]
                   ) -> Tuple[int, ...]:
        """Output shape via abstract evaluation on a batch of 1."""
        v = jax.eval_shape(module.init, jax.random.PRNGKey(0))
        out = jax.eval_shape(
            lambda vv, x: module.apply(vv, x, training=False)[0], v,
            jax.ShapeDtypeStruct((1,) + tuple(input_shape), jnp.float32))
        return tuple(out.shape)[1:]

    def _named(self, m: nn.Module) -> nn.Module:
        if self.name:
            m.set_name(self.name)
        return m


class InputLayer(KerasLayer):
    def __init__(self, input_shape: Sequence[int]):
        super().__init__(input_shape=input_shape)

    def build(self, input_shape):
        return None, tuple(input_shape)


class Dense(KerasLayer):
    """Fully-connected layer (keras.layers.Dense shape)."""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 input_shape=None, name=None, **kw):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation

    def build(self, input_shape):
        if len(input_shape) != 1:
            raise ValueError(f"Dense expects flat input, got {input_shape}")
        m = self._named(nn.Linear(input_shape[0], self.output_dim))
        act = activation_module(self.activation)
        if act is not None:
            m = nn.Sequential(m, act)
        return m, (self.output_dim,)


class Conv2D(KerasLayer):
    """2-D conv over NHWC (keras.layers.Conv2D shape; `padding` is
    'valid' or 'same')."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding: str = "valid", activation: Optional[str] = None,
                 input_shape=None, name=None, **kw):
        super().__init__(input_shape, name)
        self.filters = filters
        self.kernel = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding
        self.activation = activation

    def build(self, input_shape):
        h, w, c = input_shape
        pad = -1 if self.padding == "same" else 0
        m = self._named(nn.SpatialConvolution(
            c, self.filters, self.kernel[1], self.kernel[0],
            self.strides[1], self.strides[0], pad, pad))
        out = self._infer_out(m, input_shape)
        act = activation_module(self.activation)
        if act is not None:
            m = nn.Sequential(m, act)
        return m, out


Convolution2D = Conv2D


class _Pool2D(KerasLayer):
    _cls = None
    _kw = {}

    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool = (pool_size,) * 2 if isinstance(pool_size, int) \
            else tuple(pool_size)
        strides = strides if strides is not None else self.pool
        self.strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding

    def build(self, input_shape):
        pad = -1 if self.padding == "same" else 0
        m = self._named(self._cls(
            self.pool[1], self.pool[0], self.strides[1], self.strides[0],
            pad_w=pad, pad_h=pad, **self._kw))
        return m, self._infer_out(m, input_shape)


class MaxPooling2D(_Pool2D):
    _cls = nn.SpatialMaxPooling


class AveragePooling2D(_Pool2D):
    _cls = nn.SpatialAveragePooling
    _kw = {"count_include_pad": False}


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        m = self._named(nn.Sequential(
            nn.Mean(dimension=2, squeeze=True),
            nn.Mean(dimension=2, squeeze=True)))
        return m, (input_shape[-1],)


class Flatten(KerasLayer):
    def build(self, input_shape):
        n = 1
        for d in input_shape:
            n *= int(d)
        return self._named(nn.Reshape([n])), (n,)


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return (self._named(nn.Reshape(list(self.target_shape))),
                self.target_shape)


class Activation(KerasLayer):
    def __init__(self, activation: str, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.activation = activation

    def build(self, input_shape):
        m = activation_module(self.activation)
        if m is None:
            return None, tuple(input_shape)
        return self._named(m), tuple(input_shape)


class Dropout(KerasLayer):
    def __init__(self, rate: float, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.rate = rate

    def build(self, input_shape):
        return self._named(nn.Dropout(self.rate)), tuple(input_shape)


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.epsilon = epsilon
        self.momentum = momentum

    def build(self, input_shape):
        if len(input_shape) == 3:
            m = nn.SpatialBatchNormalization(
                input_shape[-1], eps=self.epsilon,
                momentum=1.0 - self.momentum)
        else:
            m = nn.BatchNormalization(input_shape[-1], eps=self.epsilon,
                                      momentum=1.0 - self.momentum)
        return self._named(m), tuple(input_shape)


class Embedding(KerasLayer):
    """Token ids (seq_len,) → (seq_len, output_dim)."""

    def __init__(self, input_dim: int, output_dim: int, input_shape=None,
                 input_length: Optional[int] = None, name=None):
        if input_shape is None and input_length is not None:
            input_shape = (input_length,)
        super().__init__(input_shape, name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build(self, input_shape):
        m = self._named(nn.LookupTable(self.input_dim, self.output_dim))
        return m, tuple(input_shape) + (self.output_dim,)


class LSTM(KerasLayer):
    """Recurrent LSTM over (seq_len, features); `return_sequences`
    mirrors keras (False → last output only)."""

    def __init__(self, units: int, return_sequences: bool = False,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.units = units
        self.return_sequences = return_sequences

    def build(self, input_shape):
        seq_len, feat = input_shape
        m = nn.Recurrent(nn.LSTM(feat, self.units))
        if not self.return_sequences:
            m = nn.Sequential(m, nn.Select(2, -1))
            out = (self.units,)
        else:
            out = (seq_len, self.units)
        return self._named(m), out
