"""Keras-style Sequential with compile/fit/evaluate/predict.

Reference parity: the reference line's nn/keras model classes — sugar
that lowers onto the core `Optimizer`/`Evaluator`/`Predictor` stack
(optim/Optimizer.scala path), not a separate trainer.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.keras.layers import KerasLayer
from bigdl_tpu.optim import (
    Adam, Evaluator, Loss, Optimizer, Predictor, RMSprop, SGD, Top1Accuracy,
    Trigger,
)

_OPTIMIZERS = {
    "sgd": lambda: SGD(learningrate=0.01),
    "adam": lambda: Adam(),
    "rmsprop": lambda: RMSprop(),
}

_LOSSES = {
    "sparse_categorical_crossentropy": nn.CrossEntropyCriterion,
    "categorical_crossentropy": nn.CrossEntropyCriterion,
    "nll": nn.ClassNLLCriterion,
    "mse": nn.MSECriterion,
    "mean_squared_error": nn.MSECriterion,
    "binary_crossentropy": nn.BCECriterion,
}

_METRICS = {
    "accuracy": Top1Accuracy,
    "acc": Top1Accuracy,
    "loss": Loss,
}


class _Trainable:
    """compile/fit/evaluate/predict surface shared by keras.Sequential
    and the functional keras.Model — both lower onto the core
    Optimizer/Evaluator/Predictor stack."""

    def __init__(self):
        self._module = None
        self._optim = None
        self._criterion = None
        self._metrics = None

    def build(self):
        raise NotImplementedError

    # ---- data adaptation (Model overrides for multi-input) ----------

    def _to_samples(self, x, y):
        xs = np.asarray(x)
        ys = np.asarray(y)
        return [Sample(xi, yi) for xi, yi in zip(xs, ys)]

    def _to_dataset(self, x, y) -> "DataSet":
        return DataSet.array(self._to_samples(x, y))

    # ---- training ---------------------------------------------------

    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = ()):
        self._optim = _OPTIMIZERS[optimizer]() \
            if isinstance(optimizer, str) else optimizer
        self._criterion = _LOSSES[loss]() if isinstance(loss, str) else loss
        self._metrics = [_METRICS[m]() if isinstance(m, str) else m
                         for m in metrics]
        return self

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1,
            validation_data=None, precision=None):
        if self._optim is None:
            raise RuntimeError("call compile() before fit()")
        module = self.build()
        opt = (Optimizer(module, self._to_dataset(x, y), self._criterion,
                         batch_size=batch_size)
               .set_optim_method(self._optim)
               .set_end_when(Trigger.max_epoch(epochs)))
        if validation_data is not None and self._metrics:
            vx, vy = validation_data
            opt.set_validation(Trigger.every_epoch(),
                               self._to_dataset(vx, vy), self._metrics,
                               batch_size=batch_size)
        if precision is not None:
            opt.set_precision(precision)
        trained = opt.optimize()
        self._module = trained
        return self

    def evaluate(self, x, y, batch_size: int = 32) -> dict:
        module = self.build()
        methods = self._metrics or [Loss(self._criterion
                                         or nn.ClassNLLCriterion())]
        res = Evaluator(module).test(self._to_dataset(x, y), methods,
                                     batch_size=batch_size)
        return {k: v.result()[0] for k, v in res.items()}

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        module = self.build()
        samples = [Sample(f, np.int32(0))
                   for f in self._predict_features(x)]
        return Predictor(module, batch_size=batch_size).predict(
            DataSet.array(samples))

    def _predict_features(self, x):
        return np.asarray(x)

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        return np.argmax(self.predict(x, batch_size), axis=-1)


class Sequential(_Trainable):
    """keras.models.Sequential-shaped builder; the first layer must carry
    `input_shape` (batch dim excluded, as in Keras)."""

    def __init__(self, layers: Optional[Sequence[KerasLayer]] = None):
        super().__init__()
        self.layers: List[KerasLayer] = []
        for l in layers or []:
            self.add(l)

    def add(self, layer: KerasLayer) -> "Sequential":
        if not self.layers and layer.input_shape is None:
            raise ValueError("first layer needs input_shape=...")
        self.layers.append(layer)
        self._module = None  # invalidate built module
        return self

    # ---- build ---------------------------------------------------------

    def build(self) -> nn.Sequential:
        if self._module is not None:
            return self._module
        seq = nn.Sequential()
        shape = self.layers[0].input_shape
        for layer in self.layers:
            if layer.input_shape is not None:
                shape = layer.input_shape
            m, shape = layer.build(shape)
            if m is not None:
                seq.add(m)
        self._module = seq
        self.output_shape = shape
        return seq

    @property
    def module(self) -> nn.Sequential:
        return self.build()

    def summary(self) -> str:
        lines = ["Layer (type)                 Output Shape"]
        shape = self.layers[0].input_shape
        for layer in self.layers:
            if layer.input_shape is not None:
                shape = layer.input_shape
            _, shape = layer.build(shape)
            lname = layer.name or type(layer).__name__
            lines.append(f"{lname:<29}{(None,) + tuple(shape)}")
        return "\n".join(lines)

