"""Keras-style model-building API.

Reference parity: the reference line's `nn/keras` package (Keras-1-shaped
layer wrappers over the core module library: Sequential/Model with
`compile`/`fit`/`evaluate`/`predict`, layers inferring their input shapes
from the previous layer). Thin sugar over `bigdl_tpu.nn` + `Optimizer` —
everything lowers to the same jitted training path.
"""

from bigdl_tpu.keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Conv2D, Convolution2D,
    Dense, Dropout, Embedding, Flatten, GlobalAveragePooling2D, InputLayer,
    LSTM, MaxPooling2D, Reshape,
)
from bigdl_tpu.keras.layers_extra import (
    Bidirectional, Conv3D, Cropping2D, GRU, GlobalMaxPooling2D,
    MaxPooling3D, Permute, RepeatVector, SimpleRNN, UpSampling2D,
    ZeroPadding2D,
)
from bigdl_tpu.keras.models import Sequential
from bigdl_tpu.keras.functional import (
    Add, Average, Concatenate, Dot, Input, KTensor, Maximum, Minimum,
    Model, Multiply, Subtract, merge,
)

__all__ = [
    "Sequential", "Dense", "Conv2D", "Convolution2D", "MaxPooling2D",
    "AveragePooling2D", "GlobalAveragePooling2D", "Flatten", "Activation",
    "Dropout", "Embedding", "BatchNormalization", "LSTM", "Reshape",
    "InputLayer", "Conv3D", "MaxPooling3D", "UpSampling2D",
    "GlobalMaxPooling2D", "SimpleRNN", "GRU", "Bidirectional",
    "ZeroPadding2D", "Cropping2D", "Permute", "RepeatVector",
    # functional API
    "Model", "Input", "KTensor", "merge", "Add", "Multiply", "Subtract",
    "Average", "Maximum", "Minimum", "Concatenate", "Dot",
]
