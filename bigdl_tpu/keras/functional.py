"""Keras functional (graph) API — `Model(inputs, outputs)`.

Reference parity: the reference line's `nn/keras` Model class (Keras-1
functional wiring: `Input`, calling layers on tensors, merge layers)
lowering onto the static graph container — here `nn.Graph`
(nn/StaticGraph.scala role), so the functional model trains through the
exact same jitted path as every other module.

    a = Input(shape=(16,))
    b = Input(shape=(16,))
    x = Dense(8, activation="relu")(a)
    y = Dense(8, activation="relu")(b)
    z = Add()([x, y])
    out = Dense(2, activation="log_softmax")(z)
    model = Model(inputs=[a, b], outputs=out)
    model.compile("adam", "nll").fit([xa, xb], labels)

Shapes exclude the batch dim, as everywhere in the keras package.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

from bigdl_tpu import nn
from bigdl_tpu.keras.layers import KerasLayer
from bigdl_tpu.keras.models import _Trainable
from bigdl_tpu.nn import graph as _graph


class KTensor:
    """A symbolic tensor: a graph node + its inferred (batchless) shape."""

    __slots__ = ("node", "shape")

    def __init__(self, node: _graph.Node, shape: Tuple[int, ...]):
        self.node = node
        self.shape = tuple(shape)

    def __repr__(self):
        return f"KTensor(shape={(None,) + self.shape})"


def Input(shape: Sequence[int], name: Optional[str] = None) -> KTensor:
    """Symbolic entry point (keras.layers.Input; reference nn/Input)."""
    return KTensor(_graph.Input(), tuple(shape))


def call_layer(layer: KerasLayer, tensor) -> KTensor:
    """`layer(tensor)` — wire a single-input layer into the graph
    (KerasLayer.__call__ delegates here).

    Calling the same layer instance again REUSES the module built on the
    first call (Keras weight-sharing contract; nn.Graph dedupes shared
    module objects into one parameter entry). The input shape must match
    the first call's."""
    if isinstance(tensor, (list, tuple)):
        raise TypeError(
            f"{type(layer).__name__} takes one tensor; wrap multiple "
            "tensors with a merge layer (Add, Concatenate, ...)")
    if not isinstance(tensor, KTensor):
        raise TypeError(f"expected a KTensor from Input()/a layer call, "
                        f"got {type(tensor).__name__}")
    cached = getattr(layer, "_fn_built", None)
    if cached is not None:
        in_shape, m, out_shape = cached
        if tensor.shape != in_shape:
            raise ValueError(
                f"{type(layer).__name__} was first called on shape "
                f"{in_shape}; weight sharing requires the same input "
                f"shape, got {tensor.shape}")
    else:
        m, out_shape = layer.build(tensor.shape)
        layer._fn_built = (tensor.shape, m, out_shape)
    if m is None:  # InputLayer-style passthrough
        return tensor
    return KTensor(_graph.Node(m, [tensor.node]), out_shape)


class _Merge(KerasLayer):
    """Base for layers combining a LIST of tensors."""

    def __call__(self, tensors: Sequence[KTensor]) -> KTensor:
        if not isinstance(tensors, (list, tuple)) or len(tensors) < 2:
            raise TypeError(
                f"{type(self).__name__} expects a list of >=2 tensors")
        shapes = [t.shape for t in tensors]
        m, out = self.build_merge(shapes)
        return KTensor(_graph.Node(self._named(m),
                                   [t.node for t in tensors]), out)

    def build_merge(self, shapes):
        raise NotImplementedError

    @staticmethod
    def _require_same(shapes, what):
        if any(s != shapes[0] for s in shapes[1:]):
            raise ValueError(f"{what} needs identical shapes, got {shapes}")
        return shapes[0]


class Add(_Merge):
    def build_merge(self, shapes):
        return nn.CAddTable(), self._require_same(shapes, "Add")


class Multiply(_Merge):
    def build_merge(self, shapes):
        return nn.CMulTable(), self._require_same(shapes, "Multiply")


class Subtract(_Merge):
    def __call__(self, tensors):
        if len(tensors) != 2:
            raise TypeError("Subtract expects exactly 2 tensors")
        return super().__call__(tensors)

    def build_merge(self, shapes):
        return nn.CSubTable(), self._require_same(shapes, "Subtract")


class Maximum(_Merge):
    def build_merge(self, shapes):
        return nn.CMaxTable(), self._require_same(shapes, "Maximum")


class Minimum(_Merge):
    def build_merge(self, shapes):
        return nn.CMinTable(), self._require_same(shapes, "Minimum")


class Average(_Merge):
    def build_merge(self, shapes):
        shape = self._require_same(shapes, "Average")
        return nn.Sequential(nn.CAddTable(),
                             nn.MulConstant(1.0 / len(shapes))), shape


class Concatenate(_Merge):
    """Join along `axis` of the batchless shape (default last)."""

    def __init__(self, axis: int = -1, name=None):
        super().__init__(name=name)
        self.axis = axis

    def build_merge(self, shapes):
        nd = len(shapes[0])
        ax = self.axis if self.axis >= 0 else nd + self.axis
        if not 0 <= ax < nd:
            raise ValueError(
                f"Concatenate axis={self.axis} out of range for "
                f"rank-{nd} inputs {shapes}")
        for s in shapes[1:]:
            if len(s) != nd or any(a != b for i, (a, b) in
                                   enumerate(zip(s, shapes[0])) if i != ax):
                raise ValueError(
                    f"Concatenate(axis={self.axis}) shape mismatch: {shapes}")
        out = list(shapes[0])
        out[ax] = sum(s[ax] for s in shapes)
        # JoinTable dimension is 1-based over the batchless rank with
        # n_input_dims telling it to skip the batch dim at runtime
        return nn.JoinTable(ax + 1, n_input_dims=nd), tuple(out)


class Dot(_Merge):
    """Batch dot product of two flat tensors → (1,)."""

    def __call__(self, tensors):
        if len(tensors) != 2:
            raise TypeError("Dot expects exactly 2 tensors")
        return super().__call__(tensors)

    def build_merge(self, shapes):
        self._require_same(shapes, "Dot")
        return nn.DotProduct(), (1,)


_MERGE_MODES = {
    "sum": Add, "mul": Multiply, "max": Maximum, "min": Minimum,
    "ave": Average, "sub": Subtract, "dot": Dot, "concat": Concatenate,
}


def merge(inputs: Sequence[KTensor], mode: str = "sum",
          concat_axis: int = -1) -> KTensor:
    """Keras-1 style functional merge (reference nn/keras Merge layer)."""
    if mode not in _MERGE_MODES:
        raise ValueError(f"unknown merge mode {mode!r} "
                         f"(have {sorted(_MERGE_MODES)})")
    cls = _MERGE_MODES[mode]
    layer = cls(axis=concat_axis) if cls is Concatenate else cls()
    return layer(list(inputs))


class Model(_Trainable):
    """Functional model over an arbitrary DAG of layer calls.

    Lowers to `nn.Graph`; `compile`/`fit`/`evaluate`/`predict` run the
    same core Optimizer/Evaluator/Predictor stack as keras.Sequential.
    Multi-input fit takes `x` as a list of per-input arrays; multi-output
    models train with a table-aware criterion (nn.ParallelCriterion).
    """

    def __init__(self, inputs: Union[KTensor, Sequence[KTensor]],
                 outputs: Union[KTensor, Sequence[KTensor]],
                 name: Optional[str] = None):
        super().__init__()
        self.inputs: List[KTensor] = (
            [inputs] if isinstance(inputs, KTensor) else list(inputs))
        self.outputs: List[KTensor] = (
            [outputs] if isinstance(outputs, KTensor) else list(outputs))
        self._module = nn.Graph([t.node for t in self.inputs],
                                [t.node for t in self.outputs], name=name)
        self.input_shapes = [t.shape for t in self.inputs]
        self.output_shape = (self.outputs[0].shape if len(self.outputs) == 1
                             else [t.shape for t in self.outputs])

    def build(self) -> nn.Graph:
        return self._module

    @property
    def module(self) -> nn.Graph:
        return self._module

    def _wrap_x(self, x):
        """list-of-arrays (one per input) → per-sample tuples."""
        import numpy as np

        if len(self.inputs) == 1:
            return np.asarray(x), None
        xs = [np.asarray(xi) for xi in x]
        n = len(xs[0])
        if any(len(xi) != n for xi in xs):
            raise ValueError("all inputs must have the same sample count")
        return xs, n

    def _to_samples(self, x, y):
        import numpy as np

        from bigdl_tpu.dataset import Sample

        if len(self.inputs) == 1:
            return super()._to_samples(x, y)
        xs, n = self._wrap_x(x)
        ys = np.asarray(y)
        return [Sample(tuple(xi[i] for xi in xs), ys[i]) for i in range(n)]

    def _predict_features(self, x):
        if len(self.inputs) == 1:
            return super()._predict_features(x)
        xs, n = self._wrap_x(x)
        return [tuple(xi[i] for xi in xs) for i in range(n)]
