"""bigdl_tpu — a TPU-native deep learning framework.

A ground-up re-design of the capabilities of the reference framework
(barakb/BigDL: Scala/Spark + MKL CPU engine) for TPU hardware:

- compute: jax/jnp under XLA:TPU (+ Pallas kernels for fused hot ops)
- modules: pytree-functional `Module` with `init`/`apply` (the reference's
  `AbstractModule.forward/backward` becomes pure functions + `jax.grad`)
- distribution: `jax.sharding.Mesh` + collectives over ICI/DCN (the
  reference's Spark BlockManager parameter plane becomes
  `psum_scatter` → sharded optimizer → `all_gather`, i.e. the same
  ZeRO-1 shape executed on-device)
- data: host-side Python/C++ input pipeline with per-host sharding

Reference parity map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from bigdl_tpu.utils.engine import Engine

__all__ = ["Engine", "__version__"]
