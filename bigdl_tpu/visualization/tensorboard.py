"""From-scratch TensorBoard event-file writer.

Reference parity: visualization/tensorboard/{FileWriter,EventWriter,
RecordWriter}.scala — the reference hand-writes TFRecord framing with
masked CRC32C and Event protos from Scala; this is the same trick in
Python (no tensorflow dependency): hand-encoded protobuf varints for the
tiny Event/Summary subset we emit (scalars + histograms).

TFRecord frame:  [len u64le][masked_crc32c(len) u32le][data][masked_crc32c(data) u32le]
Event proto:     1: wall_time (double), 2: step (int64), 5: summary (Summary)
Summary.Value:   1: tag (string), 2: simple_value (float), 5: histo (HistogramProto)
"""

from __future__ import annotations

import os
import struct
import time
from typing import Optional, Sequence

import numpy as np

# ----------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf enc
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _double_field(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _float_field(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _int64_field(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _bytes_field(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _scalar_event(tag: str, value: float, step: int, wall_time: float) -> bytes:
    value_msg = _bytes_field(1, tag.encode()) + _float_field(2, float(value))
    summary = _bytes_field(1, value_msg)
    return (_double_field(1, wall_time) + _int64_field(2, step)
            + _bytes_field(5, summary))


def _histogram_proto(values: np.ndarray) -> bytes:
    values = np.asarray(values, np.float64).ravel()
    if values.size == 0:
        values = np.zeros(1)
    # exponential bucket edges, the standard TB scheme
    edges = [0.0]
    v = 1e-12
    while v < 1e20:
        edges.append(v)
        v *= 1.1
    edges = np.asarray(sorted(set([-e for e in edges[1:]] + edges)))
    counts, _ = np.histogram(values, bins=np.concatenate([[-np.inf], edges]))
    msg = b"".join([
        _double_field(1, float(values.min())),
        _double_field(2, float(values.max())),
        _double_field(3, float(values.size)),
        _double_field(4, float(values.sum())),
        _double_field(5, float((values ** 2).sum())),
    ])
    # packed repeated double: bucket_limit field 6, bucket field 7
    packed_limits = b"".join(struct.pack("<d", e) for e in edges)
    packed_counts = b"".join(struct.pack("<d", float(c)) for c in counts)
    msg += _bytes_field(6, packed_limits) + _bytes_field(7, packed_counts)
    return msg


def _histo_event(tag: str, values: np.ndarray, step: int, wall_time: float) -> bytes:
    value_msg = _bytes_field(1, tag.encode()) + _bytes_field(5, _histogram_proto(values))
    summary = _bytes_field(1, value_msg)
    return (_double_field(1, wall_time) + _int64_field(2, step)
            + _bytes_field(5, summary))


class FileWriter:
    """Append TFRecord-framed events to an events file
    (reference: visualization/tensorboard/FileWriter.scala)."""

    def __init__(self, logdir: str, flush_secs: float = 10.0):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl-tpu"
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._last_flush = time.time()
        self.flush_secs = flush_secs
        # file-version header event
        self._write_record(
            _double_field(1, time.time()) + _bytes_field(3, b"brain.Event:2"))

    def _write_record(self, data: bytes) -> None:
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", masked_crc32c(data)))
        if time.time() - self._last_flush > self.flush_secs:
            self.flush()

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self._write_record(_scalar_event(tag, value, step,
                                         wall_time or time.time()))

    def add_histogram(self, tag: str, values, step: int,
                      wall_time: Optional[float] = None) -> None:
        self._write_record(_histo_event(tag, np.asarray(values), step,
                                        wall_time or time.time()))

    def flush(self) -> None:
        self._f.flush()
        self._last_flush = time.time()

    def close(self) -> None:
        self.flush()
        self._f.close()


def read_events(path: str):
    """Parse an events file back into (tag, value, step) tuples — used by
    tests to round-trip the writer (scalar events only)."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break  # truncated tail (writer mid-record) — stop cleanly
            (length,) = struct.unpack("<Q", header)
            hcrc_bytes = f.read(4)
            if len(hcrc_bytes) < 4:
                break
            (hcrc,) = struct.unpack("<I", hcrc_bytes)
            assert hcrc == masked_crc32c(header), "header crc mismatch"
            data = f.read(length)
            dcrc_bytes = f.read(4)
            if len(data) < length or len(dcrc_bytes) < 4:
                break
            (dcrc,) = struct.unpack("<I", dcrc_bytes)
            assert dcrc == masked_crc32c(data), "data crc mismatch"
            out.append(_parse_event(data))
    return [e for e in out if e is not None]


def _parse_event(data: bytes):
    i, step, tag, value = 0, 0, None, None

    def read_varint():
        nonlocal i
        shift, result = 0, 0
        while True:
            b = data[i]
            i += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    while i < len(data):
        key = read_varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            v = read_varint()
            if field == 2:
                step = v
        elif wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        elif wire == 2:
            ln = read_varint()
            payload = data[i:i + ln]
            i += ln
            if field == 5:  # summary
                j = 0

                def rv(buf, j):
                    shift, result = 0, 0
                    while True:
                        b = buf[j]
                        j += 1
                        result |= (b & 0x7F) << shift
                        if not b & 0x80:
                            return result, j
                        shift += 7

                key2, j = rv(payload, j)
                if key2 >> 3 == 1 and (key2 & 7) == 2:
                    ln2, j = rv(payload, j)
                    vmsg = payload[j:j + ln2]
                    k = 0
                    while k < len(vmsg):
                        key3, k = rv(vmsg, k)
                        f3, w3 = key3 >> 3, key3 & 7
                        if f3 == 1 and w3 == 2:
                            ln3, k = rv(vmsg, k)
                            tag = vmsg[k:k + ln3].decode()
                            k += ln3
                        elif f3 == 2 and w3 == 5:
                            (value,) = struct.unpack("<f", vmsg[k:k + 4])
                            k += 4
                        elif w3 == 2:
                            ln3, k = rv(vmsg, k)
                            k += ln3
                        elif w3 == 0:
                            _, k = rv(vmsg, k)
                        elif w3 == 5:
                            k += 4
                        else:
                            k += 8
    if tag is None:
        return None
    return (tag, value, step)
