"""Training visualization (reference: bigdl/visualization/)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.visualization.tensorboard import FileWriter, read_events


class Summary:
    """Base for Train/Validation summaries
    (reference: visualization/Summary.scala)."""

    def __init__(self, log_dir: str, app_name: str, suffix: str):
        self.log_dir = os.path.join(log_dir, app_name, suffix)
        self.writer = FileWriter(self.log_dir)
        self._triggers: Dict[str, object] = {}

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self.writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self.writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str) -> List[Tuple[str, float, int]]:
        """Read back scalars for `tag` (reference: Summary.readScalar)."""
        self.writer.flush()
        out = []
        for fname in sorted(os.listdir(self.log_dir)):
            if "tfevents" in fname:
                out.extend(e for e in read_events(os.path.join(self.log_dir, fname))
                           if e[0] == tag)
        return out

    def close(self) -> None:
        self.writer.close()


class TrainSummary(Summary):
    """Loss / Throughput / LearningRate scalars, optional parameter
    histograms (reference: visualization/TrainSummary.scala)."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        """Enable extra summaries; name in {"Parameters", "LearningRate",
        "Loss", "Throughput"} (reference: TrainSummary.setSummaryTrigger)."""
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Validation scalars keyed by ValidationMethod name
    (reference: visualization/ValidationSummary.scala)."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
