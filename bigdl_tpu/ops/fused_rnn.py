"""Persistent-RNN fused scan kernels — Pallas (Mosaic) TPU.

Reference parity: nn/Recurrent.scala (the reference's unrolled time
loop), nn/LSTM.scala, nn/GRU.scala, nn/BiRecurrent.scala. The math is
EXACTLY the hoisted-input protocol of `nn/recurrent.py`
(`step_precomputed`): the time-independent x·W_x half of every gate
matmul runs once outside as a full-sequence MXU matmul, and these
kernels run only the recurrent half — but with the ENTIRE time loop
inside one kernel launch instead of one XLA dispatch per `lax.scan`
step.

Why: the recurrent path is latency-floor-bound, not compute-bound
(PROFILE_r04 roofline: ~13 µs per sequential scan step at the BiLSTM
shape ⇒ 1.5% MFU; the (N,H)·(H,4H) recurrent matmul itself is ~0.2 µs
of MXU work). A `lax.scan` pays per-step dispatch and an HBM
round-trip of the (h, c) carry every timestep. Here:

* grid = (batch-tiles, T), time the minor sequential axis — ONE launch
  for the whole sequence; Mosaic streams the per-step input-projection
  block through VMEM while the previous step computes;
* the (h, c) carries live in VMEM scratch for the whole sweep — they
  NEVER touch HBM;
* the (N,H)·(H,4H) recurrent matmul is fused with the sigmoid/tanh
  gate elementwise block in the same kernel body (native-dtype MXU
  operands, f32 accumulation — the flash-attention convention);
* the bidirectional variant runs BOTH directions in one launch (the
  reverse direction reads/writes time-mirrored blocks via index maps,
  so no `jnp.flip` HBM passes and per-grid-cell overhead is amortized
  over twice the work);
* the backward is a `custom_vjp` with the same residency scheme: one
  reversed sweep, dh/dc carries in VMEM, gates recomputed from the
  saved activations (i, f, g, o and the cell-state sequence are the
  only residuals), dW_hh accumulated in a VMEM f32 scratch and
  emitted once per batch-tile.

Fallback: `impl="xla"` (auto-selected off-TPU, for hidden sizes that
are not lane-tileable (H % 128 != 0), and for H too large for the
VMEM-resident weight scheme) is the plain `lax.scan` this kernel
replaces — also the numeric oracle for the parity tests.

Env knobs (snapshotted at IMPORT via utils/envknobs — never read at
trace time; in-process sweeps call `envknobs.refresh()` after
mutating the environment): `BIGDL_FUSED_RNN=0` disables the kernels
(auto mode only); `BIGDL_FUSED_RNN_BLOCK_N` overrides the batch-tile
rows.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.ops.flash_attention import _tpu_compiler_params
from bigdl_tpu.utils import envknobs

# Above this hidden size the backward's VMEM residents no longer fit
# the kernel budget: at H the resident set is the (H, 4H) weight, the
# f32 dW output block + dW scratch (H·4H·4 B each), the dh/dc carries,
# and ~6 double-buffered (block_n, 4H)/(block_n, H) f32 per-step
# blocks. At H=1024 the dW pair alone is 32 MiB and the total tops
# ~100 MiB at block_n=512 — past _VMEM_LIMIT with no compile-time
# fallback — so eligibility caps at 512 (≈38 MiB at block_n=512,
# ≈25 MiB at the derated default tile below).
_MAX_HIDDEN = 512
_VMEM_LIMIT = 64 * 1024 * 1024


def _default_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover - backend init failure
        return "cpu"


def resolve_impl(hidden: int, impl: Optional[str] = None) -> str:
    """'pallas' | 'interpret' | 'xla'. Auto (None/'auto') picks the
    Mosaic kernel on TPU when the shape is kernel-eligible: the gate
    splits slice the lane dimension, so H must be a multiple of 128,
    and the resident weight scheme caps H at `_MAX_HIDDEN`.
    Unknown impl strings RAISE rather than silently degrading to the
    fallback — a typo'd 'palas' measuring the lax.scan path would be
    indistinguishable from real kernel data in a sweep."""
    if impl in ("pallas", "interpret", "xla"):
        return impl
    if impl not in (None, "auto"):
        raise ValueError(
            f"fused_rnn impl {impl!r}: expected None/'auto'/'pallas'/"
            f"'interpret'/'xla'")
    if not envknobs.FUSED_RNN_ENABLED:
        return "xla"
    if _default_platform() != "tpu":
        return "xla"
    if hidden % 128 != 0 or hidden > _MAX_HIDDEN:
        return "xla"
    return "pallas"


def _pad_batch(n: int, block_n: Optional[int],
               hidden: int) -> Tuple[int, int]:
    """(padded_n, block_n): batch rows padded to a sublane-tileable
    block multiple (16 covers bf16's (16, 128) min tile). The default
    tile derates with H so the backward's per-step f32 blocks stay
    within the VMEM budget (see _MAX_HIDDEN note); explicit/env
    overrides are trusted as-is (sweep knobs)."""
    n16 = ((n + 15) // 16) * 16
    bn = block_n or envknobs.FUSED_RNN_BLOCK_N \
        or (512 if hidden <= 256 else 256)
    bn = min(((bn + 15) // 16) * 16, n16)
    return ((n16 + bn - 1) // bn) * bn, bn


# --------------------------------------------------------------------------
# LSTM — shared per-direction step bodies
# --------------------------------------------------------------------------

def _lstm_gate_math(z, c_prev, h):
    """z (bn, 4H) f32 pre-activations, c_prev (bn, H) f32 → (h_new, c,
    gates) with gates the ACTIVATED (i, f, g, o) concat — the backward's
    residual. MUST match nn/recurrent.LSTM._gates bit-for-math."""
    i = jax.nn.sigmoid(z[:, :h])
    f = jax.nn.sigmoid(z[:, h:2 * h])
    g = jnp.tanh(z[:, 2 * h:3 * h])
    o = jax.nn.sigmoid(z[:, 3 * h:])
    c = f * c_prev + i * g
    hy = o * jnp.tanh(c)
    return hy, c, jnp.concatenate([i, f, g, o], axis=-1)


def _lstm_fwd_dir(zx_ref, w_ref, ys_ref, c_ref, g_ref, h_scr, c_scr,
                  hidden):
    """One direction's fused step: recurrent matmul + gate block, carries
    in VMEM scratch, residuals (gates, c) written to this step's block.
    c_ref/g_ref are None on the inference-only (no-residual) variant —
    then gates/c die in registers and HBM sees only ys."""
    z = zx_ref[0].astype(jnp.float32) + lax.dot_general(
        h_scr[:].astype(w_ref.dtype), w_ref[:],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    hy, c, gates = _lstm_gate_math(z, c_scr[:], hidden)
    h_scr[:] = hy
    c_scr[:] = c
    ys_ref[0] = hy.astype(ys_ref.dtype)
    if c_ref is not None:
        c_ref[0] = c.astype(c_ref.dtype)
        g_ref[0] = gates.astype(g_ref.dtype)


def _lstm_bwd_dir(w_ref, g_ref, c_ref, cp_ref, hp_ref, dy_ref, dzx_ref,
                  dh_scr, dc_scr, dw_scr, live, hidden):
    """One direction's backward step (reversed sweep): recompute the
    cell derivative chain from the saved gate activations, carry dh/dc
    in VMEM, accumulate dW_hh in f32 scratch. `live` is 0.0 at the
    direction's FIRST timestep (h_prev/c_prev are the zero init)."""
    gates = g_ref[0].astype(jnp.float32)
    i = gates[:, :hidden]
    f = gates[:, hidden:2 * hidden]
    g = gates[:, 2 * hidden:3 * hidden]
    o = gates[:, 3 * hidden:]
    c = c_ref[0].astype(jnp.float32)
    c_prev = cp_ref[0].astype(jnp.float32) * live
    h_prev = hp_ref[0].astype(jnp.float32) * live
    dh = dy_ref[0].astype(jnp.float32) + dh_scr[:]
    tc = jnp.tanh(c)
    do_pre = dh * tc * o * (1.0 - o)
    dc = dc_scr[:] + dh * o * (1.0 - tc * tc)
    di_pre = dc * g * i * (1.0 - i)
    df_pre = dc * c_prev * f * (1.0 - f)
    dg_pre = dc * i * (1.0 - g * g)
    dz = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)
    dzx_ref[0] = dz.astype(dzx_ref.dtype)
    dzn = dz.astype(w_ref.dtype)
    dh_scr[:] = lax.dot_general(
        dzn, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f
    dw_scr[:] = dw_scr[:] + lax.dot_general(
        h_prev.astype(w_ref.dtype), dzn, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# --------------------------------------------------------------------------
# LSTM — unidirectional kernels
# --------------------------------------------------------------------------

def _lstm_fwd_kernel(zx_ref, w_ref, ys_ref, c_ref, g_ref, h_scr, c_scr,
                     *, hidden):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    _lstm_fwd_dir(zx_ref, w_ref, ys_ref, c_ref, g_ref, h_scr, c_scr,
                  hidden)


def _lstm_fwd_infer_kernel(zx_ref, w_ref, ys_ref, h_scr, c_scr, *,
                           hidden):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    _lstm_fwd_dir(zx_ref, w_ref, ys_ref, None, None, h_scr, c_scr,
                  hidden)


def _lstm_bwd_kernel(w_ref, g_ref, c_ref, cp_ref, hp_ref, dy_ref,
                     dzx_ref, dw_ref, dh_scr, dc_scr, dw_scr, *, hidden,
                     n_t):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    live = jnp.where(s == n_t - 1, 0.0, 1.0)  # t == 0 has zero carry-in
    _lstm_bwd_dir(w_ref, g_ref, c_ref, cp_ref, hp_ref, dy_ref, dzx_ref,
                  dh_scr, dc_scr, dw_scr, live, hidden)

    @pl.when(s == n_t - 1)
    def _emit():
        dw_ref[0] = dw_scr[:].astype(dw_ref.dtype)


def _lstm_fwd_pallas(zx, w, block_n, interpret, save_residuals=True):
    """zx (T, N, 4H) scan-major, N a block_n multiple → (ys, c_seq,
    gates), all (T, N, ·). `save_residuals=False` (the inference-only
    primal — no vjp will consume them) emits just ys: pallas outputs
    are opaque to XLA DCE, so unwanted residuals would cost real HBM
    writes."""
    from jax.experimental.pallas import tpu as pltpu

    n_t, n, h4 = zx.shape
    hidden = h4 // 4
    blk = pl.BlockSpec((1, block_n, hidden), lambda b, t: (t, b, 0))
    blk4 = pl.BlockSpec((1, block_n, h4), lambda b, t: (t, b, 0))
    kernel = _lstm_fwd_kernel if save_residuals else _lstm_fwd_infer_kernel
    out = pl.pallas_call(
        functools.partial(kernel, hidden=hidden),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            blk4,
            pl.BlockSpec((hidden, h4), lambda b, t: (0, 0)),
        ],
        out_specs=[blk, blk, blk4] if save_residuals else [blk],
        out_shape=(
            [jax.ShapeDtypeStruct((n_t, n, hidden), zx.dtype),
             jax.ShapeDtypeStruct((n_t, n, hidden), zx.dtype),
             jax.ShapeDtypeStruct((n_t, n, h4), zx.dtype)]
            if save_residuals
            else [jax.ShapeDtypeStruct((n_t, n, hidden), zx.dtype)]),
        scratch_shapes=[pltpu.VMEM((block_n, hidden), jnp.float32),
                        pltpu.VMEM((block_n, hidden), jnp.float32)],
        interpret=interpret,
    )(zx, w)
    return out if save_residuals else (out[0], None, None)


def _lstm_bwd_pallas(w, ys, c_seq, gates, dy, block_n, interpret):
    """Reversed sweep; prev-step (h, c) come from the saved sequences
    via shifted index maps (clamped at t=0 and zeroed in-kernel)."""
    from jax.experimental.pallas import tpu as pltpu

    n_t, n, h4 = gates.shape
    hidden = h4 // 4
    at_t = lambda b, s: (n_t - 1 - s, b, 0)
    at_prev = lambda b, s: (jnp.maximum(n_t - 2 - s, 0), b, 0)
    dzx, dw = pl.pallas_call(
        functools.partial(_lstm_bwd_kernel, hidden=hidden, n_t=n_t),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            pl.BlockSpec((hidden, h4), lambda b, s: (0, 0)),       # w
            pl.BlockSpec((1, block_n, h4), at_t),                  # gates
            pl.BlockSpec((1, block_n, hidden), at_t),              # c
            pl.BlockSpec((1, block_n, hidden), at_prev),           # c_prev
            pl.BlockSpec((1, block_n, hidden), at_prev),           # h_prev
            pl.BlockSpec((1, block_n, hidden), at_t),              # dy
        ],
        out_specs=[
            pl.BlockSpec((1, block_n, h4), at_t),                  # dzx
            pl.BlockSpec((1, hidden, h4), lambda b, s: (b, 0, 0)),  # dw
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, n, h4), gates.dtype),
            jax.ShapeDtypeStruct((n // block_n, hidden, h4),
                                 jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, hidden), jnp.float32),
                        pltpu.VMEM((block_n, hidden), jnp.float32),
                        pltpu.VMEM((hidden, h4), jnp.float32)],
        interpret=interpret,
    )(w, gates, c_seq, c_seq, ys, dy)
    return dzx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_core(zx, w, cfg):
    # primal-only call (inference / no grad requested): skip residuals
    ys, _, _ = _lstm_fwd_pallas(zx, w, *cfg, save_residuals=False)
    return ys


def _lstm_core_fwd(zx, w, cfg):
    ys, c_seq, gates = _lstm_fwd_pallas(zx, w, *cfg)
    return ys, (w, ys, c_seq, gates)


def _lstm_core_bwd(cfg, res, dy):
    w, ys, c_seq, gates = res
    dzx, dw = _lstm_bwd_pallas(w, ys, c_seq, gates, dy, *cfg)
    return dzx, jnp.sum(dw, axis=0).astype(w.dtype)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def _lstm_scan_xla(zx, w_hh):
    """`lax.scan` fallback/oracle — byte-for-byte the math of
    nn/recurrent.LSTM.step_precomputed."""
    n, n_t, h4 = zx.shape
    h = h4 // 4

    def body(carry, z_t):
        h_prev, c_prev = carry
        z = z_t + h_prev @ w_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        hy = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (hy, c), hy

    z0 = jnp.zeros((n, h), zx.dtype)
    _, ys = lax.scan(body, (z0, z0), jnp.swapaxes(zx, 0, 1))
    return jnp.swapaxes(ys, 0, 1)


def lstm_scan(zx: jax.Array, w_hh: jax.Array,
              impl: Optional[str] = None,
              block_n: Optional[int] = None) -> jax.Array:
    """Run the whole LSTM time loop in one persistent kernel.

    zx: (N, T, 4H) hoisted input projections INCLUDING bias (the
    `precompute_inputs` output); w_hh: (H, 4H) recurrent weight.
    Returns the hidden-state sequence (N, T, H). Differentiable wrt
    both args (custom_vjp on the kernel path).
    """
    n, n_t, h4 = zx.shape
    hidden = w_hh.shape[0]
    impl = resolve_impl(hidden, impl)
    if impl == "xla":
        return _lstm_scan_xla(zx, w_hh)
    n_pad, bn = _pad_batch(n, block_n, hidden)
    zx_t = jnp.swapaxes(zx, 0, 1)
    if n_pad != n:
        zx_t = jnp.pad(zx_t, ((0, 0), (0, n_pad - n), (0, 0)))
    ys = _lstm_core(zx_t, w_hh, (bn, impl == "interpret"))
    return jnp.swapaxes(ys[:, :n], 0, 1)


# --------------------------------------------------------------------------
# LSTM — fused bidirectional kernels (both directions, one launch)
# --------------------------------------------------------------------------

def _bilstm_fwd_kernel(zxf_ref, zxb_ref, wf_ref, wb_ref,
                       ysf_ref, cf_ref, gf_ref, ysb_ref, cb_ref, gb_ref,
                       hf_scr, cf_scr, hb_scr, cb_scr, *, hidden):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        for scr in (hf_scr, cf_scr, hb_scr, cb_scr):
            scr[:] = jnp.zeros_like(scr)

    # forward direction at time t; reverse direction at time T-1-t —
    # its blocks arrive/depart time-mirrored via the index maps, so
    # both advance one step per grid cell
    _lstm_fwd_dir(zxf_ref, wf_ref, ysf_ref, cf_ref, gf_ref, hf_scr,
                  cf_scr, hidden)
    _lstm_fwd_dir(zxb_ref, wb_ref, ysb_ref, cb_ref, gb_ref, hb_scr,
                  cb_scr, hidden)


def _bilstm_fwd_infer_kernel(zxf_ref, zxb_ref, wf_ref, wb_ref,
                             ysf_ref, ysb_ref,
                             hf_scr, cf_scr, hb_scr, cb_scr, *, hidden):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        for scr in (hf_scr, cf_scr, hb_scr, cb_scr):
            scr[:] = jnp.zeros_like(scr)

    _lstm_fwd_dir(zxf_ref, wf_ref, ysf_ref, None, None, hf_scr, cf_scr,
                  hidden)
    _lstm_fwd_dir(zxb_ref, wb_ref, ysb_ref, None, None, hb_scr, cb_scr,
                  hidden)


def _bilstm_bwd_kernel(wf_ref, wb_ref,
                       gf_ref, cf_ref, cpf_ref, hpf_ref, dyf_ref,
                       gb_ref, cb_ref, cpb_ref, hpb_ref, dyb_ref,
                       dzxf_ref, dzxb_ref, dwf_ref, dwb_ref,
                       dhf_scr, dcf_scr, dwf_scr,
                       dhb_scr, dcb_scr, dwb_scr, *, hidden, n_t):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _init():
        for scr in (dhf_scr, dcf_scr, dwf_scr, dhb_scr, dcb_scr,
                    dwb_scr):
            scr[:] = jnp.zeros_like(scr)

    # fwd direction: backward sweep t = T-1-s; first step (zero
    # carry-in) is t == 0. bwd direction: ITS time runs u = T-1 → 0, so
    # its backward sweep is u = s, and its first step is u == T-1.
    live_f = jnp.where(s == n_t - 1, 0.0, 1.0)
    live_b = jnp.where(s == n_t - 1, 0.0, 1.0)
    _lstm_bwd_dir(wf_ref, gf_ref, cf_ref, cpf_ref, hpf_ref, dyf_ref,
                  dzxf_ref, dhf_scr, dcf_scr, dwf_scr, live_f, hidden)
    _lstm_bwd_dir(wb_ref, gb_ref, cb_ref, cpb_ref, hpb_ref, dyb_ref,
                  dzxb_ref, dhb_scr, dcb_scr, dwb_scr, live_b, hidden)

    @pl.when(s == n_t - 1)
    def _emit():
        dwf_ref[0] = dwf_scr[:].astype(dwf_ref.dtype)
        dwb_ref[0] = dwb_scr[:].astype(dwb_ref.dtype)


def _bilstm_fwd_pallas(zxf, zxb, wf, wb, block_n, interpret,
                       save_residuals=True):
    from jax.experimental.pallas import tpu as pltpu

    n_t, n, h4 = zxf.shape
    hidden = h4 // 4
    at_t = lambda b, t: (t, b, 0)
    at_rev = lambda b, t: (n_t - 1 - t, b, 0)
    w_spec = pl.BlockSpec((hidden, h4), lambda b, t: (0, 0))
    blk = lambda width: (1, block_n, width)
    ys_shape = jax.ShapeDtypeStruct((n_t, n, hidden), zxf.dtype)
    if save_residuals:
        kernel = _bilstm_fwd_kernel
        out_specs = [
            pl.BlockSpec(blk(hidden), at_t),    # ys_f
            pl.BlockSpec(blk(hidden), at_t),    # c_f
            pl.BlockSpec(blk(h4), at_t),        # gates_f
            pl.BlockSpec(blk(hidden), at_rev),  # ys_b (true-time slots)
            pl.BlockSpec(blk(hidden), at_rev),  # c_b
            pl.BlockSpec(blk(h4), at_rev),      # gates_b
        ]
        out_shape = [
            ys_shape, ys_shape,
            jax.ShapeDtypeStruct((n_t, n, h4), zxf.dtype),
            ys_shape, ys_shape,
            jax.ShapeDtypeStruct((n_t, n, h4), zxb.dtype),
        ]
    else:
        kernel = _bilstm_fwd_infer_kernel
        out_specs = [pl.BlockSpec(blk(hidden), at_t),
                     pl.BlockSpec(blk(hidden), at_rev)]
        out_shape = [ys_shape, ys_shape]
    out = pl.pallas_call(
        functools.partial(kernel, hidden=hidden),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            pl.BlockSpec(blk(h4), at_t),        # zx fwd
            pl.BlockSpec(blk(h4), at_rev),      # zx bwd (time-mirrored)
            w_spec, w_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_n, hidden), jnp.float32)
                        for _ in range(4)],
        interpret=interpret,
    )(zxf, zxb, wf, wb)
    if save_residuals:
        return out
    return out[0], None, None, out[1], None, None


def _bilstm_bwd_pallas(wf, wb, res_f, res_b, dyf, dyb, block_n,
                       interpret):
    from jax.experimental.pallas import tpu as pltpu

    ysf, cf, gf = res_f
    ysb, cb, gb = res_b
    n_t, n, h4 = gf.shape
    hidden = h4 // 4
    # fwd dir processes t = T-1-s (prev block at t-1, clamped); bwd dir
    # processes its sweep at true-time u = s (ITS prev step lives at
    # u+1, clamped)
    f_t = lambda b, s: (n_t - 1 - s, b, 0)
    f_prev = lambda b, s: (jnp.maximum(n_t - 2 - s, 0), b, 0)
    b_t = lambda b, s: (s, b, 0)
    b_prev = lambda b, s: (jnp.minimum(s + 1, n_t - 1), b, 0)
    w_spec = pl.BlockSpec((hidden, h4), lambda b, s: (0, 0))
    blk = lambda width: (1, block_n, width)
    dw_spec = pl.BlockSpec((1, hidden, h4), lambda b, s: (b, 0, 0))
    dw_shape = jax.ShapeDtypeStruct((n // block_n, hidden, h4),
                                    jnp.float32)
    dzxf, dzxb, dwf, dwb = pl.pallas_call(
        functools.partial(_bilstm_bwd_kernel, hidden=hidden, n_t=n_t),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            w_spec, w_spec,
            pl.BlockSpec(blk(h4), f_t),          # gates_f
            pl.BlockSpec(blk(hidden), f_t),      # c_f
            pl.BlockSpec(blk(hidden), f_prev),   # c_f prev
            pl.BlockSpec(blk(hidden), f_prev),   # h_f prev
            pl.BlockSpec(blk(hidden), f_t),      # dy_f
            pl.BlockSpec(blk(h4), b_t),          # gates_b
            pl.BlockSpec(blk(hidden), b_t),      # c_b
            pl.BlockSpec(blk(hidden), b_prev),   # c_b prev
            pl.BlockSpec(blk(hidden), b_prev),   # h_b prev
            pl.BlockSpec(blk(hidden), b_t),      # dy_b
        ],
        out_specs=[
            pl.BlockSpec(blk(h4), f_t),          # dzx_f
            pl.BlockSpec(blk(h4), b_t),          # dzx_b
            dw_spec, dw_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, n, h4), gf.dtype),
            jax.ShapeDtypeStruct((n_t, n, h4), gb.dtype),
            dw_shape, dw_shape,
        ],
        scratch_shapes=(
            [pltpu.VMEM((block_n, hidden), jnp.float32)] * 2
            + [pltpu.VMEM((hidden, h4), jnp.float32)]
            + [pltpu.VMEM((block_n, hidden), jnp.float32)] * 2
            + [pltpu.VMEM((hidden, h4), jnp.float32)]),
        interpret=interpret,
    )(wf, wb, gf, cf, cf, ysf, dyf, gb, cb, cb, ysb, dyb)
    return dzxf, dzxb, dwf, dwb


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _bilstm_core(zxf, zxb, wf, wb, cfg):
    # primal-only call (inference / no grad requested): skip residuals
    ysf, _, _, ysb, _, _ = _bilstm_fwd_pallas(zxf, zxb, wf, wb, *cfg,
                                              save_residuals=False)
    return ysf, ysb


def _bilstm_core_fwd(zxf, zxb, wf, wb, cfg):
    ysf, cf, gf, ysb, cb, gb = _bilstm_fwd_pallas(zxf, zxb, wf, wb,
                                                  *cfg)
    return (ysf, ysb), (wf, wb, (ysf, cf, gf), (ysb, cb, gb))


def _bilstm_core_bwd(cfg, res, dys):
    wf, wb, res_f, res_b = res
    dzxf, dzxb, dwf, dwb = _bilstm_bwd_pallas(wf, wb, res_f, res_b,
                                              dys[0], dys[1], *cfg)
    return (dzxf, dzxb, jnp.sum(dwf, axis=0).astype(wf.dtype),
            jnp.sum(dwb, axis=0).astype(wb.dtype))


_bilstm_core.defvjp(_bilstm_core_fwd, _bilstm_core_bwd)


def bilstm_scan(zx_f: jax.Array, zx_b: jax.Array, w_f: jax.Array,
                w_b: jax.Array, impl: Optional[str] = None,
                block_n: Optional[int] = None
                ) -> Tuple[jax.Array, jax.Array]:
    """Both LSTM directions in ONE persistent launch.

    zx_f/zx_b: (N, T, 4H) hoisted projections of the SAME (unflipped)
    input through each direction's weights — the reverse direction's
    time mirroring happens inside via index maps, so the caller never
    pays a `jnp.flip`. Returns (ys_fwd, ys_bwd), BOTH in true time
    order (ys_bwd[t] is the reverse pass's state after consuming
    x[T-1..t]) — concatenate/add directly.
    """
    n, n_t, h4 = zx_f.shape
    hidden = w_f.shape[0]
    impl = resolve_impl(hidden, impl)
    if impl == "xla":
        ys_f = _lstm_scan_xla(zx_f, w_f)
        ys_b = jnp.flip(_lstm_scan_xla(jnp.flip(zx_b, axis=1), w_b),
                        axis=1)
        return ys_f, ys_b
    n_pad, bn = _pad_batch(n, block_n, hidden)
    zxf_t = jnp.swapaxes(zx_f, 0, 1)
    zxb_t = jnp.swapaxes(zx_b, 0, 1)
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n), (0, 0))
        zxf_t, zxb_t = jnp.pad(zxf_t, pad), jnp.pad(zxb_t, pad)
    ysf, ysb = _bilstm_core(zxf_t, zxb_t, w_f, w_b,
                            (bn, impl == "interpret"))
    return (jnp.swapaxes(ysf[:, :n], 0, 1),
            jnp.swapaxes(ysb[:, :n], 0, 1))


# --------------------------------------------------------------------------
# GRU — persistent kernel (uni-directional)
# --------------------------------------------------------------------------

def _gru_fwd_kernel(zg_ref, zc_ref, wg_ref, wc_ref, ys_ref, zr_ref,
                    cand_ref, h_scr, *, hidden):
    """zr_ref/cand_ref are None on the inference-only variant (see
    _lstm_fwd_dir)."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    h_prev = h_scr[:]
    zr = jax.nn.sigmoid(zg_ref[0].astype(jnp.float32) + lax.dot_general(
        h_prev.astype(wg_ref.dtype), wg_ref[:],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    z = zr[:, :hidden]
    r = zr[:, hidden:]
    rh = r * h_prev
    cand = jnp.tanh(zc_ref[0].astype(jnp.float32) + lax.dot_general(
        rh.astype(wc_ref.dtype), wc_ref[:],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    h = (1.0 - z) * h_prev + z * cand
    h_scr[:] = h
    ys_ref[0] = h.astype(ys_ref.dtype)
    if zr_ref is not None:
        zr_ref[0] = zr.astype(zr_ref.dtype)
        cand_ref[0] = cand.astype(cand_ref.dtype)


def _gru_fwd_infer_kernel(zg_ref, zc_ref, wg_ref, wc_ref, ys_ref,
                          h_scr, *, hidden):
    _gru_fwd_kernel(zg_ref, zc_ref, wg_ref, wc_ref, ys_ref, None, None,
                    h_scr, hidden=hidden)


def _gru_bwd_kernel(wg_ref, wc_ref, zr_ref, cand_ref, hp_ref, dy_ref,
                    dzg_ref, dzc_ref, dwg_ref, dwc_ref,
                    dh_scr, dwg_scr, dwc_scr, *, hidden, n_t):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dwg_scr[:] = jnp.zeros_like(dwg_scr)
        dwc_scr[:] = jnp.zeros_like(dwc_scr)

    live = jnp.where(s == n_t - 1, 0.0, 1.0)
    zr = zr_ref[0].astype(jnp.float32)
    z = zr[:, :hidden]
    r = zr[:, hidden:]
    cand = cand_ref[0].astype(jnp.float32)
    h_prev = hp_ref[0].astype(jnp.float32) * live
    dh = dy_ref[0].astype(jnp.float32) + dh_scr[:]
    dz = dh * (cand - h_prev)
    dcand_pre = dh * z * (1.0 - cand * cand)
    dh_prev = dh * (1.0 - z)
    dzc_ref[0] = dcand_pre.astype(dzc_ref.dtype)
    dcn = dcand_pre.astype(wc_ref.dtype)
    drh = lax.dot_general(dcn, wc_ref[:], (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dr = drh * h_prev
    dh_prev = dh_prev + drh * r
    dz_pre = dz * z * (1.0 - z)
    dr_pre = dr * r * (1.0 - r)
    dzr = jnp.concatenate([dz_pre, dr_pre], axis=-1)
    dzg_ref[0] = dzr.astype(dzg_ref.dtype)
    dzrn = dzr.astype(wg_ref.dtype)
    dh_scr[:] = dh_prev + lax.dot_general(
        dzrn, wg_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    hpn = h_prev.astype(wg_ref.dtype)
    dwg_scr[:] = dwg_scr[:] + lax.dot_general(
        hpn, dzrn, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dwc_scr[:] = dwc_scr[:] + lax.dot_general(
        (r * h_prev).astype(wc_ref.dtype), dcn,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(s == n_t - 1)
    def _emit():
        dwg_ref[0] = dwg_scr[:].astype(dwg_ref.dtype)
        dwc_ref[0] = dwc_scr[:].astype(dwc_ref.dtype)


def _gru_fwd_pallas(zg, zc, wg, wc, block_n, interpret,
                    save_residuals=True):
    from jax.experimental.pallas import tpu as pltpu

    n_t, n, h2 = zg.shape
    hidden = h2 // 2
    at_t = lambda b, t: (t, b, 0)
    blk = pl.BlockSpec((1, block_n, hidden), at_t)
    blk2 = pl.BlockSpec((1, block_n, h2), at_t)
    ys_shape = jax.ShapeDtypeStruct((n_t, n, hidden), zg.dtype)
    kernel = _gru_fwd_kernel if save_residuals else _gru_fwd_infer_kernel
    out = pl.pallas_call(
        functools.partial(kernel, hidden=hidden),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            blk2,
            blk,
            pl.BlockSpec((hidden, h2), lambda b, t: (0, 0)),
            pl.BlockSpec((hidden, hidden), lambda b, t: (0, 0)),
        ],
        out_specs=[blk, blk2, blk] if save_residuals else [blk],
        out_shape=(
            [ys_shape, jax.ShapeDtypeStruct((n_t, n, h2), zg.dtype),
             ys_shape] if save_residuals else [ys_shape]),
        scratch_shapes=[pltpu.VMEM((block_n, hidden), jnp.float32)],
        interpret=interpret,
    )(zg, zc, wg, wc)
    return out if save_residuals else (out[0], None, None)


def _gru_bwd_pallas(wg, wc, ys, zr_seq, cand_seq, dy, block_n,
                    interpret):
    from jax.experimental.pallas import tpu as pltpu

    n_t, n, h2 = zr_seq.shape
    hidden = h2 // 2
    at_t = lambda b, s: (n_t - 1 - s, b, 0)
    at_prev = lambda b, s: (jnp.maximum(n_t - 2 - s, 0), b, 0)
    return pl.pallas_call(
        functools.partial(_gru_bwd_kernel, hidden=hidden, n_t=n_t),
        grid=(n // block_n, n_t),
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT),
        in_specs=[
            pl.BlockSpec((hidden, h2), lambda b, s: (0, 0)),
            pl.BlockSpec((hidden, hidden), lambda b, s: (0, 0)),
            pl.BlockSpec((1, block_n, h2), at_t),                # zr
            pl.BlockSpec((1, block_n, hidden), at_t),            # cand
            pl.BlockSpec((1, block_n, hidden), at_prev),         # h_prev
            pl.BlockSpec((1, block_n, hidden), at_t),            # dy
        ],
        out_specs=[
            pl.BlockSpec((1, block_n, h2), at_t),                # dzg
            pl.BlockSpec((1, block_n, hidden), at_t),            # dzc
            pl.BlockSpec((1, hidden, h2), lambda b, s: (b, 0, 0)),
            pl.BlockSpec((1, hidden, hidden), lambda b, s: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, n, h2), zr_seq.dtype),
            jax.ShapeDtypeStruct((n_t, n, hidden), zr_seq.dtype),
            jax.ShapeDtypeStruct((n // block_n, hidden, h2),
                                 jnp.float32),
            jax.ShapeDtypeStruct((n // block_n, hidden, hidden),
                                 jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, hidden), jnp.float32),
                        pltpu.VMEM((hidden, h2), jnp.float32),
                        pltpu.VMEM((hidden, hidden), jnp.float32)],
        interpret=interpret,
    )(wg, wc, zr_seq, cand_seq, ys, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _gru_core(zg, zc, wg, wc, cfg):
    # primal-only call (inference / no grad requested): skip residuals
    ys, _, _ = _gru_fwd_pallas(zg, zc, wg, wc, *cfg,
                               save_residuals=False)
    return ys


def _gru_core_fwd(zg, zc, wg, wc, cfg):
    ys, zr_seq, cand_seq = _gru_fwd_pallas(zg, zc, wg, wc, *cfg)
    return ys, (wg, wc, ys, zr_seq, cand_seq)


def _gru_core_bwd(cfg, res, dy):
    wg, wc, ys, zr_seq, cand_seq = res
    dzg, dzc, dwg, dwc = _gru_bwd_pallas(wg, wc, ys, zr_seq, cand_seq,
                                         dy, *cfg)
    return (dzg, dzc, jnp.sum(dwg, axis=0).astype(wg.dtype),
            jnp.sum(dwc, axis=0).astype(wc.dtype))


_gru_core.defvjp(_gru_core_fwd, _gru_core_bwd)


def _gru_scan_xla(zg, zc, wg, wc):
    """`lax.scan` fallback/oracle — the math of
    nn/recurrent.GRU.step_precomputed."""
    n, n_t, h2 = zg.shape
    h = h2 // 2

    def body(carry, z_t):
        zg_t, zc_t = z_t
        zr = jax.nn.sigmoid(zg_t + carry @ wg)
        z, r = zr[:, :h], zr[:, h:]
        cand = jnp.tanh(zc_t + (r * carry) @ wc)
        h_new = (1.0 - z) * carry + z * cand
        return h_new, h_new

    h0 = jnp.zeros((n, h), zg.dtype)
    _, ys = lax.scan(body, h0, (jnp.swapaxes(zg, 0, 1),
                                jnp.swapaxes(zc, 0, 1)))
    return jnp.swapaxes(ys, 0, 1)


def gru_scan(zx_gates: jax.Array, zx_cand: jax.Array, w_g: jax.Array,
             w_c: jax.Array, impl: Optional[str] = None,
             block_n: Optional[int] = None) -> jax.Array:
    """Persistent GRU scan. zx_gates: (N, T, 2H) hoisted (z, r) gate
    projections (+bias); zx_cand: (N, T, H) hoisted candidate
    projection (+bias); w_g: (H, 2H); w_c: (H, H). Returns (N, T, H)."""
    n, n_t, h2 = zx_gates.shape
    hidden = w_g.shape[0]
    impl = resolve_impl(hidden, impl)
    if impl == "xla":
        return _gru_scan_xla(zx_gates, zx_cand, w_g, w_c)
    n_pad, bn = _pad_batch(n, block_n, hidden)
    zg_t = jnp.swapaxes(zx_gates, 0, 1)
    zc_t = jnp.swapaxes(zx_cand, 0, 1)
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n), (0, 0))
        zg_t, zc_t = jnp.pad(zg_t, pad), jnp.pad(zc_t, pad)
    ys = _gru_core(zg_t, zc_t, w_g, w_c, (bn, impl == "interpret"))
    return jnp.swapaxes(ys[:, :n], 0, 1)
