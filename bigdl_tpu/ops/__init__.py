"""Custom TPU ops — Pallas (Mosaic) kernels with XLA/jnp fallbacks.

This package is the framework's native-kernel layer: where the reference
ships hand-written MKL / MKL-DNN primitives behind JNI
(com.intel.analytics.bigdl.mkl.*, SURVEY.md §2.1), we ship Pallas kernels
compiled by Mosaic for the TPU's MXU/VPU — with jnp reference
implementations doubling as CPU fallbacks and numeric oracles.
"""

from bigdl_tpu.ops.flash_attention import (
    attention_reference,
    flash_attention,
    flash_attention_with_lse,
)
from bigdl_tpu.ops.fused_rnn import bilstm_scan, gru_scan, lstm_scan

__all__ = [
    "attention_reference",
    "bilstm_scan",
    "flash_attention",
    "flash_attention_with_lse",
    "gru_scan",
    "lstm_scan",
]
