"""Fused large-vocabulary losses.

The reference pairs `nn/LogSoftMax.scala` with `nn/ClassNLLCriterion.
scala` — fine at its vocabulary sizes. For a TPU LM head the pair is
the single largest HBM sink in the training step: materializing
(B, S, V) log-probs in fp32 at V=32k costs ~2 GB per copy and OOMs a
16 GB chip at batch 8 (measured, scripts/profile_lm.py round 2).

`softmax_cross_entropy_chunked` computes the same mean NLL directly
from hidden states and the head matrix, scanning over sequence chunks:
each chunk materializes only (B, chunk, V) logits, takes the LSE and
the picked logit, and is rematerialized in the backward
(`jax.checkpoint`). Peak memory drops from O(B·S·V) to O(B·chunk·V)
with the same numerics (fp32 logits inside the chunk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def build_train_loss(model, criterion, precision=None):
    """The single training-loss construction point for every optimizer
    (LocalOptimizer, DP/ZeRO-1 step, perf harness).

    Returns ``loss_call(params, mod_state, x, y, rng) -> (loss,
    new_state)`` in training mode. When the criterion implements the
    model-fusion protocol — ``criterion.fused_loss(model)`` returning a
    callable — that fused path is used instead of
    ``criterion(model.apply(...), y)``; e.g. nn.ChunkedSoftmaxCE +
    TransformerLM computes the LM loss from hidden states without ever
    materializing the (B, S, V) log-prob tensor this module's header
    describes as OOMing a 16 GB chip.
    """
    fuse = getattr(criterion, "fused_loss", None)
    fused = fuse(model) if callable(fuse) else None

    if fused is not None:
        def loss_call(p, mod_state, x, y, rng):
            if precision is not None:
                p = precision.cast_to_compute(p)
                x = precision.cast_to_compute(x)
            loss, new_state = fused({"params": p, "state": mod_state},
                                    x, y, rng)
            if precision is not None:
                new_state = precision.cast_to_output(new_state)
            return loss, new_state
        return loss_call

    def loss_call(p, mod_state, x, y, rng):
        if precision is not None:
            p = precision.cast_to_compute(p)
            x = precision.cast_to_compute(x)
        out, new_state = model.apply({"params": p, "state": mod_state}, x,
                                     training=True, rng=rng)
        if precision is not None:
            out = precision.cast_to_output(out)
            new_state = precision.cast_to_output(new_state)
        return criterion(out, y), new_state
    return loss_call


def softmax_cross_entropy_chunked(hidden: jax.Array, head: jax.Array,
                                  targets: jax.Array,
                                  chunk: int = 256) -> jax.Array:
    """Mean token NLL of `softmax(hidden @ head)` vs int targets.

    hidden: (B, S, E); head: (E, V); targets: (B, S) int. When `chunk`
    does not divide S, the largest divisor of S that is <= chunk is
    used instead (so S=384 with the default chunk=256 runs at 192);
    if even that divisor is tiny (< chunk/4 — prime/near-prime S), the
    scan would degrade to per-token matmuls, so we raise and ask for a
    padded sequence instead of silently compiling a pathological loop.
    """
    b, s, e = hidden.shape
    if s % chunk:
        best = max(d for d in range(1, min(chunk, s) + 1) if s % d == 0)
        if best * 4 < min(chunk, s):
            raise ValueError(
                f"no usable chunk size for sequence {s} (largest divisor "
                f"<= {chunk} is {best}); pad the sequence to a multiple "
                f"of a reasonable chunk")
        chunk = best
    n = s // chunk
    hc = hidden.reshape(b, n, chunk, e).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def one(h, t):
        logits = (h @ head).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, t[..., None],
                                     axis=-1)[..., 0]
        return (lse - picked).sum()

    def body(acc, xt):
        h, t = xt
        return acc + one(h, t), None

    tot, _ = lax.scan(body, jnp.zeros((), jnp.float32), (hc, tc))
    return tot / (b * s)
