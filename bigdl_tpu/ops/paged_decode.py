"""One-launch Pallas paged-attention decode kernel (ISSUE 17).

No reference counterpart (like ops/kv_cache.py: the reference's
inference surface is batch `Predictor.scala`). This is the serving
plane's decode-attention hot op in kernel form — the vLLM
PagedAttention shape on TPU: ONE `pl.pallas_call` whose BlockSpec
index maps read the block table DIRECTLY (scalar-prefetch operand), so
each grid step streams one pool block through VMEM. The XLA path pays
a `gather_block_cache` relayout — a full (B, H, nb*bs, D) HBM
materialization of every row's logical cache — on EVERY decode step;
here the gather happens block-by-block into a VMEM scratch and nothing
cache-shaped ever lands in HBM.

Grid: (batch, head-tiles, KV-block-tiles) — batch and heads parallel,
the KV sweep 'arbitrary' (it carries the scratch). Tiles come from the
`BIGDL_PAGED_DECODE_TILES` ("BTxHT") import-time snapshot
(utils/envknobs — never read env at trace time, graftlint
trace-env-read) or per-call arguments; both must divide the launch's
table width / head count (fail-fast, like the flash tiles).

Bit-identity contract: the kernel accumulates the FULL table extent
(nb*bs) in VMEM and runs ONE full-extent softmax per (row, head) —
deliberately NOT a streamed online softmax. Online accumulation
re-orders the fp32 sums block by block, which would detach the kernel
from `ops/kv_cache.paged_attention` (the oracle) and with it every
load-bearing bitwise pin built on the full-extent reduction discipline
(warm==cold, tp, speculative acceptance — ops/kv_cache.py module
docstring). The same Q=1 row is tiny (S·D floats per head), so the
full-extent scratch is cheap; what the kernel saves is the per-step
HBM relayout, not the softmax. Interpret-mode fp32 parity vs the
oracle is BITWISE and pinned by tests/test_paged_decode.py; bf16
pools carry a tolerance contract instead (the cast to fp32 happens at
VMEM load here vs post-gather there — same values, so fp32 stays
bitwise; bf16 is bitwise too but pinned only to tolerance). On-chip
(Mosaic-compiled) numerics are MEASUREMENT DEBT for the next TPU
session — scripts/validate_tpu.py re-verifies parity on hardware
before any TPU engine trusts `attn_impl="pallas"`.

Masking matches the oracle exactly: scores masked to -1e30 AFTER the
q·K^T dot (NaN laundering of poisoned masked keys), value rows beyond
the row clock zeroed at VMEM load (0.0 * NaN = NaN poison hygiene —
`block_attention`'s `valid` mask).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.utils import envknobs

_NEG_INF = -1e30


def _default_impl() -> str:
    """'pallas' on a TPU backend, 'interpret' elsewhere (CPU tests run
    the same kernel body through the Pallas interpreter)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - backend init failure
        platform = "cpu"
    return "pallas" if platform == "tpu" else "interpret"


def resolve_tiles(num_blocks: int, num_heads: int,
                  block_tile: Optional[int] = None,
                  head_tile: Optional[int] = None) -> Tuple[int, int]:
    """(block_tile, head_tile) for a launch: explicit args win, then
    the `BIGDL_PAGED_DECODE_TILES` import-time snapshot, then (1, 1).
    Both must DIVIDE the launch's table width / head count — the
    index-map routing streams whole pool blocks, so a ragged tile
    would either read past the table or silently widen the reduction
    extent (breaking oracle parity). Raise instead."""
    env = envknobs.PAGED_DECODE_TILES
    if block_tile is None:
        block_tile = env[0] if env is not None else 1
    if head_tile is None:
        head_tile = env[1] if env is not None else 1
    if block_tile < 1 or num_blocks % block_tile:
        raise ValueError(
            f"block_tile {block_tile} must divide the table width "
            f"{num_blocks} (BIGDL_PAGED_DECODE_TILES is 'BTxHT')")
    if head_tile < 1 or num_heads % head_tile:
        raise ValueError(
            f"head_tile {head_tile} must divide the head count "
            f"{num_heads} (BIGDL_PAGED_DECODE_TILES is 'BTxHT')")
    return block_tile, head_tile


def _pd_kernel(tbl_ref, pos_ref, q_ref, *refs, block_tile, head_tile,
               num_j, block_size, seq, sm_scale, dup_batch):
    """One grid cell: stream `block_tile` table-routed pool blocks
    into the (head_tile, seq, D) VMEM scratch; on the final KV sweep
    run the oracle's full-extent masked softmax per head."""
    k_refs = refs[:block_tile]
    v_refs = refs[block_tile:2 * block_tile]
    o_ref = refs[2 * block_tile]
    k_scr = refs[2 * block_tile + 1]
    v_scr = refs[2 * block_tile + 2]

    b = pl.program_id(0)
    j = pl.program_id(2)
    row_pos = pos_ref[b]

    for i in range(block_tile):
        base = (j * block_tile + i) * block_size
        kblk = k_refs[i][0].astype(jnp.float32)      # (ht, bs, D)
        vblk = v_refs[i][0].astype(jnp.float32)
        off = lax.broadcasted_iota(jnp.int32, (block_size, 1), 0)
        valid = (base + off) <= row_pos              # (bs, 1)
        k_scr[:, pl.ds(base, block_size), :] = kblk
        # zero value rows beyond the clock at load: 0-probability rows
        # must contribute exactly 0.0, never 0.0 * NaN (the oracle's
        # `valid` hygiene — block_attention)
        v_scr[:, pl.ds(base, block_size), :] = jnp.where(
            valid[None], vblk, 0.0)

    @pl.when(j == num_j - 1)
    def _finalize():
        col = lax.broadcasted_iota(jnp.int32, (1, 1, 1, seq), 3)
        visible = col <= row_pos                     # (1, 1, 1, S)
        # the dots mirror the oracle's einsum SHAPES exactly — 4D
        # batched dot_general, batch dims (0, 1), q extent 1 — not a
        # per-head 2D gemv: XLA CPU squeezes a total-batch-extent-1
        # dot onto a different (plain 2D) code path whose fp32
        # accumulation bits differ from the batched path; any extent
        # >= 2 agrees with the oracle's (B, H) extent per element
        # (measured, this session). So when this cell's extent would
        # be 1 but the LAUNCH has B*H > 1 rows, duplicate the row to
        # extent 2 and slice — one redundant (1, S) gemv, oracle bits
        q4 = q_ref[...].astype(jnp.float32)          # (1, ht, 1, D)
        k4 = k_scr[...][None]                        # (1, ht, S, D)
        v4 = v_scr[...][None]                        # (1, ht, S, D)
        if dup_batch:
            q4 = jnp.concatenate([q4, q4], axis=0)
            k4 = jnp.concatenate([k4, k4], axis=0)
            v4 = jnp.concatenate([v4, v4], axis=0)
        s = lax.dot_general(
            q4, k4, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)      # (n, ht, 1, S)
        s = s * sm_scale
        # mask AFTER the dot — launders NaN scores a poisoned masked
        # key row would produce (oracle convention)
        s = jnp.where(visible, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        probs = p / jnp.sum(p, axis=-1, keepdims=True)
        out = lax.dot_general(
            probs, v4, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)      # (n, ht, 1, D)
        o_ref[...] = out[:1].astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pool, v_pool, table, pos, sm_scale,
                         block_tile, head_tile, interpret):
    from jax.experimental.pallas import tpu as pltpu

    from bigdl_tpu.ops.flash_attention import _tpu_compiler_params

    b, h, _, d = q.shape
    nb = table.shape[1]
    bs = k_pool.shape[2]
    seq = nb * bs
    num_j = nb // block_tile

    kernel = functools.partial(
        _pd_kernel, block_tile=block_tile, head_tile=head_tile,
        num_j=num_j, block_size=bs, seq=seq, sm_scale=float(sm_scale),
        # parity: a cell whose dot batch extent would be 1 must not
        # take XLA's squeezed single-batch path when the oracle's
        # (B, H)-extent dot doesn't (see _finalize)
        dup_batch=(head_tile == 1 and b * h > 1))

    head_spec = pl.BlockSpec(
        (1, head_tile, 1, d), lambda bb, hh, jj, tbl, ps: (bb, hh, 0, 0))
    # one spec per streamed block: the index map routes pool block
    # tbl[b, j*bt + i] through VMEM — the table read happens at grid
    # scheduling time (scalar prefetch), never inside the kernel body
    kv_specs = [
        pl.BlockSpec(
            (1, head_tile, bs, d),
            (lambda bb, hh, jj, tbl, ps, _i=i:
             (tbl[bb, jj * block_tile + _i], hh, 0, 0)))
        for i in range(block_tile)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h // head_tile, num_j),
        in_specs=[head_spec] + kv_specs + kv_specs,
        out_specs=head_spec,
        scratch_shapes=[
            pltpu.VMEM((head_tile, seq, d), jnp.float32),
            pltpu.VMEM((head_tile, seq, d), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        # batch/head cells are independent; only the kv sweep carries
        # the scratch (flash-forward's convention)
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(table.astype(jnp.int32), pos.astype(jnp.int32), q,
      *([k_pool] * block_tile), *([v_pool] * block_tile))


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, table: jax.Array,
                           pos: jax.Array,
                           sm_scale: Optional[float] = None, *,
                           impl: Optional[str] = None,
                           block_tile: Optional[int] = None,
                           head_tile: Optional[int] = None) -> jax.Array:
    """Drop-in for `ops/kv_cache.paged_attention`: q (B, H, 1, D),
    pools (N, H, bs, D), table (B, nb) int32, pos (B,) row clocks →
    (B, H, 1, D).

    impl: None → auto ('pallas' on TPU, 'interpret' elsewhere);
    'xla' → the gather-then-attend oracle path (paged_attention
    verbatim — the engine's default off-TPU); 'pallas' | 'interpret'
    → the one-launch kernel. fp32 kernel output is BITWISE the oracle
    in interpret mode (module docstring); tiles via `block_tile` /
    `head_tile` or the `BIGDL_PAGED_DECODE_TILES` snapshot."""
    if q.shape[-2] != 1:
        raise ValueError(f"paged_decode_attention decodes one row, "
                         f"got q length {q.shape[-2]}")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    impl = impl or _default_impl()
    if impl == "xla":
        from bigdl_tpu.ops.kv_cache import paged_attention
        return paged_attention(q, k_pool, v_pool, table, pos, sm_scale)
    if impl not in ("pallas", "interpret"):
        raise ValueError(f"impl {impl!r}: expected 'xla', 'pallas' or "
                         "'interpret'")
    bt, ht = resolve_tiles(table.shape[1], q.shape[1], block_tile,
                           head_tile)
    return _paged_decode_pallas(q, k_pool, v_pool, table, pos,
                                float(sm_scale), bt, ht,
                                interpret=(impl == "interpret"))
