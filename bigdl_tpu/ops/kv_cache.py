"""KV-cache primitives for incremental (autoregressive) decode.

No reference counterpart: the reference's inference surface is batch
`Predictor.scala` (full forwards only). This is the serving-plane hot
op: a static-shape per-layer key/value cache plus an O(S)-per-token
attention read, so generating T tokens costs O(T·S) attention instead
of the O(T·S²) a full re-forward per token pays. Everything here is
shape-static — `max_len` is fixed at cache creation, writes are
position-indexed `dynamic_update_slice`s — so prefill and decode each
compile exactly once regardless of request lengths (the
continuous-batching contract, bigdl_tpu/serving/engine.py).

Layout: caches are (B, H, S, D) — batch-major so a serving engine can
splice one request's rows into a slot with a single
`dynamic_update_slice` and per-row positions stay independent
(continuous batching: every slot advances its own clock).

Numerics match bigdl_tpu/ops/flash_attention: fp32 score accumulation,
masked logits at -1e30 (never -inf), softmax in fp32, output cast back
to the value dtype. The cache may be held in bf16 (`dtype=` at
creation) — scores still accumulate in fp32.

Paged layout (ISSUE 8): the second cache family here pages the
per-layer cache into fixed-size blocks held in ONE preallocated
`(num_blocks, H, block_size, D)` pool per layer. A sequence's cache is
then a BLOCK TABLE — a static `(max_blocks,)` int32 row of pool
indices — instead of a contiguous `(S, ...)` buffer: eviction, slot
elasticity and prefix sharing become integer surgery on the table plus
host-side ref-counts (serving/kv_pool.py, serving/prefix_cache.py),
never a cache copy. Block 0 is RESERVED as a scratch block: unused
table entries point at it, inactive batch rows write their garbage
into it, and no reader ever sees it unmasked.

Bit-identity contract (the load-bearing bar of the prefix cache):
every attention read — multi-row suffix prefill and one-row decode —
spans the FULL gathered table extent with per-query masking, so the
reduction shapes (and therefore the fp32 accumulation order) are
independent of WHERE a position was computed: a KV row produced by a
cold bucket-64 prefill, a warm bucket-16 suffix prefill after a prefix
hit, or a donor request's earlier prefill is bitwise the same array,
and cached-prefix decode emits tokens bit-identical to cold decode
(pinned by tests/test_kv_pool.py and the serve_prefix drill). The one
deliberate asymmetry: Q=1 decode gemms lower to different kernels
than Q>=2 prefill gemms (measured on CPU XLA), so positions a decode
step wrote are NEVER shared — the serving engine caps reuse and tree
insertion at `(len(prompt) - 1) // block_size` full blocks, keeping
the re-decoded last prompt token (and everything generated) out of
shared blocks.

Host spill tier (ISSUE 16): the bit-identity contract is what makes a
host-RAM block tier possible at all — a tree block's content is
immutable after its prefill (COW discipline) and position-invariant in
the reduction, so a refcount-0 block can be fetched to pinned host
numpy (`jax.device_get` of the per-layer k/v block rows — the
HandoffPackage wire format), its pool slot reused, and the bytes later
`device_put`-scattered into ANY free block with only a block-table
patch: the re-admitted read is the same array bitwise, never a
recomputation. The tier lives entirely above this module
(serving/prefix_cache.py parks/re-admits nodes, serving/engine.py
prices the one batched fetch per spill event) — nothing here reads or
writes host state, and the warm==cold pins extend verbatim across a
spill/re-admit round trip (tests/test_kv_pool.py TestSpillTier + the
serve_spill drill).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def init_layer_cache(batch: int, num_heads: int, max_len: int,
                     head_dim: int, dtype=jnp.float32
                     ) -> Tuple[jax.Array, jax.Array]:
    """One layer's (k, v) cache, each (B, H, max_len, D), zero-filled.
    Zeros are safe: reads mask every position > the row's clock."""
    shape = (batch, num_heads, max_len, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def write_prefill(k_cache: jax.Array, v_cache: jax.Array,
                  k_new: jax.Array, v_new: jax.Array,
                  start: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Bulk-write a prompt's (B, H, S_p, D) keys/values at [start,
    start+S_p) — same offset for every row (prefill always lands a
    fresh slot at position 0)."""
    idx = (0, 0, start, 0)
    k_cache = lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), idx)
    v_cache = lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), idx)
    return k_cache, v_cache


def update_cache(k_cache: jax.Array, v_cache: jax.Array,
                 k_new: jax.Array, v_new: jax.Array,
                 pos: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write one decode step's (B, H, 1, D) keys/values at per-row
    positions `pos` (B,) int32. vmapped dynamic_update_slice → a
    batched scatter; shape-static, so the decode step compiles once."""

    def row(kc, vc, kn, vn, p):
        idx = (0, p, 0)
        return (lax.dynamic_update_slice(kc, kn.astype(kc.dtype), idx),
                lax.dynamic_update_slice(vc, vn.astype(vc.dtype), idx))

    return jax.vmap(row)(k_cache, v_cache, k_new, v_new, pos)


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array,
                     sm_scale: Optional[float] = None) -> jax.Array:
    """One query row per sequence against the cache: q (B, H, 1, D),
    caches (B, H, S, D), pos (B,) — the row's clock, i.e. the index the
    current token was just written at. Attends to positions <= pos
    (earlier garbage beyond the clock is masked; later slots are
    overwritten before ever becoming visible). Returns (B, H, 1, D).

    O(S·D) per token — the decode-path replacement for the O(S²·D)
    full-sequence attention."""
    if q.shape[-2] != 1:
        raise ValueError(f"cached_attention decodes one row, got q "
                         f"length {q.shape[-2]}")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    kf = k_cache.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), kf) * sm_scale
    seq = k_cache.shape[-2]
    visible = (jnp.arange(seq)[None, :] <= pos[:, None])  # (B, S)
    # the where AFTER the matmul also launders NaN scores a non-finite
    # masked KEY row would produce (poison hygiene, see below)
    s = jnp.where(visible[:, None, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    probs = p / jnp.sum(p, axis=-1, keepdims=True)
    # masked positions get probability exactly 0.0, but 0.0 * NaN = NaN:
    # a non-finite VALUE row beyond the clock (a poisoned request's
    # leftovers in a recycled slot — serving/engine.py poison
    # isolation) would leak into every later read of that slot unless
    # masked rows are zeroed before the weighted sum. Zeros leave
    # healthy traffic bit-identical (0-prob rows contributed 0 either
    # way); visible rows are untouched.
    vf = jnp.where(visible[:, None, :, None],
                   v_cache.astype(jnp.float32), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vf)
    return out.astype(q.dtype)


# --------------------------------------------------------------- paged

def init_block_pool(num_blocks: int, num_heads: int, block_size: int,
                    head_dim: int, dtype=jnp.float32
                    ) -> Tuple[jax.Array, jax.Array]:
    """One layer's paged (k, v) pool, each (num_blocks, H, block_size,
    D), zero-filled. Block 0 is the scratch block by convention (see
    module docstring); the host allocator (serving/kv_pool.py) never
    hands it out."""
    shape = (num_blocks, num_heads, block_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def write_prompt_blocks(k_pool: jax.Array, v_pool: jax.Array,
                        k_new: jax.Array, v_new: jax.Array,
                        block_ids: jax.Array
                        ) -> Tuple[jax.Array, jax.Array]:
    """Bulk-write one request's prefill keys/values (1, H, S, D) into
    the blocks `block_ids` (nb,) int32, nb = ceil(S / block_size).
    S pads up to nb*block_size with zeros inside the op (the pad
    positions sit beyond the row's clock, masked like any garbage).
    Shape-static per (S, nb): one executable per prefill bucket.
    `block_ids` must be distinct (the allocator guarantees it) — the
    scatter is then order-independent and deterministic."""
    if k_new.shape[0] != 1:
        raise ValueError("write_prompt_blocks writes one request "
                         f"(batch 1), got batch {k_new.shape[0]}")
    nb = block_ids.shape[0]
    _, h, s, d = k_new.shape
    bs = k_pool.shape[2]
    pad = nb * bs - s
    if pad < 0:
        raise ValueError(f"{nb} blocks of {bs} cannot hold {s} tokens")

    def blocked(x, pool):
        x = x[0].astype(pool.dtype)                 # (H, S, D)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        # (H, nb*bs, D) → (nb, H, bs, D): one row per destination block
        return x.reshape(h, nb, bs, d).transpose(1, 0, 2, 3)

    return (k_pool.at[block_ids].set(blocked(k_new, k_pool)),
            v_pool.at[block_ids].set(blocked(v_new, v_pool)))


def write_decode_blocks(k_pool: jax.Array, v_pool: jax.Array,
                        k_new: jax.Array, v_new: jax.Array,
                        block_ids: jax.Array, offsets: jax.Array
                        ) -> Tuple[jax.Array, jax.Array]:
    """Write one decode step's (B, H, 1, D) keys/values at per-row
    (block, offset) destinations — block_ids/offsets (B,) int32,
    derived from the block table and the row clocks. Active rows
    target distinct exclusive blocks (copy-on-write: shared blocks are
    read-only, the engine never routes a write at one); inactive rows
    all target the scratch block, whose content no reader ever sees
    unmasked, so colliding garbage writes there are harmless."""
    kv = k_new[:, :, 0, :].astype(k_pool.dtype)     # (B, H, D)
    vv = v_new[:, :, 0, :].astype(v_pool.dtype)
    return (k_pool.at[block_ids, :, offsets, :].set(kv),
            v_pool.at[block_ids, :, offsets, :].set(vv))


def gather_block_cache(pool: jax.Array, table: jax.Array) -> jax.Array:
    """Materialize each row's logical cache through its block table:
    pool (N, H, bs, D) gathered by table (B, nb) → (B, H, nb*bs, D).
    A pure gather — values pass through bitwise, so attention over the
    gathered array equals attention over an equivalent contiguous
    cache bit-for-bit (tests/test_kv_pool.py pins it)."""
    g = pool[table]                                 # (B, nb, H, bs, D)
    b, nb, h, bs, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, nb * bs, d)


def block_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    visible: jax.Array, valid: jax.Array,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """Masked attention over a gathered block cache — the shared core
    of paged decode AND paged suffix prefill. q (B, H, Q, D), k/v
    (B, H, S, D), `visible` (B, Q, S) bool — per-query causal
    visibility; `valid` (B, S) bool — the union of visibility (the
    row's written region): value rows outside it are zeroed exactly,
    so garbage beyond the clock (scratch blocks, recycled content,
    a poisoned former occupant's NaN) can never ride a 0-probability
    into the weighted sum (0.0 * NaN = NaN — same hygiene as
    cached_attention). Same fp32 conventions as above."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk",
                   q.astype(jnp.float32), kf) * sm_scale
    # the where AFTER the matmul launders NaN scores a non-finite
    # masked KEY row would produce
    s = jnp.where(visible[:, None, :, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    probs = p / jnp.sum(p, axis=-1, keepdims=True)
    vf = jnp.where(valid[:, None, :, None], v.astype(jnp.float32), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vf)
    return out.astype(q.dtype)


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    table: jax.Array, pos: jax.Array,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """One query row per sequence against the paged pool: q
    (B, H, 1, D), pools (N, H, bs, D), table (B, nb), pos (B,) — the
    row clock, exactly as cached_attention. Gathers each row's blocks
    and attends positions <= pos over the FULL table extent (nb*bs),
    so the math is the dense cached_attention bit-for-bit when the
    visible content matches. Returns (B, H, 1, D)."""
    if q.shape[-2] != 1:
        raise ValueError(f"paged_attention decodes one row, got q "
                         f"length {q.shape[-2]}")
    kc = gather_block_cache(k_pool, table)
    vc = gather_block_cache(v_pool, table)
    seq = kc.shape[-2]
    visible = (jnp.arange(seq)[None, :] <= pos[:, None])    # (B, S)
    return block_attention(q, kc, vc, visible[:, None, :], visible,
                           sm_scale)
