"""KV-cache primitives for incremental (autoregressive) decode.

No reference counterpart: the reference's inference surface is batch
`Predictor.scala` (full forwards only). This is the serving-plane hot
op: a static-shape per-layer key/value cache plus an O(S)-per-token
attention read, so generating T tokens costs O(T·S) attention instead
of the O(T·S²) a full re-forward per token pays. Everything here is
shape-static — `max_len` is fixed at cache creation, writes are
position-indexed `dynamic_update_slice`s — so prefill and decode each
compile exactly once regardless of request lengths (the
continuous-batching contract, bigdl_tpu/serving/engine.py).

Layout: caches are (B, H, S, D) — batch-major so a serving engine can
splice one request's rows into a slot with a single
`dynamic_update_slice` and per-row positions stay independent
(continuous batching: every slot advances its own clock).

Numerics match bigdl_tpu/ops/flash_attention: fp32 score accumulation,
masked logits at -1e30 (never -inf), softmax in fp32, output cast back
to the value dtype. The cache may be held in bf16 (`dtype=` at
creation) — scores still accumulate in fp32.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def init_layer_cache(batch: int, num_heads: int, max_len: int,
                     head_dim: int, dtype=jnp.float32
                     ) -> Tuple[jax.Array, jax.Array]:
    """One layer's (k, v) cache, each (B, H, max_len, D), zero-filled.
    Zeros are safe: reads mask every position > the row's clock."""
    shape = (batch, num_heads, max_len, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def write_prefill(k_cache: jax.Array, v_cache: jax.Array,
                  k_new: jax.Array, v_new: jax.Array,
                  start: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Bulk-write a prompt's (B, H, S_p, D) keys/values at [start,
    start+S_p) — same offset for every row (prefill always lands a
    fresh slot at position 0)."""
    idx = (0, 0, start, 0)
    k_cache = lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), idx)
    v_cache = lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), idx)
    return k_cache, v_cache


def update_cache(k_cache: jax.Array, v_cache: jax.Array,
                 k_new: jax.Array, v_new: jax.Array,
                 pos: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write one decode step's (B, H, 1, D) keys/values at per-row
    positions `pos` (B,) int32. vmapped dynamic_update_slice → a
    batched scatter; shape-static, so the decode step compiles once."""

    def row(kc, vc, kn, vn, p):
        idx = (0, p, 0)
        return (lax.dynamic_update_slice(kc, kn.astype(kc.dtype), idx),
                lax.dynamic_update_slice(vc, vn.astype(vc.dtype), idx))

    return jax.vmap(row)(k_cache, v_cache, k_new, v_new, pos)


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array,
                     sm_scale: Optional[float] = None) -> jax.Array:
    """One query row per sequence against the cache: q (B, H, 1, D),
    caches (B, H, S, D), pos (B,) — the row's clock, i.e. the index the
    current token was just written at. Attends to positions <= pos
    (earlier garbage beyond the clock is masked; later slots are
    overwritten before ever becoming visible). Returns (B, H, 1, D).

    O(S·D) per token — the decode-path replacement for the O(S²·D)
    full-sequence attention."""
    if q.shape[-2] != 1:
        raise ValueError(f"cached_attention decodes one row, got q "
                         f"length {q.shape[-2]}")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    kf = k_cache.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), kf) * sm_scale
    seq = k_cache.shape[-2]
    visible = (jnp.arange(seq)[None, :] <= pos[:, None])  # (B, S)
    # the where AFTER the matmul also launders NaN scores a non-finite
    # masked KEY row would produce (poison hygiene, see below)
    s = jnp.where(visible[:, None, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    probs = p / jnp.sum(p, axis=-1, keepdims=True)
    # masked positions get probability exactly 0.0, but 0.0 * NaN = NaN:
    # a non-finite VALUE row beyond the clock (a poisoned request's
    # leftovers in a recycled slot — serving/engine.py poison
    # isolation) would leak into every later read of that slot unless
    # masked rows are zeroed before the weighted sum. Zeros leave
    # healthy traffic bit-identical (0-prob rows contributed 0 either
    # way); visible rows are untouched.
    vf = jnp.where(visible[:, None, :, None],
                   v_cache.astype(jnp.float32), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vf)
    return out.astype(q.dtype)
