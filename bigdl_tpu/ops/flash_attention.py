"""Flash attention — Pallas (Mosaic) TPU kernel with online softmax.

The reference has no attention at all (SURVEY.md §5.7: sequence handling
is unrolled-BPTT `nn/Recurrent.scala` only, bounded by one node's memory).
Long-context attention is this framework's TPU-first extension of that
subsystem, and the hot op is a real Pallas kernel — the TPU-native
counterpart of the reference's hand-tuned native MKL-DNN primitives
(SURVEY.md §2.1 native checklist).

Design
------
* Forward: `pl.pallas_call` over a (batch*heads, q_blocks, kv_blocks)
  grid. kv is the minor grid axis; an f32 VMEM accumulator plus running
  max / running sum scratch implement the online (streaming) softmax, so
  HBM traffic is O(S·D) and nothing of size S×S ever materializes. QK^T
  and P·V both run on the MXU via `dot_general` with f32 accumulation.
* Backward: two more Mosaic kernels — dq over a (bh, q, kv) grid and
  dk/dv over a (bh, kv, q) grid — recomputing probabilities from the
  saved log-sum-exp, VMEM accumulators, nothing S×S in HBM. (A
  blockwise `lax.scan` XLA backward remains for impl="xla".)
* The same math is exposed as `attention_reference` (jnp oracle for
  tests, CPU fallback), and `flash_attention_with_lse` returns the
  (out, lse) pair that the ring-attention combine consumes
  (bigdl_tpu/parallel/ring_attention.py).

Numerics: masked logits use a large finite negative (-1e30), not -inf,
so fully-masked rows produce zeros (not NaN) after normalization — the
convention the ring combine relies on.

Env tile overrides (`BIGDL_FLASH_FWD_TILES` / `BIGDL_FLASH_BWD_TILES`)
are snapshotted at IMPORT via utils/envknobs — never read at trace
time, so the value in the environment when `bigdl_tpu` is imported
wins and later env mutations are visibly inert (graftlint
`trace-env-read` guards the class). Sweeps set the env before the
process starts — or run each config in a fresh process, as the sweep
scripts do (scripts/sweep_attn_blocks.py,
scripts/sweep_attn_bwd_tiles.py); in-process rotation requires an
explicit `envknobs.refresh()` plus a fresh jit root per config.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.utils import envknobs

_NEG_INF = -1e30
_LOG2E = 1.4426950408889634  # MUST match between _bwd_recompute (s2) and _bwd_prep (lse2)


# --------------------------------------------------------------------------
# jnp oracle / CPU fallback
# --------------------------------------------------------------------------

def _tpu_compiler_params(pltpu, **kw):
    """pltpu.CompilerParams was TPUCompilerParams before jax 0.5 —
    same fields, renamed class."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kw)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    return_lse: bool = False,
    dropout: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
):
    """Plain softmax attention. q,k,v: (..., S, D); returns (..., S, D).

    Numeric oracle for the Pallas kernel and the non-TPU fallback.
    Materializes S×S — fine for tests and short sequences. `dropout`
    applies inverted dropout to the attention probabilities (the one
    path that needs them materialized; flash never does).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        q_len, k_len = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        col = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(col <= row + (k_len - q_len), s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    probs = p / l
    # fully-masked rows (possible when causal and seq_q > seq_k) emit
    # zeros, matching the kernel's convention
    probs = jnp.where(m > _NEG_INF / 2, probs, 0.0)
    if dropout > 0.0:
        if dropout_rng is None:
            raise ValueError("attention dropout needs dropout_rng")
        keep = 1.0 - dropout
        mask = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask, probs, 0.0) / keep
    out = jnp.einsum("...qk,...kd->...qd", probs.astype(v.dtype), v)
    if return_lse:
        lse = (m + jnp.log(l))[..., 0]
        return out, lse
    return out


# --------------------------------------------------------------------------
# Pallas forward kernel
# --------------------------------------------------------------------------

def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
               *, sm_scale, causal, block_q, block_k, seq_q, seq_k,
               num_kv):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute(masked):
        # dot NATIVE-dtype operands (bf16 on the training path) with f32
        # MXU accumulation; a pre-dot f32 cast would force the MXU into
        # multi-pass f32 mode (~3-6x slower on v5e). Scale applies to the
        # f32 s tile post-matmul (more accurate than pre-scaling bf16 q).
        q = q_ref[0]                                         # (bq, D)
        k = k_ref[0]                                         # (bk, D)
        s = lax.dot_general(q, k,
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)
        s = s * sm_scale
        if masked:
            col = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = col < seq_k
            if causal:
                # bottom-right alignment (query i sees keys ≤
                # i + seq_k-seq_q), matching attention_reference and
                # the blockwise backward
                row = q_start + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                mask = mask & (col <= row + (seq_k - seq_q))
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                                # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # zero masked columns explicitly: _NEG_INF is finite, so for a
        # fully-masked row exp(s - m_new) == 1 and the row would emit
        # mean(V) instead of the zeros the ring combine relies on
        p = jnp.exp(s - m_new)                               # (bq, bk)
        if masked:
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        acc = acc_scr[:] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, D)

        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    # a tile entirely in-bounds and (for causal) entirely below the
    # diagonal needs NO mask — skip the iota/where chain on the s tile
    # (the VPU elementwise chain is the fwd kernel's residual cost)
    in_bounds = k_start + block_k <= seq_k
    if causal:
        reachable = k_start <= q_start + block_q - 1 + (seq_k - seq_q)
        full = in_bounds & (k_start + block_k - 1
                            <= q_start + (seq_k - seq_q))

        @pl.when(full)
        def _():
            _compute(masked=False)

        @pl.when(reachable & jnp.logical_not(full))
        def _():
            _compute(masked=True)
    else:
        @pl.when(in_bounds)
        def _():
            _compute(masked=False)

        @pl.when(jnp.logical_not(in_bounds))
        def _():
            _compute(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(safe_l)
        lse = jnp.where(l == 0.0, _NEG_INF, lse)             # (bq, 1)
        # lane-broadcast: Mosaic requires the minor-most two block dims be
        # (8k, 128)-tileable, so lse rides a (bq, 128) block; the caller
        # reads lane 0
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                      interpret):
    """q,k,v: (BH, S, D) → (out (BH, S, D), lse (BH, S))."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, dim = q.shape
    seq_k = k.shape[1]

    qp = _pad_to(_pad_to(q, 1, block_q), 2, 128)
    kp = _pad_to(_pad_to(k, 1, block_k), 2, 128)
    vp = _pad_to(_pad_to(v, 1, block_k), 2, 128)
    sq, dp = qp.shape[1], qp.shape[2]
    sk = kp.shape[1]
    num_q, num_kv = sq // block_q, sk // block_k

    kernel = functools.partial(
        _fa_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=seq_q, seq_k=seq_k, num_kv=num_kv)

    out_p, lse_p = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_kv),
        # bh and q rows are independent; only the kv sweep carries the
        # online-softmax scratch. Marking them parallel lets Mosaic
        # overlap/reorder grid cells (the library kernel's convention).
        # vmem cap raised like the fused backward's so 2048-row tiles
        # compile (default 16 MiB rejects them).
        compiler_params=_tpu_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, dp), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out_p[:, :seq_q, :dim], lse_p[:, :seq_q, 0]


# --------------------------------------------------------------------------
# Pallas backward kernels (dq; dk/dv) — recompute-from-lse flash backward
# --------------------------------------------------------------------------

def _bwd_recompute(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   q_start, k_start, sm_scale, causal, block_q, block_k,
                   seq_q, seq_k, masked=True):
    """The shared dq/dkv recompute chain: (q, k, do, p, ds) for one
    (q_block, kv_block) tile — p from the saved lse, ds from delta.
    `q` comes back UNSCALED (dk needs it that way). `masked=False`
    skips the iota/where chain — only valid for tiles fully in-bounds
    on BOTH axes and (causal) entirely below the diagonal.

    All dots take NATIVE-dtype operands with f32 MXU accumulation (the
    library-kernel convention); q/k/do come back in native dtype and
    p/ds in f32 — callers cast p/ds to the operand dtype at their dots.
    A pre-dot f32 cast would force multi-pass f32 MXU mode (~3-6x
    slower on v5e) — measured as the dominant term of the round-4
    backward (PROFILE_r05).

    VPU-chain economies (the backward's bound is the elementwise chain
    over the s/p/ds tiles, not the MXU — PROFILE_r05 per-cell
    arithmetic): (1) p is computed in base 2 — _bwd_prep pre-multiplies
    lse by log2(e) and the s tile is scaled once by sm_scale·log2(e),
    so `exp2` needs no hidden ×log2(e) tile op; (2) `do` is pre-scaled
    by sm_scale at tile load (a (bq,D) op) and delta arrives pre-scaled
    from _bwd_prep, so ds = p·(dp′−delta′) drops its ×sm_scale tile op.
    Consequence for callers: the returned `do` is SCALED — dv
    accumulators must be divided by sm_scale once at finalize."""
    q = q_ref[0]                                             # (bq, D)
    k = k_ref[0]                                             # (bk, D)
    s2 = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32) \
        * (sm_scale * _LOG2E)
    lse2 = lse_ref[0, 0, pl.dslice(q_start, block_q)][:, None]
    delta = delta_ref[0, 0, pl.dslice(q_start, block_q)][:, None]
    if masked:
        row = q_start + lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0)
        col = k_start + lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1)
        # padded q rows must contribute nothing (dk/dv accumulate over
        # rows)
        mask = (col < seq_k) & (row < seq_q)
        if causal:
            mask = mask & (col <= row + (seq_k - seq_q))
        p = jnp.where(mask, jnp.exp2(s2 - lse2), 0.0)        # (bq, bk)
    else:
        p = jnp.exp2(s2 - lse2)
    if sm_scale == 0.0:  # degenerate static case: ds is exactly zero
        do = do_ref[0]
        ds = jnp.zeros_like(p)
        return q, k, do, p, ds
    do = (do_ref[0].astype(jnp.float32)
          * sm_scale).astype(do_ref.dtype)                   # (bq, D)
    dp = lax.dot_general(do, v_ref[0],
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return q, k, do, p, ds


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, sm_scale, causal, block_q,
                      block_k, seq_q, seq_k, num_kv):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        _, k, _, _, ds = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_start,
            k_start, sm_scale, causal, block_q, block_k, seq_q, seq_k)
        dq_scr[:] = dq_scr[:] + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(k_start <= q_start + block_q - 1 + (seq_k - seq_q))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale,
                       causal, block_q, block_k, seq_q, seq_k, num_q):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q, _, do, p, ds = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_start,
            k_start, sm_scale, causal, block_q, block_k, seq_q, seq_k)
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, D)
        # dk = ds^T @ q_unscaled
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks entirely above the diagonal contribute nothing
        @pl.when(q_start + block_q - 1 + (seq_k - seq_q) >= k_start)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        # do arrived pre-scaled by sm_scale (see _bwd_recompute)
        inv = 1.0 / sm_scale if sm_scale != 0.0 else 1.0
        dv_ref[0] = (dv_scr[:] * inv).astype(dv_ref.dtype)


def _bwd_prep(q, k, v, o, lse, do, block_q, block_k, sm_scale):
    """Shared backward setup (fused AND split wrappers): pad operands to
    block/lane multiples, precompute delta = sum(do*o), reshape lse and
    delta to the (BH, 1, sq) layout Mosaic accepts, and build the
    (bh, kv, q)-grid input BlockSpecs.

    lse ships PRE-MULTIPLIED by log2(e) and delta PRE-MULTIPLIED by
    sm_scale — the per-tile VPU economies _bwd_recompute documents."""
    qp = _pad_to(_pad_to(q, 1, block_q), 2, 128)
    dop = _pad_to(_pad_to(do, 1, block_q), 2, 128)
    kp = _pad_to(_pad_to(k, 1, block_k), 2, 128)
    vp = _pad_to(_pad_to(v, 1, block_k), 2, 128)
    sq, dp_ = qp.shape[1], qp.shape[2]
    sk = kp.shape[1]

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1) * sm_scale                      # (BH, Sq)
    # (BH, 1, sq): Mosaic wants the last two block dims (8,128)-tileable
    # OR equal to the array dims — (1, sq) matches exactly
    lse_p = _pad_to(lse.astype(jnp.float32) * _LOG2E,
                    1, block_q)[:, None, :]
    delta_p = _pad_to(delta, 1, block_q)[:, None, :]

    col_specs = [
        pl.BlockSpec((1, block_q, dp_), lambda b, j, i: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),   # v
        pl.BlockSpec((1, block_q, dp_), lambda b, j, i: (b, i, 0)),   # do
        pl.BlockSpec((1, 1, sq), lambda b, j, i: (b, 0, 0)),          # lse
        pl.BlockSpec((1, 1, sq), lambda b, j, i: (b, 0, 0)),          # delta
    ]
    return (qp, kp, vp, dop, lse_p, delta_p, sq, sk, dp_, col_specs)


def _fa_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr,
                         *, sm_scale, causal, block_q, block_k, seq_q,
                         seq_k, num_q, num_kv):
    """Single-pass backward: dk/dv over the (bh, kv, q) grid as before,
    with dq accumulated IN the same pass.

    The trick that makes one pass legal under Mosaic's output-revisit
    semantics: dq's output block is the WHOLE (seq, D) row plane with
    index map (b, 0, 0) — it never changes within a batch-head, so the
    block stays resident in VMEM across every (kv, q) cell and is
    flushed exactly once per bh. Each cell adds its ds·k contribution
    to the dq row-slice in a full-sequence f32 scratch, and the row
    slice is emitted during the final kv sweep. One s/p/ds recompute
    per tile instead of the two the split dq/dkv kernels pay, and half
    the grid cells.
    """
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when((ki == 0) & (qi == 0))
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute(masked):
        q, k, do, p, ds = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_start,
            k_start, sm_scale, causal, block_q, block_k, seq_q, seq_k,
            masked=masked)
        ds_n = ds.astype(q.dtype)
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, D)
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds_n, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_scr[pl.dslice(q_start, block_q)] = \
            dq_scr[pl.dslice(q_start, block_q)] + lax.dot_general(
                ds_n, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    # same unmasked fast path as the forward kernel, with the extra
    # q-rows-in-bounds requirement (padded rows feed dk/dv sums)
    full = (k_start + block_k <= seq_k) & (q_start + block_q <= seq_q)
    if causal:
        reachable = q_start + block_q - 1 + (seq_k - seq_q) >= k_start
        full = full & (k_start + block_k - 1
                       <= q_start + (seq_k - seq_q))

        @pl.when(full)
        def _():
            _compute(masked=False)

        @pl.when(reachable & jnp.logical_not(full))
        def _():
            _compute(masked=True)
    else:
        @pl.when(full)
        def _():
            _compute(masked=False)

        @pl.when(jnp.logical_not(full))
        def _():
            _compute(masked=True)

    @pl.when(qi == num_q - 1)
    def _finalize_dkv():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        # do arrived pre-scaled by sm_scale (see _bwd_recompute)
        inv = 1.0 / sm_scale if sm_scale != 0.0 else 1.0
        dv_ref[0] = (dv_scr[:] * inv).astype(dv_ref.dtype)

    # dq row-block i has received every contribution once the kv sweep
    # is past its diagonal; emitting during the LAST kv sweep is always
    # safe (later sweeps add nothing above the diagonal)
    @pl.when(ki == num_kv - 1)
    def _finalize_dq():
        dq_ref[0, pl.dslice(q_start, block_q)] = \
            dq_scr[pl.dslice(q_start, block_q)].astype(dq_ref.dtype)


def _flash_bwd_pallas_fused(q, k, v, o, lse, do, causal, sm_scale,
                            block_q, block_k, interpret):
    """One-kernel Mosaic backward (see _fa_bwd_fused_kernel). Falls
    back to the two-kernel form for very long sequences where the
    full-sequence dq scratch would crowd VMEM
    (_flash_bwd_pallas caller decides)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, dim = q.shape
    seq_k = k.shape[1]
    (qp, kp, vp, dop, lse_p, delta_p, sq, sk, dp_,
     col_specs) = _bwd_prep(q, k, v, o, lse, do, block_q, block_k,
                            sm_scale)
    num_q, num_kv = sq // block_q, sk // block_k

    dq_p, dk_p, dv_p = pl.pallas_call(
        functools.partial(
            _fa_bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=seq_q, seq_k=seq_k,
            num_q=num_q, num_kv=num_kv),
        grid=(bh, num_kv, num_q),
        # the full-sequence dq residents exceed Mosaic's default 16 MiB
        # scoped-vmem budget at long context (18.1 MiB at S=16384 with
        # native-dtype dots); v5e has 128 MiB — raise the kernel's cap.
        # Only bh is parallel: the dq plane persists across kv AND q.
        compiler_params=_tpu_compiler_params(pltpu,
            vmem_limit_bytes=64 * 1024 * 1024,
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        in_specs=col_specs,
        out_specs=[
            # whole dq row plane per bh: index map constant in (j, i),
            # so the block is flushed once per batch-head
            pl.BlockSpec((1, sq, dp_), lambda b, j, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, dp_), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, dp_), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, dp_), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((sq, dp_), jnp.float32),
                        pltpu.VMEM((block_k, dp_), jnp.float32),
                        pltpu.VMEM((block_k, dp_), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return (dq_p[:, :seq_q, :dim], dk_p[:, :seq_k, :dim],
            dv_p[:, :seq_k, :dim])


# Above this, the fused kernel's full-sequence VMEM residents (f32 dq
# scratch + dq output block in q.dtype) would crowd VMEM; use the
# two-kernel backward instead. 13 MiB admits the largest measured-good
# config (bf16 S=16384, D=64→128: 12.6 MiB resident, 70.9k tok/s —
# PROFILE_r04) while sending f32 S=16384 (16.8 MiB) to the split form.
_FUSED_BWD_MAX_RESIDENT_BYTES = 13 * 1024 * 1024


_FUSED_BWD_MAX_TILE = 1024 * 512  # bq*bk cap for the fused backward's
# DEFAULT tile derivation (512x1024 at the default fwd blocks). Round-5
# re-swept with the 64 MiB kernel-vmem limit: true 1024x1024 and
# kv-wide 1024x2048 tiles now COMPILE but are in-model neutral (186M:
# 259.4 vs 258.7 ms) to slightly worse (43M op-level 9.70/10.67 vs
# 9.43 ms) — PROFILE_r05/bwd_tile_sweep. Explicit bwd_tiles/env
# overrides bypass this cap entirely.


def resolve_bwd_form(seq_q: int, head_dim: int, itemsize: int,
                     block_q: int = 1024) -> str:
    """'fused' | 'split': which Mosaic backward a shape routes to.

    Mirrors the resident-bytes gate in `_flash_bwd_pallas` so SWEEPS
    can record (and refuse to mislabel) the kernel that actually runs:
    past the cap, a `bwd_tiles`/env override does NOT apply — the
    split backward tiles at the forward blocks. Recording this per row
    replaced the old trace-time "override ignored" warning (the
    ADVICE-r05 wrong-kernel-measurement hazard)."""
    sq_padded = ((seq_q + block_q - 1) // block_q) * block_q
    dp_padded = ((head_dim + 127) // 128) * 128
    resident = sq_padded * dp_padded * (4 + itemsize)
    return "fused" if resident <= _FUSED_BWD_MAX_RESIDENT_BYTES \
        else "split"


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, sm_scale, block_q,
                      block_k, interpret, bwd_tiles=None):
    sq_padded = ((q.shape[1] + block_q - 1) // block_q) * block_q
    dp_padded = ((q.shape[2] + 127) // 128) * 128
    # fused-path VMEM residents that scale with the FULL sequence: the
    # f32 dq scratch AND the dq output block (q.dtype) — both stay live
    # per batch-head (keep in sync with resolve_bwd_form above)
    resident = sq_padded * dp_padded * (4 + q.dtype.itemsize)
    if resident <= _FUSED_BWD_MAX_RESIDENT_BYTES:
        # the fused kernel's per-cell tiles cap lower than the split
        # kernels'. Default tie-break shrinks the Q tile first: the
        # round-5 sweep with native-dtype dots re-confirmed 512x1024 as
        # the optimum at the 186M shape (13.39 ms vs 13.58 at 1024x512,
        # 15.94 at 512x512 — PROFILE_r05/bwd_tile_sweep.log); the
        # serial kv loop amortizes better with a WIDE kv tile.
        # `bwd_tiles` overrides for experimentation.
        if bwd_tiles is None:
            bwd_tiles = envknobs.FLASH_BWD_TILES
        if bwd_tiles is not None:
            # explicit/env tiles are trusted as-is (only seq-clamped):
            # the auto-shrink below would silently rewrite a swept
            # override into a different config
            fb_q = _clamp_block(bwd_tiles[0], q.shape[1])
            fb_k = _clamp_block(bwd_tiles[1], k.shape[1])
        else:
            fb_q, fb_k = block_q, block_k
            while fb_q * fb_k > _FUSED_BWD_MAX_TILE:
                if fb_q >= fb_k:
                    fb_q //= 2
                else:
                    fb_k //= 2
        return _flash_bwd_pallas_fused(q, k, v, o, lse, do, causal,
                                       sm_scale, fb_q, fb_k, interpret)
    # NOTE: past the resident cap a bwd_tiles/env override does not
    # apply — the split backward tiles at the forward blocks. The old
    # trace-time "override ignored" warning is gone: env knobs can no
    # longer be resolved mid-trace (import-time snapshots, graftlint
    # trace-env-read), and sweep_attn_bwd_tiles.py records
    # `resolve_bwd_form` per row, skipping combos a split route would
    # mislabel.
    return _flash_bwd_pallas_split(q, k, v, o, lse, do, causal, sm_scale,
                                   block_q, block_k, interpret)


def _flash_bwd_pallas_split(q, k, v, o, lse, do, causal, sm_scale,
                            block_q, block_k, interpret):
    """Flash backward as two Mosaic kernels: dq over a (bh, q, kv) grid,
    dk/dv over a (bh, kv, q) grid, both recomputing probabilities from
    the forward's log-sum-exp (nothing S×S in HBM)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, dim = q.shape
    seq_k = k.shape[1]
    (qp, kp, vp, dop, lse_p, delta_p, sq, sk, dp_,
     col_specs) = _bwd_prep(q, k, v, o, lse, do, block_q, block_k,
                            sm_scale)
    num_q, num_kv = sq // block_q, sk // block_k

    # dq kernel iterates (bh, q, kv): same specs, swapped grid axes
    row_specs = [
        pl.BlockSpec((1, block_q, dp_), lambda b, i, j: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, dp_), lambda b, i, j: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, dp_), lambda b, i, j: (b, j, 0)),   # v
        pl.BlockSpec((1, block_q, dp_), lambda b, i, j: (b, i, 0)),   # do
        pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),          # lse
        pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),          # delta
    ]
    dq_p = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=seq_q, seq_k=seq_k,
            num_kv=num_kv),
        grid=(bh, num_q, num_kv),
        compiler_params=_tpu_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((1, block_q, dp_), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, dp_), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dp_), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    dk_p, dv_p = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=seq_q, seq_k=seq_k,
            num_q=num_q),
        grid=(bh, num_kv, num_q),
        compiler_params=_tpu_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        in_specs=col_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp_), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, dp_), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, dp_), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, dp_), jnp.float32),
                        pltpu.VMEM((block_k, dp_), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return (dq_p[:, :seq_q, :dim], dk_p[:, :seq_k, :dim],
            dv_p[:, :seq_k, :dim])


# --------------------------------------------------------------------------
# Blockwise XLA forward (online softmax, no S×S) — impl="xla"
# --------------------------------------------------------------------------

def _flash_fwd_xla(q, k, v, causal, sm_scale, block_k):
    """Flash forward as a `lax.scan` over KV blocks in plain XLA.

    Same online-softmax recurrence as the Pallas kernel, but expressed
    as jnp ops so XLA fuses the elementwise chain into the two matmuls
    per block. Memory O(S·block_k). This was the round-2 TPU default;
    since the Mosaic kernels were retuned (512x512 tiles) and gained a
    Mosaic backward it loses at every measured shape
    (PROFILE_r03/ANALYSIS.md) and remains as impl='xla' for comparison
    and as a fallback.
    """
    bh, seq_q, dim = q.shape
    seq_k = k.shape[1]
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    sk = kp.shape[1]
    num_kv = sk // block_k

    q32 = q.astype(jnp.float32) * sm_scale
    k_blocks = kp.reshape(bh, num_kv, block_k, dim).transpose(1, 0, 2, 3)
    v_blocks = vp.reshape(bh, num_kv, block_k, dim).transpose(1, 0, 2, 3)

    def step(carry, blk):
        m, l, acc = carry
        j, kb, vb = blk
        s = jnp.einsum("bqd,bkd->bqk", q32, kb.astype(jnp.float32))
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (seq_q, block_k), 1)
        mask = col < seq_k
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (seq_q, block_k), 0)
            mask = mask & (col <= row + (seq_k - seq_q))
        s = jnp.where(mask[None], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        # _NEG_INF is finite: for a fully-masked row s - m_new == 0, so a
        # bare exp would emit 1 per masked column. Zero masked columns
        # explicitly; fully-masked rows then keep l == 0 and hit the
        # zero-output guard below (the reference/ring-combine convention).
        p = jnp.where(mask[None], jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bqk,bkd->bqd", p, vb.astype(jnp.float32))
        return (m_new, l, acc), None

    m0 = jnp.full((bh, seq_q), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, seq_q), jnp.float32)
    acc0 = jnp.zeros((bh, seq_q, dim), jnp.float32)
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0),
                              (jnp.arange(num_kv), k_blocks, v_blocks))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(safe_l))
    return out, lse


# --------------------------------------------------------------------------
# Blockwise XLA backward (recompute from lse)
# --------------------------------------------------------------------------

def _flash_bwd_blockwise(q, k, v, o, lse, do, causal, sm_scale, block_k):
    """Flash backward via lax.scan over KV blocks; memory O(S·block_k)."""
    bh, seq_q, dim = q.shape
    seq_k = k.shape[1]
    pad_k = (-seq_k) % block_k
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    sk = kp.shape[1]
    num_kv = sk // block_k

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                  # (BH, Sq)
    k_blocks = kp.reshape(bh, num_kv, block_k, dim).transpose(1, 0, 2, 3)
    v_blocks = vp.reshape(bh, num_kv, block_k, dim).transpose(1, 0, 2, 3)

    q32, do32 = q.astype(jnp.float32), do.astype(jnp.float32)

    def step(dq_acc, blk):
        j, kb, vb = blk                                       # (BH, bk, D)
        s = jnp.einsum("bqd,bkd->bqk", q32,
                       kb.astype(jnp.float32)) * sm_scale
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (seq_q, block_k), 1)
        mask = col < seq_k
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (seq_q, block_k), 0)
            mask = mask & (col <= row + (seq_k - seq_q))
        p = jnp.where(mask[None], jnp.exp(s - lse[..., None]), 0.0)
        dv = jnp.einsum("bqk,bqd->bkd", p, do32)
        dp = jnp.einsum("bqd,bkd->bqk", do32, vb.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds,
                                     kb.astype(jnp.float32))
        dk = jnp.einsum("bqk,bqd->bkd", ds, q32)
        return dq_acc, (dk, dv)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, jnp.zeros_like(q32),
        (jnp.arange(num_kv), k_blocks, v_blocks))
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(bh, sk, dim)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(bh, sk, dim)
    if pad_k:
        dk, dv = dk[:, :seq_k], dv[:, :seq_k]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# Public entry with custom VJP
# --------------------------------------------------------------------------

def _forward(q, k, v, causal, sm_scale, block_q, block_k, impl):
    if impl == "reference":
        return attention_reference(q, k, v, causal, sm_scale,
                                   return_lse=True)
    if impl == "xla":
        return _flash_fwd_xla(q, k, v, causal, sm_scale, block_k)
    return _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                             interpret=(impl == "interpret"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_core(q, k, v, causal, sm_scale, block_q, block_k, bwd_block_k,
                impl, bwd_tiles):
    out, _ = _forward(q, k, v, causal, sm_scale, block_q, block_k, impl)
    return out


def _flash_core_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                    bwd_block_k, impl, bwd_tiles):
    out, lse = _forward(q, k, v, causal, sm_scale, block_q, block_k, impl)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(causal, sm_scale, block_q, block_k, bwd_block_k, impl,
                    bwd_tiles, res, do):
    q, k, v, out, lse = res
    if impl in ("pallas", "interpret"):
        # Mosaic backward; fused-kernel tiles chosen independently
        return _flash_bwd_pallas(q, k, v, out, lse, do, causal, sm_scale,
                                 block_q, block_k,
                                 interpret=(impl == "interpret"),
                                 bwd_tiles=bwd_tiles)
    return _flash_bwd_blockwise(q, k, v, out, lse, do, causal, sm_scale,
                                bwd_block_k)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _clamp_block(block: int, seq: int) -> int:
    """Clamp a block size to the (128-rounded-up) sequence length, so
    short sequences run a single Mosaic-tileable block."""
    return min(block, ((max(seq, 1) + 127) // 128) * 128)


def _resolve_impl_and_blocks(q, k, block_q, block_k, impl):
    """Shared default resolution for both public entry points: pick the
    impl (Mosaic kernels on TPU, reference elsewhere), then per-impl
    default tiles, clamped to the sequences.

    Mosaic default tiles are 1024x1024 (round-4 sweep: the grid-cell
    count, not the MXU, binds, so fewer/bigger cells win), EXCEPT the
    single-tile-per-bh regime bh<=64 AND S<=2048 where one whole-
    sequence 2048x2048 tile per batch-head wins (+3.6% in-model at the
    43M shape — PROFILE_r05/fwd2048_43m_inmodel_ab.log; at BH>=128
    2048-row tiles regress, r4+r5 sweeps). `BIGDL_FLASH_FWD_TILES=BQxBK`
    overrides when no explicit blocks are passed. The XLA scan keeps
    128."""
    impl = impl or _default_impl()
    big = impl in ("pallas", "interpret")
    env = envknobs.FLASH_FWD_TILES if big else None
    if env is not None and (block_q is None and block_k is None):
        block_q, block_k = env
    default = 1024
    if big and block_q is None and block_k is None:
        # single-tile-per-bh regime: at few batch*heads the grid has too
        # few cells to amortize per-cell overhead — one whole-sequence
        # tile per bh wins (43M in-model: 202.0k -> 209.4k tok/s,
        # +3.6%, PROFILE_r05). At BH>=128 2048-tiles regress (r4+r5
        # sweeps), and at long context the 1024 default stays.
        import math as _math

        bh = int(_math.prod(q.shape[:-2])) if q.ndim >= 3 else 1
        if bh <= 64 and q.shape[-2] <= 2048 and k.shape[-2] <= 2048:
            default = 2048
    block_q = _clamp_block(block_q or (default if big else 128),
                           q.shape[-2])
    block_k = _clamp_block(block_k or (default if big else 128),
                           k.shape[-2])
    return impl, block_q, block_k


def _default_impl() -> str:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - backend init failure
        platform = "cpu"
    if platform != "tpu":
        return "reference"
    # Round-3 full-step measurements on the real chip (S=2048, D=64,
    # remat, fused loss): with both the forward kernel (512x512 tiles)
    # AND the Mosaic backward (dq + dk/dv kernels), pallas wins at every
    # measured shape — 48.9k vs 27.5k tok/s at 186M (B*H=128) and
    # 150.7k vs 139.1k at 43M (B*H=64) against the round-2
    # blockwise-XLA-scan default. (Fwd-kernel-only, the 43M shape
    # preferred the scan — the Mosaic backward is what tipped it.)
    return "pallas"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    impl: Optional[str] = None,
    bwd_tiles: Optional[Tuple[int, int]] = None,
) -> jax.Array:
    """Memory-efficient attention. q,k,v: (B, H, S, D) or (BH, S, D).

    impl: None → auto ('pallas' on TPU — Mosaic forward AND backward
    kernels, fastest at every measured shape; 'reference' off-TPU);
    explicit choices: 'xla' (blockwise-scan fwd + scan bwd) | 'pallas'
    | 'interpret' (Pallas interpreter mode, for CPU tests) |
    'reference'.

    Block sizes default per impl from measurement: the Mosaic kernels
    want LARGE tiles — 1024x1024, or one whole-sequence 2048x2048 tile
    per batch-head when bh<=64 and S<=2048 (see
    _resolve_impl_and_blocks) — while the XLA scan wants SMALL kv
    blocks (128 — its per-block elementwise chain stays
    cache-resident). `BIGDL_FLASH_FWD_TILES` overrides the fwd default.
    `bwd_block_k` applies only to the impl='xla' scan backward.
    `bwd_tiles=(bq, bk)` overrides the FUSED Mosaic backward's tiles
    (default: the fwd blocks, q-tile halved first until bq·bk fits the
    VMEM cap — 512x1024 at the default fwd blocks, re-confirmed optimal
    by the round-5 sweep). All are clamped to the sequence lengths, so
    short sequences run a single-tile kernel.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    impl, block_q, block_k = _resolve_impl_and_blocks(
        q, k, block_q, block_k, impl)
    bwd_block_k = _clamp_block(bwd_block_k or 128, k.shape[-2])
    squeeze = q.ndim == 4
    if squeeze:
        b, h, s, d = q.shape
        sk = k.shape[2]
        q = q.reshape(b * h, s, d)
        k = k.reshape(b * h, sk, k.shape[-1])
        v = v.reshape(b * h, sk, v.shape[-1])
    out = _flash_core(q, k, v, causal, float(sm_scale), block_q, block_k,
                      bwd_block_k, impl,
                      None if bwd_tiles is None else tuple(bwd_tiles))
    if squeeze:
        out = out.reshape(b, h, s, -1)
    return out


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    impl: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(out, lse) for one KV chunk — a building block for callers that
    combine partial attention results themselves (online-softmax style).

    Not wrapped in the custom VJP, so the DEFAULT impl here is the
    AD-able 'xla' blockwise scan on TPU (the raw Mosaic kernel has no
    differentiation rule — pass impl='pallas' explicitly for a
    forward-only kernel call).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if impl is None:
        impl = "xla" if _default_impl() == "pallas" else _default_impl()
    impl, block_q, block_k = _resolve_impl_and_blocks(
        q, k, block_q, block_k, impl)
    return _forward(q, k, v, causal, float(sm_scale), block_q, block_k,
                    impl)
