"""shard_map compatibility shim.

jax moved `shard_map` out of `jax.experimental` and renamed its
`check_rep` flag to `check_vma` (jax >= 0.8). The mesh code in this
package is written against the new spelling; this shim lets the same
call run on either installed jax by translating the flag to whatever
the resolved function actually accepts. Every shard_map import in
bigdl_tpu goes through here — without it, the whole distributed plane
(and the CPU fault drill that tier-1 runs) breaks on a pre-0.8 jax.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


def axis_size(name: str) -> int:
    """STATIC size of the named mesh axis from inside shard_map.

    `jax.lax.axis_size` is newer than pre-0.5 jax; the fallback reads
    the axis frame (an int in those versions). Static matters: callers
    use it for Python loop bounds (ring attention's N-1 hops), where a
    traced `psum(1, axis)` would not do."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    from jax._src import core

    frame = core.axis_frame(name)
    return frame.size if hasattr(frame, "size") else frame
