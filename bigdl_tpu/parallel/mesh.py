"""Device-mesh construction.

Reference parity: the reference's "cluster topology" is Spark executors
discovered by utils/Engine.scala; its parameter plane assumes one
partition per executor (parameters/AllReduceParameter.scala#init). Here
topology is a `jax.sharding.Mesh` over PJRT devices; axes are named for
the parallelism they carry:

    data   — data parallelism (the reference's only strategy)
    model  — tensor parallelism (post-parity extension)
    seq    — sequence/context parallelism (ring attention)
    expert — expert parallelism (MoE)
    pipe   — pipeline stages

On real hardware, axis order maps onto the physical ICI torus: keep the
fastest-communicating axis (model/seq) innermost so its collectives ride
neighboring chips.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_axes(s: str) -> Dict[str, int]:
    """Parse a CLI mesh string like ``"data=8"`` or ``"data=4,model=2"``."""
    out: Dict[str, int] = {}
    for part in s.split(","):
        name, eq, size = part.partition("=")
        name = name.strip()
        if not eq or not name:
            raise ValueError(
                f"bad mesh spec {part!r} in {s!r}; expected name=size")
        try:
            out[name] = int(size)
        except ValueError:
            raise ValueError(
                f"bad mesh size {size!r} for axis {name!r} in {s!r}")
    return out


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from {axis_name: size}; sizes must multiply to the
    device count (one axis may be -1 to absorb the rest)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {"data": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded(mesh: Mesh, *axis_names: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axis_names))


def host_to_global(mesh: Mesh, spec: P, array: np.ndarray) -> jax.Array:
    """Build a global device array from per-host data.

    Reference parity: the reference's data plane keeps partitions
    executor-local and Spark never gathers them (SURVEY.md §5.8 "Spark
    only partitions data"); likewise each host here contributes only its
    local shard — on one process this is a plain sharded device_put.
    """
    if jax.process_count() == 1:
        return jax.device_put(array, NamedSharding(mesh, spec))
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), array)


def place_global(mesh: Mesh, spec: P, tree):
    """device_put a host-resident GLOBAL pytree onto the mesh under
    `spec`, multi-process safe.

    On a multi-process mesh, `jax.device_put` of host data onto a
    non-addressable sharding first runs a cross-process equality check
    (`multihost_utils.assert_equal`) — a collective that CPU backends
    (jax 0.4.x) cannot run outside jit. Every caller here already
    guarantees value equality across processes (deterministic init,
    checkpoint loads of the same files), so build each process's
    addressable shards locally via `make_array_from_callback` instead:
    no communication, same resulting global array. The weights/slots
    placement counterpart of `host_to_global` (which handles per-host
    DATA, where local shards genuinely differ)."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    return jax.tree_util.tree_map(put, tree)
