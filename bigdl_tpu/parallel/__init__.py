"""bigdl_tpu.parallel — mesh, collectives-based parameter plane, and
parallelism strategies (reference: bigdl/parameters/ + optim/DistriOptimizer)."""

from bigdl_tpu.parallel.mesh import (
    make_mesh, parse_axes, replicated, sharded, host_to_global,
)
from bigdl_tpu.parallel.data_parallel import (
    FlatParamSpec, make_dp_train_step, make_dp_eval_step,
)
from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
