"""bigdl_tpu.parallel — mesh, collectives-based parameter plane, and
parallelism strategies (reference: bigdl/parameters/ + optim/DistriOptimizer)."""

from bigdl_tpu.parallel.mesh import (
    make_mesh, parse_axes, replicated, sharded, host_to_global,
)
from bigdl_tpu.parallel.data_parallel import (
    FlatParamSpec, make_dp_accum_steps, make_dp_train_step,
    make_dp_eval_step,
)
from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
from bigdl_tpu.parallel.ring_attention import (
    make_ring_attention, ring_attention, ulysses_attention,
    zigzag_ring_attention,
)
from bigdl_tpu.parallel.tensor_parallel import (
    make_transformer_train_step, shard_params, slot_specs_for,
    transformer_tp_specs,
)
from bigdl_tpu.parallel.pipeline import (
    interleaved_bubble_fraction,
    make_pipeline_train_step,
    pipeline_bubble_fraction,
    pipeline_specs,
    to_virtual_layout,
)
from bigdl_tpu.parallel.moe import (
    MoE, make_moe_lm_train_step, moe_lm_specs, moe_specs,
)
