"""Mixture-of-Experts with expert parallelism over a mesh axis.

No reference counterpart (SURVEY.md §2.3 lists EP as absent); this is
the `expert` mesh axis. Switch-style top-1 routing with capacity:

    gates   = softmax(x @ router)                 (T, E)
    expert  = argmax(gates); position-in-expert via cumsum
    dispatch = onehot(expert) ∧ (position < capacity)   (T, E, C)
    expert_in  = dispatchᵀ x                      (E, C, D)
    --- all_to_all over the expert axis ---       each device receives
    expert_out = local experts (E_local of them)  every device's tokens
    --- all_to_all back ---                       for ITS experts
    y = combine (dispatch · gate) expert_out      (T, D)

The einsum-dispatch formulation keeps everything dense/static for XLA
(no dynamic shapes — dropped tokens beyond capacity fall out of the
dispatch mask, the standard Switch trade-off) and the two all_to_alls
are the only cross-device traffic, riding ICI.

A load-balancing auxiliary loss (mean gate fraction × mean dispatch
fraction × E, per Switch/GShard) is returned alongside the output.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.initialization import Xavier
from bigdl_tpu.nn.module import Module
from bigdl_tpu.parallel.shard_map_compat import axis_size


class MoE(Module):
    """Top-1 (Switch) MoE feed-forward layer.

    apply(variables, x (..., T, D)) → ((..., T, D), aux_loss) — the
    output is a tuple; aux_loss should be added to the training loss
    scaled by e.g. 0.01.

    With `expert_axis` set, apply() must run inside shard_map on a mesh
    containing that axis; the expert-stacked params (leading dim
    num_experts) are then sharded P(expert_axis, ...) and each device
    holds num_experts/axis_size experts, exchanging tokens via
    all_to_all.
    """

    def __init__(self, dim: int, hidden: int, num_experts: int,
                 capacity_factor: float = 1.25,
                 expert_axis: Optional[str] = None, top_k: int = 1,
                 routing: str = "top_k",
                 name: Optional[str] = None):
        super().__init__(name=name)
        if top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {top_k}")
        if routing not in ("top_k", "expert_choice"):
            raise ValueError(
                f"routing must be top_k|expert_choice, got {routing!r}")
        if routing == "expert_choice" and top_k != 1:
            raise ValueError(
                "top_k has no meaning under expert_choice routing "
                "(experts pick tokens; capacity_factor is the knob) — "
                "leave top_k=1")
        self.dim = dim
        self.hidden = hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.expert_axis = expert_axis
        self.top_k = top_k
        self.routing = routing

    def init_params(self, rng):
        e, d, f = self.num_experts, self.dim, self.hidden
        ks = jax.random.split(rng, 3)
        init = Xavier()
        return {
            "router": init(ks[0], (d, e), fan_in=d, fan_out=e),
            "w1": init(ks[1], (e, d, f), fan_in=d, fan_out=f),
            "b1": jnp.zeros((e, f), jnp.float32),
            "w2": init(ks[2], (e, f, d), fan_in=f, fan_out=d),
            "b2": jnp.zeros((e, d), jnp.float32),
        }

    def _route(self, x2, router):
        """x2: (T, D) → dispatch (T, E, C), combine (T, E, C), aux.

        top_k=1: Switch. top_k=2: GShard — second choice masked from the
        first, both gate values renormalized to sum to 1, second-choice
        tokens queue BEHIND all first-choice tokens in an expert's
        capacity buffer (first choices are never dropped in favor of
        seconds). Capacity scales with top_k.
        """
        t = x2.shape[0]
        e = self.num_experts
        cap = max(1, int(self.capacity_factor * self.top_k * t / e))
        gates = jax.nn.softmax(x2 @ router, axis=-1)          # (T, E)

        def choice_slot(onehot, offset):
            """dispatch mask (T,E,C) for one choice, given per-expert
            queue offsets (E,) from earlier choices."""
            pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0   # (T, E)
            pos = pos + offset[None, :] * onehot
            keep = onehot * (pos < cap)                       # (T, E)
            pos_oh = jax.nn.one_hot(
                pos.max(axis=-1).astype(jnp.int32), cap,
                dtype=jnp.float32)                            # (T, C)
            return keep[:, :, None] * pos_oh[:, None, :], keep

        oh1 = jax.nn.one_hot(jnp.argmax(gates, axis=-1), e,
                             dtype=jnp.float32)               # (T, E)
        d1, keep1 = choice_slot(oh1, jnp.zeros((e,), jnp.float32))
        g1 = jnp.sum(gates * keep1, axis=-1)                  # (T,)

        # Switch load-balancing aux from the FIRST choice (both modes):
        # fraction routed × mean gate, per expert
        frac = jnp.mean(oh1, axis=0)
        mean_gate = jnp.mean(gates, axis=0)
        aux = jnp.sum(frac * mean_gate) * e

        if self.top_k == 1:
            combine = d1 * g1[:, None, None]
            return d1, combine, aux, cap

        gates2 = gates * (1.0 - oh1)                          # mask top-1
        oh2 = jax.nn.one_hot(jnp.argmax(gates2, axis=-1), e,
                             dtype=jnp.float32)
        d2, keep2 = choice_slot(oh2, jnp.sum(oh1, axis=0))
        g2 = jnp.sum(gates * keep2, axis=-1)
        # renormalize over the SURVIVING choices (a dropped second
        # choice leaves the first at full weight, and vice versa)
        denom = g1 + g2 + 1e-9
        w1, w2 = g1 / denom, g2 / denom
        dispatch = d1 + d2          # disjoint experts: no overlap
        combine = d1 * w1[:, None, None] + d2 * w2[:, None, None]
        return dispatch, combine, aux, cap

    def _route_expert_choice(self, x2, router):
        """Expert-choice routing (Zhou et al. 2022) — the dropless
        answer to Switch's capacity dropping: instead of tokens picking
        experts (and overflowing their buffers), each EXPERT picks its
        top-C tokens by affinity. Every expert buffer is exactly full —
        perfect load balance BY CONSTRUCTION, so there is no capacity
        overflow, no dropped-token path, and no load-balancing
        auxiliary loss (aux ≡ 0).

        Static shapes throughout: `lax.top_k` over the token axis per
        expert, dense one-hot dispatch — the same (T, E, C) dispatch /
        combine tensors the top-k router emits, so the expert-parallel
        all_to_all plumbing is shared unchanged.

        Caveat (documented, inherent to the method): expert selections
        depend on ALL tokens in the batch/sequence, so it is not
        causally masked — use for encoder-style models, or accept the
        train-time approximation for decoder LMs.
        """
        t = x2.shape[0]
        e = self.num_experts
        cap = max(1, min(t, int(self.capacity_factor * t / e)))
        scores = jax.nn.softmax(x2 @ router, axis=-1)         # (T, E)
        g, idx = lax.top_k(scores.T, cap)                     # (E, C)
        # dispatch[t, e, c] = 1 iff expert e picked token t for slot c
        dispatch = jax.nn.one_hot(idx, t, dtype=jnp.float32,
                                  axis=-1).transpose(2, 0, 1)  # (T,E,C)
        combine = dispatch * g[None, :, :]    # affinity as gate weight
        return dispatch, combine, cap

    def _experts(self, p, xin):
        """xin: (E_local, C_tot, D) → same shape through each expert."""
        h = jnp.einsum("ecd,edf->ecf", xin, p["w1"]) + p["b1"][:, None, :]
        h = jax.nn.gelu(h)
        return jnp.einsum("ecf,efd->ecd", h, p["w2"]) + p["b2"][:, None, :]

    def apply(self, variables, x, training=False, rng=None):
        p = variables["params"]
        shape = x.shape
        x2 = x.reshape(-1, self.dim)
        if self.routing == "expert_choice":
            dispatch, combine, cap = self._route_expert_choice(
                x2, p["router"])
            aux = jnp.zeros((), jnp.float32)  # balanced by construction
        else:
            dispatch, combine, aux, cap = self._route(x2, p["router"])

        if self.expert_axis is None:
            xin = jnp.einsum("tec,td->ecd", dispatch, x2)
            yout = self._experts(p, xin)
            y = jnp.einsum("tec,ecd->td", combine, yout)
            return (y.reshape(shape), aux), variables["state"]

        # expert-parallel: params arrive expert-sharded; route globally,
        # exchange tokens so each device runs only its local experts
        axis = self.expert_axis
        n = axis_size(axis)
        e_local = p["w1"].shape[0]                 # num_experts / n
        if e_local * n != self.num_experts:
            raise ValueError(
                f"num_experts {self.num_experts} != {e_local}·{n}")
        xin = jnp.einsum("tec,td->ecd", dispatch, x2)   # (E, C, D)
        # (E, C, D) = (n, e_local, C, D): send slice j to device j
        xin = xin.reshape(n, e_local, cap, self.dim)
        xin = lax.all_to_all(xin, axis, split_axis=0, concat_axis=0,
                             tiled=True)               # (n, e_local, C, D)
        xin = xin.transpose(1, 0, 2, 3).reshape(
            e_local, n * cap, self.dim)                # my experts, all toks
        yout = self._experts(p, xin)                   # (e_local, nC, D)
        yout = yout.reshape(e_local, n, cap, self.dim).transpose(1, 0, 2, 3)
        yout = lax.all_to_all(yout, axis, split_axis=0, concat_axis=0,
                              tiled=True)              # (n, e_local, C, D)
        yout = yout.reshape(self.num_experts, cap, self.dim)
        y = jnp.einsum("tec,ecd->td", combine, yout)
        # aux is computed from THIS shard's tokens only — callers must
        # pmean it over the expert axis before using it as a loss term
        return (y.reshape(shape), aux), variables["state"]


def moe_specs(expert_axis: str = "expert"):
    """PartitionSpecs for MoE params (experts stacked on the lead dim)."""
    from jax.sharding import PartitionSpec as P

    return {"router": P(),
            "w1": P(expert_axis, None, None), "b1": P(expert_axis, None),
            "w2": P(expert_axis, None, None), "b2": P(expert_axis, None)}


def moe_lm_specs(ep_axis: str, tie_embeddings: bool = True):
    """PartitionSpecs for a MoE-FFN TransformerLM's params: expert-
    stacked block leaves (l, EX, ...) sharded on the EXPERT dim, all
    else replicated. Derived from transformer_tp_specs (param-key
    structure) + moe_specs (expert leaf layout) so there is no third
    hand-maintained key list."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.tensor_parallel import transformer_tp_specs

    base = transformer_tp_specs("unused_axis", tie_embeddings)
    specs = jax.tree_util.tree_map(
        lambda _: P(), base, is_leaf=lambda x: isinstance(x, P))
    # MoE leaves: moe_specs' per-expert layout with the layer dim
    # prepended; the replicated router stays P()
    specs["blocks"].update({
        k: (P() if k == "router" else P(None, *tuple(s)))
        for k, s in moe_specs(ep_axis).items()})
    return specs


def make_moe_lm_train_step(model, method, mesh, ep_axis: str = "expert"):
    """Jitted expert-parallel training step for a MoE-FFN TransformerLM.

    Signature: (params, slots, tokens, targets, lr, stepno, rng)
             -> (params', slots', mean_loss)

    The expert axis doubles as the batch axis (tokens shard on it, the
    standard EP deployment): each device computes its shard's loss with
    the per-layer all_to_all expert exchange inside the scan. Scaling:
    the local loss is the local token-mean divided by the axis size, so
    summed over shards it is the GLOBAL mean — expert-sharded leaves'
    gradients then arrive complete and correctly scaled through the
    all_to_all transposes with no extra collective, while replicated
    leaves (router, attention, embeddings) psum their per-shard
    contributions. The model must be built with ep_axis=<axis>.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.shard_map_compat import shard_map

    if getattr(model, "ep_axis", None) != ep_axis:
        raise ValueError(
            f"model.ep_axis={getattr(model, 'ep_axis', None)!r} != "
            f"step ep_axis={ep_axis!r}")
    if model.tp_axis is not None or model.sp_axis is not None:
        raise NotImplementedError(
            "the EP step runs on a pure expert mesh (the expert axis "
            "doubles as the batch axis); tp/sp composition is not "
            "implemented")
    n = mesh.shape[ep_axis]
    specs = moe_lm_specs(ep_axis, model.cfg.tie_embeddings)

    def body(params, slots, tokens, targets, lr, stepno, rng):
        rng = jax.random.fold_in(rng, lax.axis_index(ep_axis))

        def loss_fn(p):
            # local token-mean / n: sums to the global mean over shards
            return model.loss({"params": p, "state": {}}, tokens,
                              targets, training=True, rng=rng) / n

        local_loss, grads = jax.value_and_grad(loss_fn)(params)

        # replicated leaves: per-shard partial contributions → psum;
        # expert-sharded leaves: already complete via the all_to_all
        # transposes
        grads = jax.tree_util.tree_map(
            lambda sp, g: g if any(a is not None for a in sp)
            else lax.psum(g, ep_axis),
            specs, grads, is_leaf=lambda x: isinstance(x, P))
        loss = lax.psum(local_loss, ep_axis)

        new_params, new_slots = method.update(grads, params, slots, lr,
                                              stepno)
        return new_params, new_slots, loss

    from bigdl_tpu.parallel.tensor_parallel import slot_specs_for

    slot_specs = slot_specs_for(method, specs)
    tok_spec = P(ep_axis, None)
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(specs, slot_specs, tok_spec, tok_spec, P(), P(), P()),
        out_specs=(specs, slot_specs, P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=(0, 1))
