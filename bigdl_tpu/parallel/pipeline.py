"""Pipeline parallelism — GPipe microbatch schedule over a mesh axis.

No reference counterpart (SURVEY.md §2.3: the reference has data
parallelism only); this is the `pipe` mesh axis. The TransformerLM's
stacked-layer parameters make stages trivial: stage i owns the
contiguous layer slice blocks[i·L/n : (i+1)·L/n] — i.e. every stacked
block leaf is sharded on its LAYER axis with P('pipe', ...). Activations
hop stage→stage over the ICI ring with `lax.ppermute`.

Schedule: classic GPipe. M microbatches flow through n stages in
M + n - 1 ticks; stage s processes microbatch t - s at tick t. The
backward schedule is derived by jax.grad reversing the forward
(ppermute transposes to the inverse permutation), so warmup/drain
bubbles match GPipe's 2(n-1) ticks.

Losses exist only on the last stage; they cross to every stage through
the same psum-forward/identity-backward operator the tensor-parallel
plane uses (models/transformer.py#tp_reduce). Replicated leaves
(embed/pos/final LN) are USED on different stages (lookup on stage 0,
head on stage n-1), so their per-stage grads are partial and get psum'd
over the pipe axis; layer-sharded leaves' grads are exact locally.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerLM, tp_reduce

from bigdl_tpu.parallel.shard_map_compat import shard_map


def pipeline_specs(pipe_axis: str = "pipe", tie_embeddings: bool = True):
    """PartitionSpecs: stacked block leaves sharded on the layer axis."""
    def blk(ndim):
        return P(pipe_axis, *([None] * (ndim - 1)))

    blocks = {
        "ln1_g": blk(2), "ln1_b": blk(2), "ln2_g": blk(2), "ln2_b": blk(2),
        "wq": blk(3), "wk": blk(3), "wv": blk(3), "wo": blk(3),
        "bq": blk(2), "bk": blk(2), "bv": blk(2), "bo": blk(2),
        "w1": blk(3), "b1": blk(2), "w2": blk(3), "b2": blk(2),
    }
    specs = {"embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
             "blocks": blocks}
    if not tie_embeddings:
        specs["head"] = P()
    return specs


def pipeline_bubble_fraction(n_stages: int, microbatches: int) -> float:
    """GPipe idle fraction: each stage is idle for (n−1) of the
    m+n−1 ticks (warmup + drain). Raising `microbatches` amortizes it;
    report this when choosing a schedule."""
    return (n_stages - 1) / (microbatches + n_stages - 1)


def _injection_schedule(n: int, m: int, v: int):
    """Interleaved-schedule injection ticks: microbatch j enters virtual
    stage 0 (device 0, chunk 0) at tick inject[j]. An in-flight
    microbatch occupies device (t−t0) mod n at tick t for v·n ticks, so
    two microbatches collide iff their injection ticks share a residue
    mod n while both in flight; greedy first-free-tick is optimal here.
    v=1 degenerates to GPipe (inject = 0..m−1)."""
    inject, last = [], {}
    t = 0
    for _ in range(m):
        while True:
            r = t % n
            if r not in last or last[r] + v * n <= t:
                break
            t += 1
        inject.append(t)
        last[t % n] = t
        t += 1
    return inject


def interleaved_bubble_fraction(n_stages: int, microbatches: int,
                                virtual_stages: int) -> float:
    """Idle fraction of the interleaved (Megatron-style virtual-stage)
    schedule: total_ticks ticks of length T/v versus m·T of useful work
    per device. Strictly below GPipe's for v>1 at equal microbatches
    (e.g. 4 stages × 8 microbatches: 0.273 → 0.158 at v=2)."""
    inject = _injection_schedule(n_stages, microbatches, virtual_stages)
    total_ticks = inject[-1] + virtual_stages * n_stages
    return 1.0 - microbatches * virtual_stages / total_ticks


def to_virtual_layout(tree, n_stages: int, virtual_stages: int,
                      inverse: bool = False):
    """Permute a params-shaped tree's stacked "blocks" leaves from
    standard layer order into the interleaved schedule's virtual-stage
    order (or back, inverse=True).

    Virtual stage c·n+d (chunk c of device d) must own global layers
    [(c·n+d)·Lc, (c·n+d+1)·Lc); under the P('pipe') row sharding device
    d holds rows [d·L/n, (d+1)·L/n), so new row d·(L/n)+c·Lc+l maps to
    old row (c·n+d)·Lc+l. Optimizer-slot dicts (params-shaped trees one
    level down) are handled by recursing until a "blocks" key appears.
    Apply ONCE at setup; checkpoints should store standard layout (run
    inverse=True before saving)."""
    import numpy as np

    if not isinstance(tree, dict) or not tree:
        return tree
    if "blocks" not in tree:
        return {k: to_virtual_layout(v, n_stages, virtual_stages,
                                     inverse) for k, v in tree.items()}
    blocks = tree["blocks"]
    any_leaf = jax.tree_util.tree_leaves(blocks)[0]
    L = any_leaf.shape[0]
    n, v = n_stages, virtual_stages
    if L % (n * v):
        raise ValueError(
            f"{L} stacked layers not divisible by {n} stages x {v} "
            "virtual stages — refusing to build a garbage permutation")
    lc = L // (n * v)
    perm = np.empty(L, np.int64)
    for d in range(n):
        for c in range(v):
            for l in range(lc):
                perm[d * (L // n) + c * lc + l] = (c * n + d) * lc + l
    if inverse:
        perm = np.argsort(perm)
    out = dict(tree)
    out["blocks"] = jax.tree_util.tree_map(
        lambda a: jnp.take(a, jnp.asarray(perm), axis=0), blocks)
    return out


def make_pipeline_train_step(
    model: TransformerLM,
    method,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    dp_axis: Optional[str] = None,
    microbatches: int = 4,
    virtual_stages: int = 1,
) -> Callable:
    """Jitted pipeline training step for TransformerLM over pipe(×data).

    Signature: (params, slots, tokens, targets, lr, stepno, rng)
             -> (params', slots', mean_loss)

    tokens/targets: (B, S) with B divisible by microbatches (× dp size).
    The model must have tp_axis=None/sp_axis=None (pipe composes with dp
    here; TP/SP composition inside a stage is a further extension).

    virtual_stages=1 is classic GPipe. virtual_stages=v>1 is the
    interleaved (Megatron-style) schedule: each device owns v
    round-robin layer chunks, every tick runs ONE chunk (L/(n·v)
    layers), and a microbatch circles the ring v times — warmup/drain
    shrinks from (n−1) full-stage ticks to (n−1) chunk ticks, cutting
    the bubble fraction by ~v at equal microbatches (the backward
    mirrors the forward via jax.grad, so the whole step benefits).
    Params/slots must be pre-permuted with `to_virtual_layout` (and
    inverse-permuted before checkpointing in standard layout).
    """
    if model.tp_axis is not None or model.sp_axis is not None:
        raise ValueError("pipeline stage model must not set tp/sp axes")
    if model.cfg.moe_experts:
        raise NotImplementedError(
            "pipeline over a MoE-FFN TransformerLM (the MoE aux loss "
            "and expert-stacked specs are not plumbed through GPipe)")
    n = mesh.shape[pipe_axis]
    v = virtual_stages
    if model.cfg.num_layers % (n * v):
        raise ValueError(
            f"num_layers {model.cfg.num_layers} not divisible by "
            f"{n} pipeline stages x {v} virtual stages")
    m_micro = microbatches
    cfg = model.cfg
    layers_per_chunk = cfg.num_layers // (n * v)
    inject = _injection_schedule(n, m_micro, v)
    total_ticks = inject[-1] + v * n
    # static per-tick tables: which chunk each device runs (idle → 0,
    # its result simply never reaches a loss), which microbatch is
    # injected at device 0 / finished at device n-1 this tick
    import numpy as np
    chunk_tbl = np.zeros((total_ticks, n), np.int32)
    for j, t0 in enumerate(inject):
        for dt in range(v * n):
            chunk_tbl[t0 + dt, dt % n] = dt // n
    inject_at = {t0: j for j, t0 in enumerate(inject)}
    finish_at = {t0 + v * n - 1: j for j, t0 in enumerate(inject)}

    def body(params, slots, tokens, targets, lr, stepno, rng):
        idx = lax.axis_index(pipe_axis)
        b, s = tokens.shape
        mb = b // m_micro
        toks_mb = tokens.reshape(m_micro, mb, s)
        tgts_mb = targets.reshape(m_micro, mb, s)

        def loss_fn(p):
            def embed(tk):
                return p["embed"][tk] + p["pos"][:s]

            def stage(x, chunk):
                # local blocks rows = this device's v chunks in order
                bp = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_slice_in_dim(
                        a, chunk * layers_per_chunk, layers_per_chunk, 0),
                    p["blocks"]) if v > 1 else p["blocks"]

                def blk(x, bpar):
                    y, _aux = model._block(x, bpar, jax.random.PRNGKey(0),
                                           False)
                    return y, None
                x, _ = lax.scan(blk, x, bp)
                return x

            def head_loss(x, tg):
                x = model._ln(x, p["lnf_g"], p["lnf_b"])
                head = p["embed"].T if cfg.tie_embeddings else p["head"]
                logp = jax.nn.log_softmax(x @ head, axis=-1)
                return jnp.mean(
                    -jnp.take_along_axis(logp, tg[..., None], -1))

            perm = [(j, (j + 1) % n) for j in range(n)]
            h = jnp.zeros((mb, s, cfg.dim), jnp.float32)
            total = jnp.zeros((), jnp.float32)
            for t in range(total_ticks):
                x_in = h
                if t in inject_at:  # static: device 0's slot is free
                    x_in = jnp.where(idx == 0,
                                     embed(toks_mb[inject_at[t]]), h)
                chunk = jnp.asarray(chunk_tbl[t])[idx]
                y = stage(x_in, chunk)
                if t in finish_at:  # static: mb leaves chunk v-1 at n-1
                    total = total + jnp.where(
                        idx == n - 1,
                        head_loss(y, tgts_mb[finish_at[t]]), 0.0)
                if t != total_ticks - 1:
                    h = lax.ppermute(y, pipe_axis, perm)
            # share the last stage's loss with every stage (identity bwd)
            return tp_reduce(total, pipe_axis) / m_micro

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # replicated leaves are used on different stages → sum partials
        specs = pipeline_specs(pipe_axis, cfg.tie_embeddings)
        grads = jax.tree_util.tree_map(
            lambda sp, g: g if any(a is not None for a in sp)
            else lax.psum(g, pipe_axis),
            specs, grads, is_leaf=lambda x: isinstance(x, P))
        if dp_axis:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), grads)
            loss = lax.pmean(loss, dp_axis)

        new_params, new_slots = method.update(grads, params, slots, lr,
                                              stepno)
        return new_params, new_slots, loss

    specs = pipeline_specs(pipe_axis, cfg.tie_embeddings)
    from bigdl_tpu.parallel.tensor_parallel import slot_specs_for

    slot_specs = slot_specs_for(method, specs)
    tok_spec = P(dp_axis, None) if dp_axis else P()
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(specs, slot_specs, tok_spec, tok_spec, P(), P(), P()),
        out_specs=(specs, slot_specs, P()),
        check_vma=False,
    )
    step = jax.jit(smapped, donate_argnums=(0, 1))
    bubble = interleaved_bubble_fraction(n, m_micro, v)
    step.bubble_fraction = bubble
    import logging

    logging.getLogger("bigdl_tpu.parallel").info(
        "pipeline schedule: %d stages x %d microbatches x %d virtual, "
        "bubble fraction %.3f%s", n, m_micro, v, bubble,
        "" if v > 1 else " (GPipe)")
    return step
