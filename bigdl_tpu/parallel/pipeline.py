"""Pipeline parallelism — GPipe microbatch schedule over a mesh axis.

No reference counterpart (SURVEY.md §2.3: the reference has data
parallelism only); this is the `pipe` mesh axis. The TransformerLM's
stacked-layer parameters make stages trivial: stage i owns the
contiguous layer slice blocks[i·L/n : (i+1)·L/n] — i.e. every stacked
block leaf is sharded on its LAYER axis with P('pipe', ...). Activations
hop stage→stage over the ICI ring with `lax.ppermute`.

Schedule: classic GPipe. M microbatches flow through n stages in
M + n - 1 ticks; stage s processes microbatch t - s at tick t. The
backward schedule is derived by jax.grad reversing the forward
(ppermute transposes to the inverse permutation), so warmup/drain
bubbles match GPipe's 2(n-1) ticks.

Losses exist only on the last stage; they cross to every stage through
the same psum-forward/identity-backward operator the tensor-parallel
plane uses (models/transformer.py#tp_reduce). Replicated leaves
(embed/pos/final LN) are USED on different stages (lookup on stage 0,
head on stage n-1), so their per-stage grads are partial and get psum'd
over the pipe axis; layer-sharded leaves' grads are exact locally.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerLM, tp_reduce

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def pipeline_specs(pipe_axis: str = "pipe", tie_embeddings: bool = True):
    """PartitionSpecs: stacked block leaves sharded on the layer axis."""
    def blk(ndim):
        return P(pipe_axis, *([None] * (ndim - 1)))

    blocks = {
        "ln1_g": blk(2), "ln1_b": blk(2), "ln2_g": blk(2), "ln2_b": blk(2),
        "wq": blk(3), "wk": blk(3), "wv": blk(3), "wo": blk(3),
        "bq": blk(2), "bk": blk(2), "bv": blk(2), "bo": blk(2),
        "w1": blk(3), "b1": blk(2), "w2": blk(3), "b2": blk(2),
    }
    specs = {"embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
             "blocks": blocks}
    if not tie_embeddings:
        specs["head"] = P()
    return specs


def pipeline_bubble_fraction(n_stages: int, microbatches: int) -> float:
    """GPipe idle fraction: each stage is idle for (n−1) of the
    m+n−1 ticks (warmup + drain). Raising `microbatches` amortizes it;
    report this when choosing a schedule."""
    return (n_stages - 1) / (microbatches + n_stages - 1)


def make_pipeline_train_step(
    model: TransformerLM,
    method,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    dp_axis: Optional[str] = None,
    microbatches: int = 4,
) -> Callable:
    """Jitted GPipe training step for TransformerLM over pipe(×data).

    Signature: (params, slots, tokens, targets, lr, stepno, rng)
             -> (params', slots', mean_loss)

    tokens/targets: (B, S) with B divisible by microbatches (× dp size).
    The model must have tp_axis=None/sp_axis=None (pipe composes with dp
    here; TP/SP composition inside a stage is a further extension).
    """
    if model.tp_axis is not None or model.sp_axis is not None:
        raise ValueError("pipeline stage model must not set tp/sp axes")
    if model.cfg.moe_experts:
        raise NotImplementedError(
            "pipeline over a MoE-FFN TransformerLM (the MoE aux loss "
            "and expert-stacked specs are not plumbed through GPipe)")
    n = mesh.shape[pipe_axis]
    if model.cfg.num_layers % n:
        raise ValueError(
            f"num_layers {model.cfg.num_layers} not divisible by "
            f"{n} pipeline stages")
    m_micro = microbatches
    cfg = model.cfg

    def body(params, slots, tokens, targets, lr, stepno, rng):
        idx = lax.axis_index(pipe_axis)
        b, s = tokens.shape
        mb = b // m_micro
        toks_mb = tokens.reshape(m_micro, mb, s)
        tgts_mb = targets.reshape(m_micro, mb, s)

        def loss_fn(p):
            def embed(tk):
                return p["embed"][tk] + p["pos"][:s]

            def stage(x):
                def blk(x, bp):
                    y, _aux = model._block(x, bp, jax.random.PRNGKey(0),
                                           False)
                    return y, None
                x, _ = lax.scan(blk, x, p["blocks"])
                return x

            def head_loss(x, tg):
                x = model._ln(x, p["lnf_g"], p["lnf_b"])
                head = p["embed"].T if cfg.tie_embeddings else p["head"]
                logp = jax.nn.log_softmax(x @ head, axis=-1)
                return jnp.mean(
                    -jnp.take_along_axis(logp, tg[..., None], -1))

            perm = [(j, (j + 1) % n) for j in range(n)]
            h = jnp.zeros((mb, s, cfg.dim), jnp.float32)
            total = jnp.zeros((), jnp.float32)
            for t in range(m_micro + n - 1):
                x_in = jnp.where(idx == 0,
                                 embed(toks_mb[min(t, m_micro - 1)]), h)
                y = stage(x_in)
                mb_id = t - idx
                valid_last = (idx == n - 1) & (mb_id >= 0) & (mb_id < m_micro)
                tg = lax.dynamic_index_in_dim(
                    tgts_mb, jnp.clip(mb_id, 0, m_micro - 1), axis=0,
                    keepdims=False)
                total = total + jnp.where(valid_last, head_loss(y, tg), 0.0)
                if t != m_micro + n - 2:
                    h = lax.ppermute(y, pipe_axis, perm)
            # share the last stage's loss with every stage (identity bwd)
            return tp_reduce(total, pipe_axis) / m_micro

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # replicated leaves are used on different stages → sum partials
        specs = pipeline_specs(pipe_axis, cfg.tie_embeddings)
        grads = jax.tree_util.tree_map(
            lambda sp, g: g if any(a is not None for a in sp)
            else lax.psum(g, pipe_axis),
            specs, grads, is_leaf=lambda x: isinstance(x, P))
        if dp_axis:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), grads)
            loss = lax.pmean(loss, dp_axis)

        new_params, new_slots = method.update(grads, params, slots, lr,
                                              stepno)
        return new_params, new_slots, loss

    specs = pipeline_specs(pipe_axis, cfg.tie_embeddings)
    from bigdl_tpu.parallel.tensor_parallel import slot_specs_for

    slot_specs = slot_specs_for(method, specs)
    tok_spec = P(dp_axis, None) if dp_axis else P()
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(specs, slot_specs, tok_spec, tok_spec, P(), P(), P()),
        out_specs=(specs, slot_specs, P()),
        check_vma=False,
    )
    step = jax.jit(smapped, donate_argnums=(0, 1))
    bubble = pipeline_bubble_fraction(n, m_micro)
    step.bubble_fraction = bubble
    import logging

    logging.getLogger("bigdl_tpu.parallel").info(
        "pipeline schedule: %d stages x %d microbatches, GPipe bubble "
        "fraction %.3f", n, m_micro, bubble)
    return step
