"""Data-parallel training plane — ZeRO-1 over the ICI mesh.

Reference parity: parameters/AllReduceParameter.scala — THE distributed
core of the reference (SURVEY.md §5.8). The reference keeps all weights
in ONE flat vector (Module.getParameters), splits it into partitionNum
slices, and per iteration does:

    putGradients            → scatter my gradient, sliced, FP16 on the wire
    aggregateGradientPartition → fetch + sum my slice     (= reduce-scatter)
    optimMethod.optimize on my slice                      (= sharded ZeRO-1 step)
    sendWeightPartition / getWeights                      (= all-gather)

TPU-first redesign: the SAME shape executed as XLA collectives inside one
jitted, shard_mapped step — no blocks, no netty, no host:

    grads  = jax.grad(loss)(unflatten(flat_w))      per-device local batch
    g_my   = psum_scatter(flatten(grads), 'data')   reduce-scatter over ICI
    w_my   = my slice of flat_w
    w_my'  = optim.update(g_my, w_my, slots_my)     slots live sharded (ZeRO-1)
    flat_w'= all_gather(w_my', 'data')              all-gather over ICI

The reference's FP16CompressedTensor wire compression maps to bf16
gradient communication (`grad_dtype='bfloat16'`): contributions cross the
wire as bf16 via all_to_all and are summed locally in f32 — the exact
compress-on-wire / f32-accumulate split of the reference's
putGradients/aggregateGradientPartition, at half the wire cost and with
accumulation error independent of the axis size.

ZeRO-2 (`zero=2`; ISSUE 9, arXiv 2004.13336 cross-replica weight-update
sharding): the master fp32 flat weight vector ALSO lives sharded on the
data axis — each device persists only its (shard_size,) slice between
steps, and the step opens with one all_gather to rebuild the full
vector for the forward/backward. The collective volume per step is
identical to ZeRO-1 (one all-gather either way: ZeRO-1 gathers the
updated shards at the END of step k, ZeRO-2 gathers the same bytes at
the START of step k+1), but per-device weight residency drops from
`padded` to `padded / n` floats. Because `all_gather` of the disjoint
slices reconstructs the exact concatenation, the ZeRO-2 step is
BIT-IDENTICAL to the ZeRO-1 step in fp32 (tests/test_zero2.py pins
this; the zero2 dryrun leg in __graft_entry__.py asserts it on the
8-device virtual mesh).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.utils.anomaly import health_ok, select_update as _select_update

from bigdl_tpu.parallel.shard_map_compat import shard_map
# the flatten/pad/slice algebra lives in the param-layout spine
# (ISSUE 18) — re-exported here because this module IS its historical
# home and every training consumer imports it from parallel/
from bigdl_tpu.parallel.param_layout import FlatParamSpec  # noqa: F401


def _make_scattered_grads(model, criterion, spec, axis, grad_dtype,
                          precision):
    """Per-device closure: local fwd/bwd on the batch shard, then
    reduce-scatter of the flat gradient — the putGradients/
    aggregateGradientPartition half of the reference's iteration.
    Returns (g_my (shard_size,) f32 mean-over-global-batch, new_state,
    local loss)."""
    n = spec.num_shards

    from bigdl_tpu.ops.losses import build_train_loss

    loss_call = build_train_loss(model, criterion, precision)

    def scattered_grads(flat_w, mod_state, bx, by, rng):
        params = spec.unflatten(flat_w)
        my_index = lax.axis_index(axis)
        local_rng = jax.random.fold_in(rng, my_index)

        (loss, new_state), grads = jax.value_and_grad(
            lambda p: loss_call(p, mod_state, bx, by, local_rng),
            has_aux=True)(params)

        flat_g = spec.flatten(grads)
        if grad_dtype is not None:
            # The reference's FP16 wire compression with f32 accumulation
            # (FP16CompressedTensor.compress on the wire, decompress + f32
            # sum in aggregateGradientPartition): send each device's
            # contribution to each slice as bf16 via all_to_all, then sum
            # the received contributions locally in f32 — bf16 wire cost,
            # f32 accumulation numerics at any axis size.
            g_chunks = flat_g.reshape(n, spec.shard_size).astype(grad_dtype)
            recv = lax.all_to_all(g_chunks, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
            g_my = jnp.sum(recv.reshape(n, spec.shard_size)
                           .astype(jnp.float32), axis=0) / n
        else:
            # exact path: fused f32 reduce-scatter
            g_my = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                    tiled=True) / n
        return g_my, new_state, loss

    return scattered_grads


def _clip_shard(g_my, clip_const, clip_norm, axis):
    if clip_const is not None:
        g_my = jnp.clip(g_my, clip_const[0], clip_const[1])
    if clip_norm is not None:
        # global grad norm needs the full (pre-scatter) vector; compute
        # from the scattered shards with a psum — mathematically equal
        sq = lax.psum(jnp.sum(g_my * g_my), axis)
        scale = jnp.minimum(1.0, clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))
        g_my = g_my * scale
    return g_my


NON_REDUCIBLE_STATE_KEYS = frozenset({"num_batches", "step", "counter"})


def _reduce_state(new_state, axis, non_reducible: bool = False):
    """BN running stats etc. diverge per shard of the batch; average them
    so replicated state stays replicated (documented divergence: the
    reference keeps per-replica stats — SURVEY.md §7 hard parts).

    NOT every float leaf is averaged. Two opt-outs, per the contract on
    nn.Module.init_state: a dict key starting with '_' exempts its whole
    subtree (the explicit convention); a key in NON_REDUCIBLE_STATE_KEYS
    exempts ONLY a direct leaf under that key — it does not propagate to
    subtrees, so a future module whose batch-dependent stats happen to
    live under a generic name like 'step' cannot silently diverge. All
    shards advance exempt leaves identically under SPMD, so "keep local"
    is "keep replicated"."""
    if isinstance(new_state, dict):
        out = {}
        for k, v in new_state.items():
            named_leaf = (isinstance(k, str) and k in NON_REDUCIBLE_STATE_KEYS
                          and not isinstance(v, (dict, list, tuple)))
            nr = non_reducible or named_leaf or (
                isinstance(k, str) and k.startswith("_"))
            out[k] = _reduce_state(v, axis, nr)
        return out
    if isinstance(new_state, (list, tuple)):
        return type(new_state)(_reduce_state(v, axis, non_reducible)
                               for v in new_state)
    if non_reducible:
        return new_state
    if jnp.issubdtype(jnp.asarray(new_state).dtype, jnp.floating):
        return lax.pmean(new_state, axis)
    return new_state


def make_dp_train_step(
    model: Module,
    criterion: Criterion,
    method,
    mesh: Mesh,
    spec: FlatParamSpec,
    axis: str = "data",
    grad_dtype: Optional[str] = "bfloat16",
    clip_const: Optional[Tuple[float, float]] = None,
    clip_norm: Optional[float] = None,
    precision=None,
    health: bool = False,
    zero: int = 1,
) -> Callable:
    """Build the jitted SPMD train step.

    Signature: (flat_w, slots, mod_state, bx, by, lr, stepno, rng)
             -> (flat_w', slots', mod_state', mean_loss)

    With `health=True` (anomaly guard armed on the Optimizer) the step
    takes a trailing `max_gnorm` scalar and returns two extra scalars
    `(ok, gnorm)`: the pre-clip global gradient norm and the
    utils/anomaly health predicate over (mean loss, norm, threshold).
    When `ok` is false the update is discarded ON DEVICE — the returned
    flat_w/slots/mod_state are the bit-identical inputs — so an
    anomalous step can never write to the weights regardless of host
    policy. Costs two scalar collectives; `health=False` builds exactly
    the historical step.

    Shardings: slots sharded on `axis`; mod_state replicated; batch
    sharded on `axis`. `zero=1` keeps flat_w replicated (historical
    ZeRO-1 step); `zero=2` shards flat_w on `axis` too — the step then
    opens with an all_gather of the weight shards and returns the
    updated SHARDED vector (see the module docstring: same collective
    volume, 1/n weight residency, bit-identical fp32 results).
    `precision` is a utils.precision.Policy for bf16-compute mixed
    precision (master weights stay fp32 in flat_w).
    """
    if zero not in (1, 2):
        raise ValueError(f"zero must be 1 or 2, got {zero!r}")
    other_axes = [a for a in mesh.axis_names if a != axis]
    scattered_grads = _make_scattered_grads(model, criterion, spec, axis,
                                            grad_dtype, precision)

    def body(flat_w, slots, mod_state, bx, by, lr, stepno, rng,
             max_gnorm=None):
        if zero == 2:
            # flat_w arrives as this device's (shard_size,) slice;
            # all_gather of the disjoint slices rebuilds the exact full
            # vector the ZeRO-1 step would have held replicated
            w_my = flat_w
            flat_w = lax.all_gather(w_my, axis, axis=0, tiled=True)
        g_my, new_state, loss = scattered_grads(flat_w, mod_state, bx, by,
                                                rng)
        mean_loss = lax.pmean(loss, axis)
        new_state = _reduce_state(new_state, axis)
        if other_axes:
            mean_loss = lax.pmean(mean_loss, tuple(other_axes))
        if health:
            # pre-clip global norm of the mean gradient: the shards are
            # disjoint slices of the flat vector, so one scalar psum
            gnorm = jnp.sqrt(lax.psum(jnp.sum(g_my * g_my), axis))
            ok = health_ok(mean_loss, gnorm, max_gnorm)
        g_my = _clip_shard(g_my, clip_const, clip_norm, axis)

        if zero == 1:
            w_my = spec.shard_slice(flat_w, lax.axis_index(axis))
        new_w_my, new_slots = method.update(g_my, w_my, slots, lr, stepno)
        if zero == 2:
            new_flat_w, prev_w = new_w_my, w_my  # stays sharded
        else:
            new_flat_w = lax.all_gather(new_w_my, axis, axis=0, tiled=True)
            prev_w = flat_w

        if health:
            new_flat_w = _select_update(ok, new_flat_w, prev_w)
            new_slots = _select_update(ok, new_slots, slots)
            new_state = _select_update(ok, new_state, mod_state)
            return new_flat_w, new_slots, new_state, mean_loss, ok, gnorm
        return new_flat_w, new_slots, new_state, mean_loss

    batch_spec = P(axis)
    w_spec = P(axis) if zero == 2 else P()
    in_specs = (w_spec, P(axis), P(), batch_spec, batch_spec, P(), P(), P())
    out_specs = (w_spec, P(axis), P(), P())
    if health:
        in_specs += (P(),)
        out_specs += (P(), P())
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=(0, 1))


def make_dp_accum_steps(
    model: Module,
    criterion: Criterion,
    method,
    mesh: Mesh,
    spec: FlatParamSpec,
    axis: str = "data",
    grad_dtype: Optional[str] = "bfloat16",
    clip_const: Optional[Tuple[float, float]] = None,
    clip_norm: Optional[float] = None,
    precision=None,
    health: bool = False,
    zero: int = 1,
) -> Tuple[Callable, Callable]:
    """Gradient accumulation on the mesh: the accumulator lives SHARDED
    (shard_size,) per device — micro-steps reduce-scatter then add, so
    accumulation costs one extra f32 vector per shard, never a full
    gradient replica (cheap exactly as VERDICT r1 #3 prescribes:
    accumulate the scattered shard, after psum_scatter, before the
    optimizer step).

    Returns (micro_fn, apply_fn):
      micro_fn: (flat_w, g_acc, mod_state, bx, by, rng)
              -> (g_acc', mod_state', mean_loss)
      apply_fn: (flat_w, slots, g_acc, lr, stepno, n_micro)
              -> (flat_w', slots', zeroed g_acc)
    Clipping applies to the averaged accumulated gradient at update time
    (same semantics as the local path's clip_and_update).

    With `health=True` micro_fn takes a trailing `max_gnorm` and returns
    extra `(ok, gnorm)` scalars; an anomalous micro-gradient is NOT
    added to the accumulator (and module state keeps its inputs), so
    the guard screens each micro-batch before it can poison the cycle —
    the host skips its micro_n increment, extending the cycle by one
    batch. apply_fn is unchanged: it only ever sees screened gradients.

    `zero=2`: flat_w is sharded on `axis` in BOTH functions — micro_fn
    all_gathers the weight shards for the forward/backward (the
    ZeRO-2 residency/volume trade, see make_dp_train_step), apply_fn
    updates the local shard directly and returns it sharded.
    """
    if zero not in (1, 2):
        raise ValueError(f"zero must be 1 or 2, got {zero!r}")
    other_axes = [a for a in mesh.axis_names if a != axis]
    scattered_grads = _make_scattered_grads(model, criterion, spec, axis,
                                            grad_dtype, precision)

    def micro_body(flat_w, g_acc, mod_state, bx, by, rng, max_gnorm=None):
        if zero == 2:
            flat_w = lax.all_gather(flat_w, axis, axis=0, tiled=True)
        g_my, new_state, loss = scattered_grads(flat_w, mod_state, bx, by,
                                                rng)
        mean_loss = lax.pmean(loss, axis)
        new_state = _reduce_state(new_state, axis)
        if other_axes:
            mean_loss = lax.pmean(mean_loss, tuple(other_axes))
        if health:
            gnorm = jnp.sqrt(lax.psum(jnp.sum(g_my * g_my), axis))
            ok = health_ok(mean_loss, gnorm, max_gnorm)
            # where-select the SUM, not the addend: adding 0.0 would
            # flip -0.0 accumulator elements to +0.0 and break the
            # bit-identical-discard contract
            new_acc = jnp.where(ok, g_acc + g_my, g_acc)
            new_state = _select_update(ok, new_state, mod_state)
            return new_acc, new_state, mean_loss, ok, gnorm
        return g_acc + g_my, new_state, mean_loss

    def apply_body(flat_w, slots, g_acc, lr, stepno, n_micro):
        g_my = _clip_shard(g_acc / n_micro, clip_const, clip_norm, axis)
        if zero == 2:
            w_my = flat_w
        else:
            w_my = spec.shard_slice(flat_w, lax.axis_index(axis))
        new_w_my, new_slots = method.update(g_my, w_my, slots, lr, stepno)
        if zero == 2:
            new_flat_w = new_w_my
        else:
            new_flat_w = lax.all_gather(new_w_my, axis, axis=0, tiled=True)
        return new_flat_w, new_slots, jnp.zeros_like(g_acc)

    batch_spec = P(axis)
    w_spec = P(axis) if zero == 2 else P()
    micro_in = (w_spec, P(axis), P(), batch_spec, batch_spec, P())
    micro_out = (P(axis), P(), P())
    if health:
        micro_in += (P(),)
        micro_out += (P(), P())
    micro_fn = jax.jit(shard_map(
        micro_body, mesh=mesh,
        in_specs=micro_in,
        out_specs=micro_out,
        check_vma=False,
    ), donate_argnums=(1,))
    apply_fn = jax.jit(shard_map(
        apply_body, mesh=mesh,
        in_specs=(w_spec, P(axis), P(axis), P(), P(), P()),
        out_specs=(w_spec, P(axis), P(axis)),
        check_vma=False,
    ), donate_argnums=(0, 1, 2))
    return micro_fn, apply_fn


def make_dp_eval_step(model: Module, methods, mesh: Mesh, axis: str = "data"):
    """SPMD eval step: forward on the local batch shard, psum the
    (sum, count) stats — the reference's Evaluator mapPartitions+reduce
    (optim/Evaluator.scala) as one collective.

    Signature: (params, mod_state, bx, by, row_mask) -> [(sum, count), ...]
    row_mask is a per-row 0/1 float vector (masks padded tail rows).
    """

    def body(params, mod_state, bx, by, row_mask):
        out, _ = model.apply({"params": params, "state": mod_state}, bx,
                             training=False)
        stats = []
        for m in methods:
            s, c = m.stats(out, by, row_mask)
            stats.append((lax.psum(s, axis), lax.psum(c, axis)))
        return stats

    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(smapped)
