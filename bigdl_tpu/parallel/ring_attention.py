"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

No reference counterpart — SURVEY.md §5.7 records the reference's sequence
stack as single-node unrolled BPTT with "no ring attention, no
context/sequence parallel". This module is the TPU-first long-context
plane: the sequence axis of attention is sharded over a mesh axis and the
KV chunks travel the ICI ring, so context length scales linearly with the
number of chips.

Two strategies, both called INSIDE shard_map (the mesh axis must be
bound; see make_ring_attention for a jit-ready wrapper):

* `ring_attention(q, k, v, axis)` — each device keeps its Q chunk and
  streams KV chunks around the ring with `lax.ppermute`, accumulating an
  online (running max / running sum) softmax exactly like the flash
  kernel does across KV blocks — the ring IS the outer loop of flash
  attention, with chunks living on different chips. n-1 hops overlap
  compute with ICI transfers; peak memory is O(S_local² · heads) per
  step. Fully differentiable: the backward of `ppermute` is the reverse
  permute, so jax.grad derives the ring backward automatically.
* `ulysses_attention(q, k, v, axis)` — all-to-all swaps the sharded axis
  from sequence to heads (each device gets the FULL sequence for
  heads/n heads), runs dense/flash attention locally, and swaps back.
  Two all-to-alls per call; requires num_heads % axis_size == 0. The
  local attention is global-sequence, so it rides the Pallas flash
  kernel on TPU (`impl=` passthrough).

Causality across chunks uses global positions: device i's rows cover
[i·S_local, (i+1)·S_local); a KV chunk that originated on device j is
fully visible when j < i, diagonal (locally causal) when j == i, and
fully masked when j > i. The masking is positional, so unequal
chunk-vs-source comparisons compile to one `jnp.where` — no dynamic
control flow inside jit.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.shard_map_compat import axis_size, shard_map

_NEG_INF = -1e30


def _chunk_stats(q, k, v, sm_scale, q_off, k_off, causal):
    """Unnormalized attention of a Q chunk against one KV chunk.

    q: (B, H, Sq, D), k/v: (B, H, Sk, D); q_off/k_off are the chunks'
    global sequence offsets — scalars for contiguous chunks, or (Sq,)/
    (Sk,) position VECTORS for non-contiguous layouts (zigzag).
    Returns (o_unnorm (B,H,Sq,D), m (B,H,Sq), l (B,H,Sq)).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = (q_off[:, None] if jnp.ndim(q_off) == 1 else
                q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0))
        kpos = (k_off[None, :] if jnp.ndim(k_off) == 1 else
                k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1))
        s = jnp.where(kpos <= qpos, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                              # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == -inf-ish → p would be exp(0)=1; zero them
    alive = (m > _NEG_INF / 2)[..., None]
    p = jnp.where(alive, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o.astype(jnp.float32), m, l


def _online_combine(acc, m_acc, l_acc, o_i, m_i, l_i):
    """Merge one chunk's (o, m, l) into the running accumulator."""
    m_new = jnp.maximum(m_acc, m_i)
    a1 = jnp.exp(m_acc - m_new)[..., None]
    a2 = jnp.exp(m_i - m_new)[..., None]
    acc = acc * a1 + o_i * a2
    l_new = l_acc * jnp.exp(m_acc - m_new) + l_i * jnp.exp(m_i - m_new)
    return acc, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "seq",
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over mesh axis `axis`. Call inside shard_map.

    q, k, v: (B, H, S_local, D) — the local sequence chunk. Returns the
    local chunk of the attention output, (B, H, S_local, D).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = axis_size(axis)
    my = lax.axis_index(axis)
    s_local = q.shape[-2]
    q_off = my * s_local

    acc = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m_acc = jnp.full(q.shape[:-1], _NEG_INF, jnp.float32)
    l_acc = jnp.zeros(q.shape[:-1], jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]
    kv = (k, v)
    for i in range(n):
        # after i hops the resident KV chunk originated on device my - i
        src = (my - i) % n
        k_i, v_i = kv
        o_i, m_i, l_i = _chunk_stats(q, k_i, v_i, sm_scale, q_off,
                                     src * k_i.shape[-2], causal)
        acc, m_acc, l_acc = _online_combine(acc, m_acc, l_acc, o_i, m_i, l_i)
        if i != n - 1:
            kv = jax.tree_util.tree_map(
                lambda x: lax.ppermute(x, axis, perm), kv)

    safe_l = jnp.where(l_acc == 0.0, 1.0, l_acc)[..., None]
    return (acc / safe_l).astype(q.dtype)


def zigzag_positions(n: int, s_local: int):
    """Global row positions device i holds under the zigzag layout.

    Causal masking makes contiguous ring chunks unbalanced: device 0's
    rows attend 1 chunk, device n-1's attend n — half the ring idles.
    Zigzag gives each device TWO half-chunks, one from the front and
    the mirrored one from the back (device i: half-chunks i and
    2n-1-i), so every device's causal work is equal. Returns a list of
    (s_local,) int arrays, one per device.
    """
    h = s_local // 2
    return [jnp.concatenate([i * h + jnp.arange(h, dtype=jnp.int32),
                             (2 * n - 1 - i) * h
                             + jnp.arange(h, dtype=jnp.int32)])
            for i in range(n)]


def zigzag_order(n: int, s: int) -> jax.Array:
    """Global gather order for the zigzag layout over the full sequence
    (concatenation of every device's zigzag_positions), with the
    divisibility check every entry point needs. THE single source of
    the layout invariant — the model's pos gather, the train-step feed
    permutation, and the attention wrapper all use this module's
    functions, so a layout change stays in one place."""
    if s % (2 * n):
        raise ValueError(
            f"zigzag needs sequence length divisible by 2·{n} "
            f"(two half-chunks per device), got {s}")
    return jnp.concatenate(zigzag_positions(n, s // n))


def zigzag_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "seq",
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Load-balanced CAUSAL ring attention. Call inside shard_map.

    q, k, v: (B, H, S_local, D) in the ZIGZAG layout — device i holds
    global rows [i·h, (i+1)·h) ∪ [(2n−1−i)·h, (2n−i)·h) with
    h = S_local/2 (use `make_ring_attention(mode="zigzag")` for the
    global-array wrapper that applies/undoes the permutation).

    Unlike the contiguous causal ring — where the dense per-hop kernel
    computes every (Sq×Sk) score and throws the masked half away — the
    zigzag hop computes ONLY the visible half-blocks. The case analysis
    for kv arriving from `src` (lo = front half-chunk, hi = mirrored
    back half-chunk; positions lo(my) < lo(src<my) < n·h ≤ hi(any)):

        hi_q · lo_k : fully visible for EVERY (my, src)   — always done
        src < my    : + lo_q · lo_k fully visible
        src > my    : + hi_q · hi_k fully visible
        src == my   : + lo_q·lo_k and hi_q·hi_k, each diagonal

    so every hop after the first costs exactly 2 unmasked half-blocks
    on every device: half the dense ring's flops, perfectly balanced
    (hop 0 is the src==my diagonal case on all devices simultaneously).
    The per-device branch is a lax.switch on traced (src vs my) —
    legal SPMD: devices run independent programs between ppermutes.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = axis_size(axis)
    my = lax.axis_index(axis)
    s_local = q.shape[-2]
    if s_local % 2:
        raise ValueError("zigzag needs an even local sequence length")
    h = s_local // 2
    half = jnp.arange(h, dtype=jnp.int32)
    q_lo, q_hi = q[..., :h, :], q[..., h:, :]

    def state0(qh):
        return (jnp.zeros(qh.shape[:-1] + (v.shape[-1],), jnp.float32),
                jnp.full(qh.shape[:-1], _NEG_INF, jnp.float32),
                jnp.zeros(qh.shape[:-1], jnp.float32))

    lo, hi = state0(q_lo), state0(q_hi)
    perm = [(j, (j + 1) % n) for j in range(n)]
    kv = (k, v)
    diag = (half[:, None] >= half[None, :])  # within-half causal mask

    def attn_full(qh, kh, vh):
        return _chunk_stats(qh, kh, vh, sm_scale, 0, 0, causal=False)

    def attn_diag(qh, kh, vh):
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) \
            * sm_scale
        s = jnp.where(diag, s, _NEG_INF)
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vh.dtype), vh)
        return o.astype(jnp.float32), m, l

    for i in range(n):
        src = (my - i) % n
        k_i, v_i = kv
        k_lo, k_hi = k_i[..., :h, :], k_i[..., h:, :]
        v_lo, v_hi = v_i[..., :h, :], v_i[..., h:, :]

        # hi_q sees src's lo half in full, for every (my, src)
        hi = _online_combine(*hi, *attn_full(q_hi, k_lo, v_lo))

        def case_before(lo, hi):   # src < my: lo_q sees lo_k fully
            return _online_combine(*lo, *attn_full(q_lo, k_lo, v_lo)), hi

        def case_after(lo, hi):    # src > my: hi_q sees hi_k fully
            return lo, _online_combine(*hi, *attn_full(q_hi, k_hi, v_hi))

        def case_self(lo, hi):     # src == my: two diagonal halves
            return (_online_combine(*lo, *attn_diag(q_lo, k_lo, v_lo)),
                    _online_combine(*hi, *attn_diag(q_hi, k_hi, v_hi)))

        idx = jnp.where(src == my, 2, jnp.where(src < my, 0, 1))
        lo, hi = lax.switch(idx, [case_before, case_after, case_self],
                            lo, hi)
        if i != n - 1:
            kv = jax.tree_util.tree_map(
                lambda x: lax.ppermute(x, axis, perm), kv)

    def finish(state, qh):
        acc, m_acc, l_acc = state
        safe_l = jnp.where(l_acc == 0.0, 1.0, l_acc)[..., None]
        return (acc / safe_l).astype(qh.dtype)

    return jnp.concatenate([finish(lo, q_lo), finish(hi, q_hi)], axis=-2)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "seq",
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Ulysses (all-to-all) sequence parallelism. Call inside shard_map.

    q, k, v: (B, H, S_local, D) with H divisible by the axis size.
    all_to_all → (B, H/n, S_global, D) → dense/flash attention (global
    sequence, so the plain `causal` flag is exact) → all_to_all back.
    """
    from bigdl_tpu.ops.flash_attention import flash_attention

    n = axis_size(axis)
    h = q.shape[1]
    if h % n:
        raise ValueError(f"num_heads {h} not divisible by axis size {n}")

    def gather_seq(x):   # (B, H, S_local, D) -> (B, H/n, S_global, D)
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def scatter_seq(x):  # inverse
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
    out = flash_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                          impl=impl)
    return scatter_seq(out)


def make_ring_attention(
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = False,
    mode: str = "ring",
    impl: Optional[str] = None,
) -> Callable:
    """jit-ready wrapper: (q, k, v) global arrays sharded on the sequence
    axis → attention output with the same sharding. q,k,v: (B,H,S,D),
    S divisible by the axis size.

    mode: "ring" (contiguous chunks) | "ulysses" (all-to-all) |
    "zigzag" (causal-only load-balanced ring: the wrapper permutes the
    global sequence into the zigzag layout, runs the balanced ring, and
    inverse-permutes the output — callers keeping their data in zigzag
    layout end-to-end should call `zigzag_ring_attention` inside their
    own shard_map instead and skip both permutes)."""
    if mode == "zigzag" and not causal:
        raise ValueError("zigzag balancing only applies to causal "
                         "attention; use mode='ring'")

    def body(q, k, v):
        if mode == "ring":
            return ring_attention(q, k, v, axis=axis, causal=causal)
        if mode == "zigzag":
            return zigzag_ring_attention(q, k, v, axis=axis)
        return ulysses_attention(q, k, v, axis=axis, causal=causal,
                                 impl=impl)

    spec = P(None, None, axis, None)
    smapped = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)
    fn = jax.jit(smapped)
    if mode != "zigzag":
        return fn

    n = mesh.shape[axis]

    def zig(q, k, v):
        order = zigzag_order(n, q.shape[2])
        inv = jnp.argsort(order)
        out = fn(q[:, :, order], k[:, :, order], v[:, :, order])
        return out[:, :, inv]

    return jax.jit(zig)
