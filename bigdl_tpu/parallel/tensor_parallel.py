"""Tensor parallelism for the transformer stack — Megatron-style sharding
expressed as shard_map + XLA collectives over the ICI mesh.

No reference counterpart: the reference's only strategy is data
parallelism (SURVEY.md §2.3 "Parallelism strategies present"); TP is one
of this framework's additive mesh axes. The split is the classic one:

    wq/wk/wv/w1 column-sharded  (each device owns heads/tp heads,
                                 ffn/tp hidden units — no comm needed)
    wo/w2       row-sharded      (partial sums → one psum per matmul)
    ln/embed/pos/bo/b2 replicated

`TransformerLM._block` already runs this split unchanged inside
shard_map (it infers its local head count from the weight shard and
psums after the row-parallel matmuls); this module supplies the
PartitionSpecs for the stacked parameter pytree and a full jitted
training step that composes TP with data parallelism and ring-attention
sequence parallelism on one mesh.

Gradient collectives: after per-device jax.grad, every leaf is averaged
over the data (and sequence) axes. Across TP no per-leaf correction is
needed — the model's `tp_identity` (Megatron's conjugate "f": identity
forward, psum backward) sums partial activation cotangents before they
reach TP-replicated params, so their grads emerge full and identical on
every shard, while TP-sharded leaves' grads are exact locally.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerLM

from bigdl_tpu.parallel.shard_map_compat import shard_map

# stacked-block leaves: which dim (after the layer axis) carries the shard
_COL = {"wq", "wk", "wv", "w1"}          # shard last dim
_ROW = {"wo", "w2"}                      # shard middle (input) dim
_COL_BIAS = {"bq", "bk", "bv", "b1"}     # shard last dim


def transformer_tp_specs(tp_axis: str = "model",
                         tie_embeddings: bool = True) -> Dict[str, Any]:
    """PartitionSpec pytree for TransformerLM params (stacked blocks)."""
    blocks = {}
    for k in ("ln1_g", "ln1_b", "ln2_g", "ln2_b", "bo", "b2"):
        blocks[k] = P()
    for k in _COL:
        blocks[k] = P(None, None, tp_axis)
    for k in _ROW:
        blocks[k] = P(None, tp_axis, None)
    for k in _COL_BIAS:
        blocks[k] = P(None, tp_axis)
    specs = {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "blocks": blocks,
    }
    if not tie_embeddings:
        specs["head"] = P()  # replicated: the loss needs the full vocab
    return specs


def make_transformer_train_step(
    model: TransformerLM,
    method,
    mesh: Mesh,
    dp_axis: Optional[str] = "data",
    tp_axis: Optional[str] = "model",
    sp_axis: Optional[str] = None,
) -> Callable:
    """Build the jitted SPMD LM training step over a dp×tp(×sp) mesh.

    Signature: (params, slots, tokens, targets, lr, stepno, rng)
             -> (params', slots', mean_loss)

    tokens/targets: (B, S) int32, batch sharded on dp, sequence sharded
    on sp. The model must have been constructed with matching
    tp_axis/sp_axis. Use `transformer_tp_specs()` + `shard_params` to
    place params/slots.
    """
    if (model.tp_axis or None) != (tp_axis or None):
        raise ValueError(
            f"model.tp_axis={model.tp_axis!r} != step tp_axis={tp_axis!r}")
    if model.cfg.moe_experts:
        raise NotImplementedError(
            "make_transformer_train_step over a MoE-FFN TransformerLM "
            "(the expert-stacked param specs and the aux loss are not "
            "plumbed; train MoE via the Optimizer path, or shard "
            "experts with parallel/moe.py directly)")
    if (model.sp_axis or None) != (sp_axis or None):
        raise ValueError(
            f"model.sp_axis={model.sp_axis!r} != step sp_axis={sp_axis!r}")

    tie = model.cfg.tie_embeddings
    specs = transformer_tp_specs(tp_axis, tie) if tp_axis else \
        jax.tree_util.tree_map(lambda _: P(),
                               transformer_tp_specs("x", tie),
                               is_leaf=lambda x: isinstance(x, P))
    batch_axes = tuple(a for a in (dp_axis,) if a)
    seq_axes = tuple(a for a in (sp_axis,) if a)
    reduce_axes = batch_axes + seq_axes

    def body(params, slots, tokens, targets, lr, stepno, rng):
        if reduce_axes:
            # unique id per (data, seq) shard — mixed-radix over the axes;
            # NOT folded over tp (tp shards must share the dropout mask)
            shard_id, stride = 0, 1
            for a in reduce_axes:
                shard_id = shard_id + lax.axis_index(a) * stride
                stride *= mesh.shape[a]
            rng = jax.random.fold_in(rng, shard_id)

        def loss_fn(p):
            logp, _ = model.apply({"params": p, "state": {}}, tokens,
                                  training=True, rng=rng)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return jnp.mean(nll)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # batch/sequence shards each saw part of the data → average.
        # No per-leaf TP correction is needed: the model's tp_identity
        # (Megatron "f") already makes replicated-leaf grads full and
        # identical per shard, and TP-sharded leaves' grads are exact.
        if reduce_axes:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, reduce_axes), grads)
            loss = lax.pmean(loss, reduce_axes)

        new_params, new_slots = method.update(grads, params, slots, lr,
                                              stepno)
        return new_params, new_slots, loss

    tok_spec = P(dp_axis, sp_axis)
    slot_specs = slot_specs_for(method, specs)

    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(specs, slot_specs, tok_spec, tok_spec, P(), P(), P()),
        out_specs=(specs, slot_specs, P()),
        check_vma=False,
    )
    step = jax.jit(smapped, donate_argnums=(0, 1))
    if sp_axis is None or getattr(model, "sp_mode", "ring") != "zigzag":
        return step

    # zigzag SP: permute tokens/targets into the balanced layout before
    # the shard_map (the LM loss is a mean over positions, so the
    # consistent permutation leaves it — and every gradient — exactly
    # equal to the contiguous-layout step)
    from bigdl_tpu.parallel.ring_attention import zigzag_order

    n_sp = mesh.shape[sp_axis]
    tok_sharding = NamedSharding(mesh, tok_spec)
    orders = {}  # seq len → device-resident permutation (stable shapes)

    def _order(s):
        if s not in orders:
            orders[s] = jnp.asarray(zigzag_order(n_sp, s))
        return orders[s]

    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        # jax 0.4.x GSPMD partitions a TRACED cross-shard gather that
        # feeds a shard_map in_spec shard-locally — silently wrong
        # values, no error, and with_sharding_constraint does not help.
        # Run the permutation eagerly with an explicit reshard instead:
        # correct on 0.4.x, and int32 tokens make the extra dispatch
        # noise next to the train step. Single-process only (the eager
        # fancy-index needs fully-addressable arrays); multi-host
        # zigzag needs the traced path of jax >= 0.5.
        def zig_step(params, slots, tokens, targets, lr, stepno, rng):
            order = _order(tokens.shape[1])
            return step(params, slots,
                        jax.device_put(tokens[:, order], tok_sharding),
                        jax.device_put(targets[:, order], tok_sharding),
                        lr, stepno, rng)

        return zig_step

    def zig_step(params, slots, tokens, targets, lr, stepno, rng):
        order = _order(tokens.shape[1])
        return step(params, slots, tokens[:, order], targets[:, order],
                    lr, stepno, rng)

    return jax.jit(zig_step, donate_argnums=(0, 1))


def slot_specs_for(method, specs):
    """Optimizer slots are {slot_name: params-like tree} (see
    OptimMethod.init_slots); each slot leaf shards like its param."""
    probe = method.init_slots({"x": jnp.zeros((1,), jnp.float32)})
    return {k: specs for k in probe}


def shard_params(mesh: Mesh, specs, tree):
    """device_put a pytree according to a matching PartitionSpec pytree."""
    return jax.tree_util.tree_map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs, tree, is_leaf=lambda x: isinstance(x, P))
