"""One param-layout spine — the flatten/pad/shard/unstack algebra every
layout consumer shares (ISSUE 18 tentpole (c)).

Before this module the same layout algebra lived in four hand-rolled
copies, each re-deriving the others' invariants:

* ZeRO shard slices — `parallel/data_parallel.py` flattened the params
  pytree, padded to a multiple of the axis size and sliced per device;
* checkpoint reshard — `parallel/distri_optimizer.py::_adapt_slots`
  stripped a saved layout's padding and re-padded into this run's, and
  `serialization/checkpoint.py::_load_sharded_dir` concatenated the
  per-shard slices back into the full vectors;
* serving repack — `models/transformer.py::serving_params` unstacked
  the (L, ...) training stack into per-layer tuples and
  `serving/quant.py` walked those per-layer blocks to quantize;
* tp gather/shard — `serving/tp.py` kept its own table of which
  serving-layout leaves are column-sharded and rebuilt the spec pytree.

Draft hot-swap (tentpole (b)) would have been a fifth copy. Now the
algebra lives HERE once: `FlatParamSpec` (flatten/unflatten/pad +
`shard_slice`, the ZeRO slice rule), `adapt_flat_tree`/`repad_flat`
(the elastic-resume reshard), `concat_shard_trees` (the load-side
inverse), `unstack_blocks`/`map_block_leaves` (the serving repack
walks) and `tp_serving_block_specs`/`tp_serving_specs`/`gather_tree`
(the tp placement schedule). The original call sites delegate — every
pre-existing bitwise pin (zero2==zero1, reshard roundtrip across world
sizes, tp==unsharded, warm==cold) re-ran green over the reroute, and
`tests/test_param_layout.py` pins each path against its pre-refactor
form. The flat side is deliberately ZeRO-3-ready (arXiv 2004.13336):
a future param-sharded forward needs exactly `shard_slice` +
`unflatten` composed per layer, nothing new.

This module depends only on jax/numpy — serving/, models/ and
serialization/ all import it without cycles. Placement itself
(`shard_params` over a mesh) stays with its callers: the spine owns
WHAT the layout is, not where it lives.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["FlatParamSpec", "repad_flat", "adapt_flat_tree",
           "concat_shard_trees", "unstack_blocks", "map_block_leaves",
           "TP_COL", "TP_COL_BIAS", "tp_serving_block_specs",
           "tp_serving_specs", "gather_tree"]


class FlatParamSpec:
    """Flatten/unflatten a params pytree to one padded flat vector.

    Reference parity: Module.getParameters() — the reference compacts all
    weights into a single contiguous Tensor so AllReduceParameter can
    slice it evenly; we pad to a multiple of the mesh axis size so every
    device owns an equal slice (the reference does the same ceil-division
    in AllReduceParameter.init).
    """

    def __init__(self, params: Any, num_shards: int):
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.total = sum(self.sizes)
        self.num_shards = num_shards
        self.padded = ((self.total + num_shards - 1) // num_shards) * num_shards
        self.shard_size = self.padded // num_shards

    def flatten(self, params) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(params)
        flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
        return jnp.pad(flat, (0, self.padded - self.total))

    def unflatten(self, flat: jax.Array):
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(lax.dynamic_slice(flat, (off,), (size,))
                       .reshape(shape).astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def shard_slice(self, flat: jax.Array, index) -> jax.Array:
        """Shard `index`'s (shard_size,) slice of a (padded,) flat
        vector — THE ZeRO slice rule. Traceable (`index` may be
        `lax.axis_index`); the slices of indices 0..num_shards-1 are
        disjoint and cover the padded vector exactly, which is what
        makes all_gather-of-slices bitwise == the replicated vector
        (the zero2==zero1 pin)."""
        return lax.dynamic_slice(flat, (index * self.shard_size,),
                                 (self.shard_size,))


def repad_flat(flat: jax.Array, old_total: int,
               padded: int) -> jax.Array:
    """Re-pad one flat vector from a different world size's layout:
    strip the OLD padding down to the real `old_total` parameters,
    then zero-pad to this layout's `padded` length. The elastic-resume
    primitive `adapt_flat_tree` and `restore_accum` both reduce to."""
    flat = jnp.asarray(flat)
    return jnp.pad(flat[:old_total], (0, padded - old_total))


def adapt_flat_tree(saved_slots, optim_meta, spec: FlatParamSpec):
    """Convert checkpointed slots to this run's ZeRO flat layout.

    Three cases (see the `optim_meta` written at save time):
    - same `padded` → use directly
    - zero{1,2}_flat from a different mesh size → strip padding,
      re-pad (the elastic-resume reshard)
    - pytree slots from a LocalOptimizer checkpoint → flatten each
      top-level slot branch with this spec
    """
    layout = (optim_meta or {}).get("layout")
    if layout in ("zero1_flat", "zero2_flat"):
        if optim_meta["padded"] == spec.padded:
            return saved_slots
        total = optim_meta["total"]
        return jax.tree_util.tree_map(
            lambda v: repad_flat(v, total, spec.padded), saved_slots)
    # local (pytree-per-slot) checkpoint: each top-level entry mirrors
    # the params tree — flatten it into this run's flat vector layout
    return {k: spec.flatten(v) for k, v in saved_slots.items()}


def concat_shard_trees(parts):
    """Concatenate per-shard slot trees (shard order) back into the
    full (padded,) vectors — the load-side inverse of `shard_slice`.
    Host-side on purpose: the shards were loaded as numpy, and callers
    re-place/re-shard onto the current mesh, so a jnp.concatenate here
    would bounce the full optimizer state through the default device
    for nothing."""
    return jax.tree_util.tree_map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *parts)


# ---------------------------------------------------------------- serving
def unstack_blocks(p: Dict[str, Any], num_layers: int) -> tuple:
    """Per-layer block tuples from the stacked (L, ...) training
    layout (tuple/list passthrough) — the serving-repack walk
    `TransformerLM.serving_params` / `_layer_blocks` and the draft
    hot-swap all route through. Device-side tree_map slices: the
    repack is one O(params) gather, never a host fetch."""
    blocks = p["blocks"]
    if isinstance(blocks, (tuple, list)):
        return tuple(blocks)
    return tuple(jax.tree_util.tree_map(lambda a: a[l], blocks)
                 for l in range(num_layers))


def map_block_leaves(params: Dict[str, Any], fn) -> Dict[str, Any]:
    """Rebuild a serving-layout dict with `fn(key, leaf)` applied to
    every per-layer block leaf (top-level entries pass through
    untouched — callers transform those explicitly). Requires the
    per-layer tuple layout: the walk is the quantized-repack /
    hot-swap spine and must never silently retrace a stacked tree."""
    if not isinstance(params["blocks"], (tuple, list)):
        raise ValueError(
            "map_block_leaves expects the per-layer serving layout — "
            "call model.serving_params(variables) first")
    out = dict(params)
    out["blocks"] = tuple(
        {k: fn(k, v) for k, v in bp.items()}
        for bp in params["blocks"])
    return out


# ---------------------------------------------------------------- tp spec
# per-layer serving-layout leaves: which are column-sharded (last dim)
TP_COL = frozenset({"wq", "wk", "wv", "w1"})
TP_COL_BIAS = frozenset({"bq", "bk", "bv", "b1"})


def tp_serving_block_specs(axis: str = "model") -> Dict[str, Any]:
    """PartitionSpecs for ONE per-layer serving block (the unstacked
    dict `serving_params` produces). wq/wk/wv split by head column,
    w1 by ffn hidden; wo/w2/ln/biases-of-row-gemms replicated (the
    bit-identity construction — serving/tp.py module docstring)."""
    spec: Dict[str, Any] = {}
    for k in ("ln1_g", "ln1_b", "ln2_g", "ln2_b", "wo", "bo", "w2",
              "b2"):
        spec[k] = P()
    for k in TP_COL:
        spec[k] = P(None, axis)
    for k in TP_COL_BIAS:
        spec[k] = P(axis)
    return spec


def tp_serving_specs(params, axis: str = "model") -> Dict[str, Any]:
    """Spec pytree matching a serving-layout param tree (per-layer
    tuple of blocks, as `TransformerLM.serving_params` returns).
    Derived from the tree's own structure so checkpoint-loaded trees
    reshard without the model object."""
    block = tp_serving_block_specs(axis)
    specs: Dict[str, Any] = {
        k: P() for k in params if k != "blocks"}
    specs["blocks"] = tuple(block for _ in params["blocks"])
    return specs


def gather_tree(params):
    """Host (checkpoint) form of a possibly-sharded param tree: every
    leaf fetched as a GLOBAL numpy array — the gather half of the
    re-placement round-trip (`serving/tp.py::shard_serving_params` is
    the inverse; placement round-trips bitwise because the mesh only
    places values, never changes them). A deliberate whole-tree fetch:
    host-side setup/checkpoint form by name, never a hot path."""
    return jax.tree_util.tree_map(np.asarray, params)
