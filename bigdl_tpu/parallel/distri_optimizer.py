"""Distributed (mesh) training loop.

Reference parity: optim/DistriOptimizer.scala — the heart of the
reference (SURVEY.md §3.1): per-iteration Spark job → local fwd/bwd →
AllReduceParameter reduce-scatter → sharded optim step → all-gather,
plus driver-side triggers/validation/checkpoint and failure recovery.

TPU-first redesign: the per-iteration Spark job becomes ONE jitted SPMD
step over the mesh (see data_parallel.py); the driver loop below is pure
host orchestration. Multi-host: every process runs this same loop in
lockstep (PJRT collectives span hosts); each feeds its own data shard —
exactly the reference's one-executor-per-node layout with "Spark only
partitions data".

Failure recovery (reference: DistriOptimizer retry + reload-last-
checkpoint, SURVEY.md §5.3): on a step exception with a checkpoint
configured, reload the latest checkpoint and continue (`max_retries`).
The reference gets its *guarantees* from Spark task retry + lineage
(arXiv 1804.05839 §4); the substitutes here are explicit and tested:
checkpoint loads verify per-array checksums and fall back past corrupt
dirs (serialization/checkpoint.py), the numeric-anomaly guard discards
NaN/Inf/spike updates on device with skip/rollback/halt policies
(utils/anomaly.py, `Optimizer.set_anomaly_guard`), and every recovery
path is exercised deterministically by fault injection
(utils/faults.py, scripts/fault_drill.py).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.optim.metrics import Metrics, Timer
from bigdl_tpu.optim.optimizer import LocalOptimizer, Optimizer, _batch_iterator
from bigdl_tpu.optim.validation import ValidationResult
from bigdl_tpu.parallel.data_parallel import (
    FlatParamSpec, make_dp_accum_steps, make_dp_eval_step,
    make_dp_train_step,
)
from bigdl_tpu.parallel.mesh import host_to_global, place_global

logger = logging.getLogger("bigdl_tpu.optim")


class DistriOptimizer(LocalOptimizer):
    """Mesh data-parallel optimizer (reference: optim/DistriOptimizer.scala)."""

    def __init__(self, opt: Optimizer, mesh: Mesh, axis: str = "data",
                 grad_dtype: Optional[str] = "bfloat16", max_retries: int = 3,
                 zero: int = 1):
        super().__init__(opt)
        if zero not in (1, 2):
            raise ValueError(f"zero must be 1 or 2, got {zero!r}")
        self.mesh = mesh
        self.axis = axis
        self.grad_dtype = grad_dtype
        self.max_retries = max_retries
        self.zero = zero
        self._gather_fn = None

    # ------------------------------------------------------------- helpers
    def _batch_spec(self, x) -> P:
        return P(self.axis, *([None] * (x.ndim - 1)))

    def _global(self, x):
        """Place a host batch (array or tuple of arrays for multi-input
        models) on the mesh, sharded over the data axis."""
        if isinstance(x, tuple):
            return tuple(self._global(e) for e in x)
        arr = np.asarray(x)
        return host_to_global(self.mesh, self._batch_spec(arr), arr)

    def _place_sharded_slots(self, slots):
        # multi-process safe: every process holds the identical global
        # slot values (same init / same checkpoint files)
        return place_global(self.mesh, P(self.axis), slots)

    def _gather(self, tree):
        """Fetch a (possibly cross-process-sharded) ZeRO-1 tree to host.

        Single process: a plain device_get. Multi-host: sharded arrays
        span non-addressable devices, so an XLA all-gather (jitted
        identity re-sharded to replicated) runs first — the analogue of
        the reference's driver pulling weight slices before writing a
        checkpoint (SURVEY.md §5.4). The jitted identity is built once
        per optimizer so repeated checkpoints hit the trace cache."""
        if jax.process_count() == 1:
            return jax.device_get(tree)
        if self._gather_fn is None:
            self._gather_fn = jax.jit(
                lambda t: t,
                out_shardings=NamedSharding(self.mesh, P()))
        return jax.device_get(self._gather_fn(tree))

    @staticmethod
    def _local_shard_slices(tree, spec, mesh=None, axis="data"):
        """{shard index: host tree of that shard's slot slices} for the
        shards whose devices are addressable from THIS process — the
        "each host saves only its shards" half of the async sharded
        checkpoint (ISSUE 9). Slot leaves are global (padded,) vectors
        sharded P(axis), so each addressable device shard IS one ZeRO
        shard; its global offset // shard_size is the shard index.
        (static: scripts/scaling_bench.py reuses it to feed the
        checkpoint-overlap row the exact shard trees the real save
        path writes)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            # slot-less method (plain SGD): no sharded array to read
            # ownership from, so derive it from the mesh — shard i
            # belongs to the process owning the i-th device on the
            # data axis. Without a mesh (single-process callers) every
            # shard is this host's.
            if mesh is None:
                return {i: tree for i in range(spec.num_shards)}
            me = jax.process_index()
            axes = list(mesh.axis_names)
            dev = np.moveaxis(np.asarray(mesh.devices),
                              axes.index(axis), 0).reshape(
                                  mesh.shape[axis], -1)
            return {i: tree for i in range(spec.num_shards)
                    if dev[i, 0].process_index == me}
        per_shard: Dict[int, list] = {}
        for li, leaf in enumerate(leaves):
            for sh in leaf.addressable_shards:
                start = sh.index[0].start or 0
                sidx = start // spec.shard_size
                per_shard.setdefault(
                    sidx, [None] * len(leaves))[li] = np.asarray(sh.data)
        return {s: jax.tree_util.tree_unflatten(treedef, lv)
                for s, lv in sorted(per_shard.items())}

    @staticmethod
    def _adapt_slots(saved_slots, optim_meta, spec):
        """Convert checkpointed slots to this run's ZeRO flat layout.

        Three cases (see the `optim_meta` written at save time):
        - same `padded` → use directly
        - zero{1,2}_flat from a different mesh size → strip padding,
          re-pad (the elastic-resume reshard)
        - pytree slots from a LocalOptimizer checkpoint → flatten each
          top-level slot branch with this spec

        The algebra lives in the param-layout spine (ISSUE 18) — this
        wrapper keeps the historical call site (scripts and the
        recover/resume paths reference it by name).
        """
        from bigdl_tpu.parallel.param_layout import adapt_flat_tree

        return adapt_flat_tree(saved_slots, optim_meta, spec)

    # ------------------------------------------------------------------ run
    def run(self):
        o = self.o
        n = self.mesh.shape[self.axis]
        if o.batch_size is None or o.batch_size % n != 0:
            raise ValueError(
                f"global batch_size {o.batch_size} must be divisible by the "
                f"'{self.axis}' mesh axis size {n}")

        if o.validation_methods and (o.validation_batch_size or o.batch_size) % n != 0:
            raise ValueError(
                f"validation batch_size {o.validation_batch_size} must be "
                f"divisible by the '{self.axis}' mesh axis size {n}")

        # Multi-host: batch_size is GLOBAL; each process feeds its
        # 1/nproc shard of every batch (the reference's "Spark only
        # partitions data" — each executor iterates its partition).
        nproc = jax.process_count()
        if o.batch_size % nproc:
            raise ValueError(
                f"global batch_size {o.batch_size} must be divisible by "
                f"the process count {nproc}")
        vbs = o.validation_batch_size or o.batch_size
        if o.validation_methods and vbs % nproc:
            raise ValueError(
                f"validation batch_size {vbs} must be divisible by the "
                f"process count {nproc}")
        self._local_bs = o.batch_size // nproc
        self._local_vbs = vbs // nproc

        rng = jax.random.PRNGKey(o.seed)
        variables = dict(o.model.variables)
        spec = FlatParamSpec(variables["params"], n)
        self._unflatten = jax.jit(spec.unflatten)
        logger.info("DistriOptimizer: %d devices on axis %r (ZeRO-%d), "
                    "%d params (padded %d, %d per shard)", n, self.axis,
                    self.zero, spec.total, spec.padded, spec.shard_size)

        # ZeRO-2: the master fp32 flat weights persist SHARDED on the
        # data axis between steps (the step all_gathers on entry)
        w_spec = P(self.axis) if self.zero == 2 else P()

        guard = o.anomaly_guard
        accum = o.grad_accum
        if accum == 1:
            step_fn = make_dp_train_step(
                o.model, o.criterion, o.optim_method, self.mesh, spec,
                axis=self.axis, grad_dtype=self.grad_dtype,
                clip_const=o.grad_clip_const, clip_norm=o.grad_clip_norm,
                precision=o.precision, health=guard is not None,
                zero=self.zero)
        else:
            micro_fn, apply_fn = make_dp_accum_steps(
                o.model, o.criterion, o.optim_method, self.mesh, spec,
                axis=self.axis, grad_dtype=self.grad_dtype,
                clip_const=o.grad_clip_const, clip_norm=o.grad_clip_norm,
                precision=o.precision, health=guard is not None,
                zero=self.zero)
        if o.validation_methods:
            eval_fn = make_dp_eval_step(o.model, o.validation_methods,
                                        self.mesh, self.axis)

        flat_w = place_global(self.mesh, w_spec,
                              spec.flatten(variables["params"]))
        mod_state = place_global(self.mesh, P(), variables["state"])
        # slot arrays are GLOBAL (padded,) shapes, device-placed sharded on
        # the data axis — each device materializes only its (shard_size,)
        # slice: the ZeRO-1 optimizer-state sharding
        slots = self._place_sharded_slots(
            o.optim_method.init_slots(jnp.zeros((spec.padded,), jnp.float32)))

        def fresh_acc():
            return place_global(self.mesh, P(self.axis),
                                jnp.zeros((spec.padded,), jnp.float32))

        g_acc = fresh_acc() if accum > 1 else None
        micro_n = 0
        # "nupdates" is the applied-update clock (stepno/schedules);
        # see LocalOptimizer.run — guard-discarded updates and
        # uncounted micro-batches do not advance it
        train_state: Dict[str, Any] = {"epoch": 1, "neval": 0,
                                       "nupdates": 0, "records": 0,
                                       "loss": None, "score": None}

        def adopt_train_state(saved_ts):
            train_state.update(saved_ts)
            if "nupdates" not in saved_ts:  # pre-counter checkpoint
                train_state["nupdates"] = train_state["neval"] // accum

        def restore_accum(optim_meta):
            """Reinstall a checkpointed mid-cycle accumulator (or reset).
            Handles a pytree-layout accumulator from a LocalOptimizer
            checkpoint (flatten into this run's ZeRO-1 layout) and a
            flat accumulator from a different mesh size (strip the old
            padding, re-pad — mirrors _adapt_slots)."""
            nonlocal g_acc, micro_n
            saved = o.checkpoint.load_accum() if o.checkpoint else None
            if accum == 1:
                if saved is not None:
                    logger.warning(
                        "checkpoint holds a mid-cycle accumulator (%d "
                        "micro-batches) but this run has grad_accum=1; "
                        "the partial gradients are discarded",
                        int(saved["micro_n"]))
                return
            if saved is None or int(saved["micro_n"]) >= accum:
                if saved is not None:
                    logger.warning(
                        "checkpointed accumulation cycle (%d micro-"
                        "batches) does not fit grad_accum=%d; restarting "
                        "the cycle", int(saved["micro_n"]), accum)
                g_acc, micro_n = fresh_acc(), 0
                return
            acc = saved["g_acc"]
            if isinstance(acc, dict):
                flat = spec.flatten(acc)
            else:
                from bigdl_tpu.parallel.param_layout import repad_flat

                flat = jnp.asarray(acc)
                old_total = (optim_meta or {}).get("total")
                if flat.shape[0] != spec.padded:
                    if old_total is None or old_total > spec.padded:
                        raise ValueError(
                            f"cannot adapt accumulator of length "
                            f"{flat.shape[0]} to padded {spec.padded}")
                    flat = repad_flat(flat, old_total, spec.padded)
            g_acc = place_global(self.mesh, P(self.axis), flat)
            micro_n = int(saved["micro_n"])

        def recover():
            """Reload the newest VALID checkpoint (Checkpoint.load skips
            corrupt dirs) and re-align the batch stream — shared by the
            step-exception retry path and the anomaly guard's rollback
            policy (the reference's reload-last-checkpoint recovery,
            SURVEY.md §5.3)."""
            nonlocal flat_w, mod_state, slots, batches
            o.checkpoint.wait()  # surface pending async-save errors
            saved_vars, saved_slots, saved_ts, om = o.checkpoint.load(
                with_optim_meta=True)
            flat_w = place_global(self.mesh, w_spec,
                                  spec.flatten(saved_vars["params"]))
            mod_state = place_global(self.mesh, P(), saved_vars["state"])
            slots = self._place_sharded_slots(
                self._adapt_slots(saved_slots, om, spec))
            adopt_train_state(saved_ts)
            batches = _batch_iterator(o.dataset, True, self._local_bs,
                                      skip=train_state["neval"])
            restore_accum(om)

        if o._resume and o.checkpoint is not None and o.checkpoint.latest():
            saved_vars, saved_slots, saved_ts, optim_meta = o.checkpoint.load(
                with_optim_meta=True)
            flat_w = place_global(self.mesh, w_spec,
                                  spec.flatten(saved_vars["params"]))
            mod_state = place_global(self.mesh, P(), saved_vars["state"])
            slots = self._place_sharded_slots(
                self._adapt_slots(saved_slots, optim_meta, spec))
            adopt_train_state(saved_ts)
            restore_accum(optim_meta)
            logger.info("resumed from %s at %s",
                        o.checkpoint._last_loaded, saved_ts)

        from bigdl_tpu.utils import faults

        plan = faults.get_plan()
        dataset_size = o.dataset.size()
        # fast-forward the deterministic batch stream past what the
        # checkpointed run consumed (bit-for-bit resume; no-op fresh)
        batches = _batch_iterator(o.dataset, True, self._local_bs,
                                  skip=train_state["neval"])
        iter_start = time.perf_counter()
        retries = 0

        while not o.end_when(train_state):
            # outside the retry try — the retry budget must never
            # absorb a preemption (faults.FaultPlan.maybe_preempt)
            try:
                plan.maybe_preempt(train_state["neval"])
            except faults.Preempted:
                # dead worker propagating out (recovery is a fresh
                # process with --resume): record the incident for the
                # flight recorder (ISSUE 11) before re-raising
                from bigdl_tpu import obs

                obs.emit_event("preempted", plane="training",
                               step=train_state["neval"])
                raise
            try:
                plan.maybe_raise("step", train_state["neval"])
                with Timer(self.metrics, "data_fetch_s"):
                    mb = next(batches)
                if plan.fires("nan", train_state["neval"]):
                    mb = faults.poison_minibatch(mb)
                # schedules and the optimizer's step counter advance per
                # APPLIED update, not per (micro-)batch (mirrors
                # LocalOptimizer): a guard-discarded update re-uses its
                # step index
                eff_step = train_state["nupdates"]
                lr = o.optim_method.current_rate(
                    train_state if accum == 1 and guard is None
                    else {**train_state, "neval": eff_step})
                step_rng = jax.random.fold_in(rng, train_state["neval"])
                thr = None if guard is None else jnp.asarray(
                    guard.threshold(), jnp.float32)
                with Timer(self.metrics, "dispatch_s"):
                    if accum == 1:
                        step_args = (
                            flat_w, slots, mod_state,
                            self._global(mb.input), self._global(mb.target),
                            jnp.asarray(lr, jnp.float32),
                            jnp.asarray(eff_step, jnp.int32),
                            step_rng)
                        if guard is None:
                            flat_w, slots, mod_state, loss = step_fn(
                                *step_args)
                        else:
                            (flat_w, slots, mod_state, loss, ok_d,
                             gnorm_d) = step_fn(*step_args, thr)
                    else:
                        micro_args = (
                            flat_w, g_acc, mod_state,
                            self._global(mb.input), self._global(mb.target),
                            step_rng)
                        if guard is None:
                            g_acc, mod_state, loss = micro_fn(*micro_args)
                            micro_n += 1
                        else:
                            (g_acc, mod_state, loss, ok_d,
                             gnorm_d) = micro_fn(*micro_args, thr)
                            # an anomalous micro-gradient was zeroed out
                            # of the accumulator on device; don't count
                            # it toward the cycle either
                            micro_n += int(bool(ok_d))
                        if micro_n == accum:
                            flat_w, slots, g_acc = apply_fn(
                                flat_w, slots, g_acc,
                                jnp.asarray(lr, jnp.float32),
                                jnp.asarray(eff_step, jnp.int32),
                                jnp.asarray(accum, jnp.float32))
                            micro_n = 0
                            train_state["nupdates"] += 1
            except Exception:
                if (o.checkpoint is not None and o.checkpoint.latest()
                        and retries < self.max_retries):
                    retries += 1
                    logger.exception(
                        "step failed; recovering from checkpoint "
                        "(retry %d/%d)", retries, self.max_retries)
                    recover()
                    continue
                raise

            ok_host, gnorm_host = True, None
            if guard is not None:
                # scalar fetch syncs the step (the documented guard
                # cost); the anomalous update is already discarded on
                # device — the host only applies policy
                ok_host, gnorm_host = bool(ok_d), float(gnorm_d)
                action = guard.observe(ok_host, gnorm_host,
                                       train_state["neval"])
                if action == "rollback":
                    self._require_rollback_checkpoint()
                    recover()
                    continue

            # consecutive-failure budget, not a lifetime cap (the reference
            # budgets retries against repeated failure of the same step)
            retries = 0

            real = getattr(mb, "real_size", mb.size)
            train_state["neval"] += 1
            if accum == 1:
                # a guard-discarded update keeps its step index for the
                # next batch; the applied-update clock only advances on
                # healthy steps (accum>1 advances at apply_fn above)
                train_state["nupdates"] += 1 if guard is None \
                    else int(ok_host)
            train_state["records"] += real
            train_state["loss"] = loss
            now = time.perf_counter()
            iter_wall, iter_start = now - iter_start, now
            self.metrics.add("iter_s", iter_wall)
            throughput = real / max(iter_wall, 1e-9)

            # one emission path (obs/training.StepTelemetry): registry
            # + event log + TrainSummary sink + log line. The
            # float(loss) fence only runs on steps that always fetched
            # it (summary sink armed, or a log_every step) — telemetry
            # alone never adds a device→host sync; off-fence events
            # omit the loss field (piggyback contract), and with
            # everything off the step skips emission entirely so the
            # host can run ahead of the device
            from bigdl_tpu import obs

            fence = (o.train_summary is not None
                     or train_state["neval"] % o.log_every == 0)
            if fence or obs.enabled():
                loss_host = None
                if fence:
                    with Timer(self.metrics, "fence_s"):
                        loss_host = float(loss)
                self.telemetry.emit_step(
                    epoch=train_state["epoch"],
                    step=train_state["neval"],
                    loss=loss_host, lr=lr, throughput=throughput,
                    records=real, update_applied=ok_host,
                    gnorm=gnorm_host,
                    metrics_summary=self.metrics.summary())

            if train_state["records"] >= dataset_size:
                train_state["epoch"] += 1
                train_state["records"] = 0

            if (o.validation_trigger is not None
                    and o.validation_trigger(train_state)):
                res = self._validate_mesh(eval_fn, spec, flat_w, mod_state)
                for name, r in res.items():
                    v, cnt = r.result()
                    logger.info("validation %s = %.6f (%d)", name, v, cnt)
                    if o.validation_summary is not None:
                        o.validation_summary.add_scalar(
                            name, v, train_state["neval"])
                first = next(iter(res.values()), None)
                if first is not None:
                    train_state["score"] = first.result()[0]
                    sched = o.optim_method.schedule
                    if hasattr(sched, "on_metric"):
                        sched.on_metric(train_state["score"])

            if (o.checkpoint is not None and o.checkpoint_trigger is not None
                    and o.checkpoint_trigger(train_state)):
                # zero2 keeps flat_w sharded across processes: gather
                # before unflattening the model tree for the save.
                # The gather is a COLLECTIVE (every host participates)
                # but the full-model host tree is materialized only
                # where it will be written — secondaries' sharded
                # saves ignore model_variables, so they must not pay a
                # whole-model device->host fetch on the step path.
                # Sharded zero1 saves need the host copy too: the
                # primary-only _unflatten below must never be handed a
                # device-global array (a jit entered by one controller
                # of a multi-process run is a launch mismatch)
                flat_for_save = self._gather(flat_w) \
                    if (nproc > 1 and (self.zero == 2
                                       or o.checkpoint.sharded)) \
                    else flat_w
                primary = jax.process_index() == 0
                if primary or not o.checkpoint.sharded:
                    saved_variables = {
                        "params": jax.device_get(
                            self._unflatten(flat_for_save)),
                        "state": jax.device_get(mod_state),
                    }
                else:
                    saved_variables = None
                accum_state = None
                if micro_n:  # mid-cycle: persist the partial accumulator
                    accum_state = {"g_acc": self._gather(g_acc),
                                   "micro_n": micro_n}
                train_meta = {k: train_state[k] for k in
                              ("epoch", "neval", "nupdates", "records")}
                optim_meta = {"layout": f"zero{self.zero}_flat",
                              "num_shards": n,
                              "total": spec.total,
                              "padded": spec.padded}
                with Timer(self.metrics, "checkpoint_s"):
                    # with async_save this times only the host snapshot
                    # + enqueue; the disk write overlaps the next steps
                    # (scaling_bench's checkpoint-overlap row measures
                    # the on-vs-off per-step cost)
                    if o.checkpoint.sharded:
                        # each host hands over exactly the shard slices
                        # its devices own — no slot gather, no
                        # full-state replica on any single host
                        path = o.checkpoint.save_sharded(
                            train_state["neval"], saved_variables,
                            self._local_shard_slices(
                                slots, spec, mesh=self.mesh,
                                axis=self.axis),
                            nshards=n, train_state=train_meta,
                            optim_meta=optim_meta,
                            accum_state=accum_state)
                    else:
                        path = o.checkpoint.save(
                            train_state["neval"], saved_variables,
                            self._gather(slots),
                            train_meta,
                            optim_meta=optim_meta,
                            accum_state=accum_state)
                if nproc > 1:
                    # barrier: no host may run ahead (and potentially
                    # recover from this checkpoint) until the write is
                    # complete everywhere. Async saves drain first —
                    # cross-host overlap would need a coordination
                    # service; the async win is measured per-host
                    # (single-process) where steps genuinely never
                    # stall on I/O
                    o.checkpoint.wait()
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        f"ckpt-{train_state['neval']}")
                logger.info("checkpoint -> %s", path)

        # end trigger may fire mid-accumulation-cycle: flush the partial
        # accumulator (mean over micro-batches actually seen) so that
        # gradient work isn't silently discarded — mirrors LocalOptimizer
        if accum > 1 and micro_n:
            eff_step = train_state["nupdates"]
            lr = o.optim_method.current_rate(
                {**train_state, "neval": eff_step})
            flat_w, slots, g_acc = apply_fn(
                flat_w, slots, g_acc, jnp.asarray(lr, jnp.float32),
                jnp.asarray(eff_step, jnp.int32),
                jnp.asarray(micro_n, jnp.float32))
            micro_n = 0

        if o.checkpoint is not None:
            # drain the background writer: a failed async save (incl.
            # an injected ckpt_async_torn kill) must fail the run
            o.checkpoint.wait()
        flat_final = self._gather(flat_w) \
            if (self.zero == 2 and jax.process_count() > 1) else flat_w
        o.model.variables = {
            "params": jax.device_get(self._unflatten(flat_final)),
            "state": jax.device_get(mod_state),
        }
        return o.model

    # ------------------------------------------------------------ validate
    def _validate_mesh(self, eval_fn, spec, flat_w, mod_state):
        o = self.o
        params = self._unflatten(flat_w)
        results = [ValidationResult(0.0, 0.0, m.name)
                   for m in o.validation_methods]
        it = _batch_iterator(o.validation_dataset, False, self._local_vbs)
        multi = jax.process_count() > 1
        last = None
        while True:
            mb = next(it, None)
            if multi:
                # Hosts may own uneven validation shards (sizes differ
                # by up to one batch). eval_fn and _global are cross-
                # process collectives, so EVERY host must join EVERY
                # round: exchange have-data flags, and exhausted hosts
                # feed an all-masked copy of their previous batch.
                from jax.experimental import multihost_utils

                flags = multihost_utils.process_allgather(
                    np.asarray([0 if mb is None else 1]))
                if not flags.any():
                    break
                if mb is None:
                    if last is None:
                        raise RuntimeError(
                            "a host has an empty validation shard; give "
                            "every process at least one batch "
                            "(DataSet.sharded of >= nproc samples)")
                    # every filler row must be IDENTICAL so the Loss
                    # edge-correction cancels the shard exactly
                    from bigdl_tpu.dataset.sample import MiniBatch

                    def tile_first(x, rows):
                        if isinstance(x, tuple):
                            return tuple(tile_first(e, rows) for e in x)
                        a = np.asarray(x)
                        return np.repeat(a[:1], rows, axis=0)

                    mb = MiniBatch(tile_first(last.input, last.size),
                                   tile_first(last.target, last.size))
                    real = 0
                else:
                    last = mb
                    real = getattr(mb, "real_size", mb.size)
            elif mb is None:
                break
            else:
                real = getattr(mb, "real_size", mb.size)
            mask = (np.arange(mb.size) < real).astype(np.float32)
            stats = eval_fn(params, mod_state,
                            self._global(mb.input), self._global(mb.target),
                            self._global(mask))
            for i, (s, c) in enumerate(stats):
                results[i] = results[i] + ValidationResult(float(s), float(c))
        return {m.name: r for m, r in zip(o.validation_methods, results)}
