"""Host-side span tracer — Chrome-trace / Perfetto JSON.

Records named spans (begin/end pairs collapsed to complete "X" events)
from the serving request lifecycle (queued → admitted → prefill →
decode×N → terminal status) and the training step phases (data / step
/ fence / checkpoint), and renders them as a `chrome://tracing` /
Perfetto-loadable JSON object.

Alignment with device traces: when a span is recorded while a
`utils/profiler.trace()` capture is active, the tracer ALSO enters a
`jax.profiler.TraceAnnotation` of the same name, so the host span and
the XLA device timeline carry matching labels in one Perfetto view.
The annotation is host-side only — a span NEVER adds a device→host
sync (the block_until_ready/FencedTimer caveat applies to any timing
you do around device work: wall-clock spans around an un-fenced
dispatch measure dispatch, not compute; fence with a real fetch first,
see utils/profiler.FencedTimer).

The tracer is OFF by default (`enabled=False` → `span()` is a shared
no-op context manager, ~no overhead); drills and profiling sessions
turn it on. Both the clock and the buffer are injectable/bounded.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

__all__ = ["SpanTracer", "get_tracer", "set_tracer"]


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _obs_enabled() -> bool:
    """Global kill-switch check (call-time import — obs/__init__
    imports this module, so a top-level import would cycle). Every
    record path honors BIGDL_OBS=off even on an enabled tracer, per
    the 'every emission path early-outs on enabled()' contract."""
    from bigdl_tpu import obs

    return obs.enabled()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = self.tracer._clock()
        self.tracer._enter_annotation(self.name)
        return self

    def __exit__(self, *exc):
        self.tracer._exit_annotation()
        self.tracer._record(self.name, self.cat, self._t0,
                            self.tracer._clock(), self.args)
        return False


class SpanTracer:
    """Bounded buffer of complete spans + instant events.

    `clock` returns seconds (injectable — the serving engine passes its
    own clock so deadline drills produce deterministic spans);
    timestamps are exported in microseconds as Chrome trace requires."""

    def __init__(self, capacity: int = 65536, clock=None,
                 enabled: bool = False, pid: Optional[int] = None):
        import time as _time

        self._clock = clock or _time.perf_counter
        self._events: deque = deque(maxlen=capacity)
        self.enabled = enabled
        self._pid = os.getpid() if pid is None else pid
        self._ann = threading.local()

    # ------------------------------------------------------------ record
    def span(self, name: str, cat: str = "host",
             args: Optional[dict] = None):
        """Context manager recording one complete ("X") span."""
        if not self.enabled or not _obs_enabled():
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None) -> None:
        """Zero-duration marker ("i" event) — terminal statuses,
        faults."""
        if not self.enabled or not _obs_enabled():
            return
        self._events.append({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": self._clock() * 1e6, "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            **({"args": args} if args else {})})

    def complete(self, name: str, cat: str, t0: float, t1: float,
                 args: Optional[dict] = None) -> None:
        """Record a span from externally measured endpoints (seconds).

        Clock-domain contract: `t0`/`t1` must come from the SAME clock
        the rest of the timeline uses. The serving engine passes its
        own injectable clock's readings here (the ISSUE 5 requirement
        that request spans be deterministic under the deadline
        drills); the training Timer spans use this tracer's clock
        (default perf_counter). On Linux the defaults (monotonic vs
        perf_counter) share an epoch; elsewhere, or with an injected
        engine clock, build the tracer with the engine's clock
        (`SpanTracer(clock=engine_clock, enabled=True)`) to keep the
        merged timeline aligned."""
        if not self.enabled or not _obs_enabled():
            return
        self._record(name, cat, t0, t1, args)

    def _record(self, name, cat, t0, t1, args):
        self._events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": t0 * 1e6, "dur": max(t1 - t0, 0.0) * 1e6,
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            **({"args": args} if args else {})})

    # ------------------------------------------------- jax trace alignment
    def _enter_annotation(self, name: str) -> None:
        """Mirror the span as a jax host TraceAnnotation so a
        concurrent jax.profiler capture shows the same label on its
        host track. Lazy import; never raises (telemetry must not take
        down the loop it observes)."""
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
            stack = getattr(self._ann, "stack", None)
            if stack is None:
                stack = self._ann.stack = []
            stack.append(ann)
        except Exception:
            pass

    def _exit_annotation(self) -> None:
        stack = getattr(self._ann, "stack", None)
        if stack:
            try:
                stack.pop().__exit__(None, None, None)
            except Exception:
                pass

    # ------------------------------------------------------------- export
    def to_chrome_trace(self) -> Dict[str, object]:
        """`{"traceEvents": [...], "displayTimeUnit": "ms"}` — loads
        in chrome://tracing and ui.perfetto.dev."""
        return {"traceEvents": list(self._events),
                "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def events(self, name: Optional[str] = None) -> List[dict]:
        return [e for e in self._events
                if name is None or e["name"] == name]

    def clear(self) -> None:
        self._events.clear()


_tracer = SpanTracer()


def get_tracer() -> SpanTracer:
    return _tracer


def set_tracer(tracer: Optional[SpanTracer]) -> SpanTracer:
    """Install a tracer (None → fresh disabled default); returns the
    active one."""
    global _tracer
    _tracer = tracer or SpanTracer()
    return _tracer
