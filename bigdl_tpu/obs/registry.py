"""Process-wide metrics registry — counters, gauges, fixed-bucket
histograms with label sets.

Reference anchor: the reference's operability story is per-iteration
`optim/Metrics` counters printed to the driver log (arXiv 1804.05839
§4) plus BigDL 2.0 Cluster Serving's Prometheus-style monitoring
(arXiv 2204.01715). Here both planes report into ONE registry with a
shared schema: deterministic `snapshot()` (sorted names and label
sets), Prometheus text exposition, and JSON export.

Design constraints (carried as tests, tests/test_obs.py):

* **Injectable clock.** The registry never reads wall time on the hot
  path; the clock is only consulted by `snapshot()` for the stamp, and
  is injectable so drill snapshots are bit-reproducible.
* **Bounded memory.** Histograms are FIXED-bucket (counts + sum +
  count, no sample retention) — a long-lived serving engine observes
  millions of latencies into a few dozen ints. Quantiles are estimated
  by linear interpolation inside the owning bucket, the standard
  Prometheus `histogram_quantile` scheme.
* **Cheap when disabled.** Every mutator checks `obs.enabled()` via
  the child objects handed out once at registration; the per-call cost
  when ON is a dict hit + int add (+ a bisect for histograms).
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "set_registry", "DEFAULT_LATENCY_BUCKETS",
           "quantile_from_buckets", "series_key"]


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical flat key for one labeled series —
    `name{k1=v1,k2=v2}` with labels sorted, bare `name` when
    unlabeled. THE rendering shared by obs.provenance (bench rows) and
    scripts/obs_report (snapshot digests): the same series must key
    identically everywhere."""
    if not labels:
        return name
    return (name + "{"
            + ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            + "}")

# seconds-scale latency buckets: 100 us .. 10 s, roughly log-spaced —
# wide enough for both CPU decode steps (~10 ms) and tunnel-TPU steps
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    1e-1, 2.5e-1, 5e-1, 1.0, 2.5, 5.0, 10.0)


def quantile_from_buckets(buckets: Sequence[float],
                          counts: Sequence[int],
                          q: float) -> Optional[float]:
    """Estimate the q-quantile of a fixed-bucket histogram by linear
    interpolation inside the owning bucket (Prometheus
    `histogram_quantile` semantics). `counts` has one entry per upper
    bound in `buckets` plus a trailing +Inf overflow entry. None on an
    empty histogram; the +Inf bucket clamps to the top finite edge (an
    unbounded bucket has no upper edge to lerp toward). THE estimator
    — live registry children and snapshot consumers (obs_report) share
    it so their percentiles can never drift."""
    total = sum(counts)
    if total == 0:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError("quantile must be in [0, 1]")
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            if i == len(buckets):               # +Inf bucket
                return buckets[-1] if buckets else None
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * ((rank - (cum - c)) / c)
    return buckets[-1] if buckets else None


def _label_key(labelnames: Sequence[str],
               labels: Dict[str, str]) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared "
            f"labelnames {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Base: a named family holding one child per label-value tuple."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **labels: str):
        key = _label_key(self.labelnames, labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _default_child(self):
        """The label-less child (only valid with no labelnames)."""
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} declares labels "
                f"{self.labelnames}; use .labels(...)")
        return self.labels()

    def _new_child(self):
        raise NotImplementedError

    # ------------------------------------------------------------- export
    def _sorted_children(self) -> List[Tuple[Tuple[str, ...], object]]:
        return sorted(self._children.items())


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return sum(c.value for c in self._children.values())


class _GaugeChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        child = self._children.get(())
        return child.value if child is not None else 0.0


class _HistogramChild:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets                 # upper bounds, ascending
        self.counts = [0] * (len(buckets) + 1)  # +1 = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """See quantile_from_buckets — the one shared estimator."""
        return quantile_from_buckets(self.buckets, self.counts, q)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = b

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def quantile(self, q: float) -> Optional[float]:
        return self._default_child().quantile(q)


class MetricsRegistry:
    """Named metric families; one per process by default
    (`get_registry()`), swappable for isolation (`set_registry`).

    Registration is idempotent: re-requesting a name returns the
    existing family (mismatched kind/labels/buckets raises — two call
    sites disagreeing on a metric's schema is a bug, not a merge)."""

    def __init__(self, clock=None):
        import time as _time

        self._clock = clock or _time.time
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- registration
    def _get_or_make(self, cls, name: str, help: str,
                     labelnames: Sequence[str], **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise ValueError(f"metric {name!r} already registered as "
                             f"{m.kind}, not {cls.kind}")
        if m.labelnames != tuple(labelnames):
            raise ValueError(f"metric {name!r} labelnames mismatch: "
                             f"{m.labelnames} vs {tuple(labelnames)}")
        if kw.get("buckets") is not None \
                and tuple(sorted(float(b) for b in kw["buckets"])) \
                != getattr(m, "buckets", None):
            raise ValueError(f"histogram {name!r} bucket mismatch")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=tuple(buckets))

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every family — test/drill isolation."""
        with self._lock:
            self._metrics.clear()

    # ------------------------------------------------------------- export
    def snapshot(self) -> dict:
        """Deterministic dict: metric names sorted, label tuples
        sorted; identical metric activity → byte-identical JSON (the
        clock stamp is the only time-dependent field, and it is
        injectable)."""
        out: Dict[str, dict] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            fam: dict = {"kind": m.kind, "help": m.help,
                         "labelnames": list(m.labelnames), "series": []}
            for key, child in m._sorted_children():
                labels = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    fam["series"].append({
                        "labels": labels,
                        "buckets": list(m.buckets),
                        "counts": list(child.counts),
                        "sum": child.sum, "count": child.count})
                else:
                    fam["series"].append({"labels": labels,
                                          "value": child.value})
            out[name] = fam
        return {"schema": 1, "ts": self._clock(), "metrics": out}

    def to_json(self, **dumps_kw) -> str:
        dumps_kw.setdefault("sort_keys", True)
        return json.dumps(self.snapshot(), **dumps_kw)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (families sorted, series
        sorted within a family)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key, child in m._sorted_children():
                base = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    cum = 0
                    for ub, c in zip(list(m.buckets) + ["+Inf"],
                                     child.counts):
                        cum += c
                        lbl = _fmt_labels({**base, "le": _fmt_num(ub)})
                        lines.append(f"{name}_bucket{lbl} {cum}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(base)} "
                        f"{_fmt_num(child.sum)}")
                    lines.append(
                        f"{name}_count{_fmt_labels(base)} {child.count}")
                else:
                    lines.append(f"{name}{_fmt_labels(base)} "
                                 f"{_fmt_num(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_num(v) -> str:
    if isinstance(v, str):
        return v
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    def esc(v):
        return str(v).replace("\\", "\\\\").replace('"', '\\"')
    inner = ",".join(f'{k}="{esc(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def set_registry(registry: Optional[MetricsRegistry]) -> MetricsRegistry:
    """Install a registry (None → fresh default). Returns the active
    one, so `set_registry(MetricsRegistry(clock=fake))` reads well in
    drills."""
    global _registry
    _registry = registry or MetricsRegistry()
    return _registry
