"""Scrape endpoint — stdlib HTTP exposition of the telemetry plane
(ISSUE 14 tentpole).

BigDL 2.0 Cluster Serving exposes its serving tier to a Prometheus
scraper (arXiv 2204.01715); `ScrapeServer` is that surface for this
stack, stdlib-only (http.server on one daemon thread):

    /metrics   the registry's Prometheus text exposition (the same
               `render_prometheus()` bytes the drills pin)
    /health    JSON ops view: scrape counter, sampler freshness
               (obs/timeseries.py), per-objective compliance and
               alert states (obs/slo.py)
    /alerts    JSON alert states only

Knobs are CONSTRUCTOR args, never env (graftlint trace-env-read):
`registry` (default: the active one per request), `sampler`,
`alert_engine`, `host`, `port` (0 → ephemeral; `start()` returns the
bound port).

Threading contract (lock-discipline): requests are answered on the
server's daemon thread while the owning loop keeps ticking the
sampler/alert engine — every piece of shared mutable state is locked
on BOTH sides (the scrape counter under this server's lock; the
sampler ring and alert states under their own locks inside their
accessors). The handler never touches JAX state: everything served is
an already-fetched host value (hidden-device-sync holds trivially),
and scraping never emits telemetry of its own — observing the plane
must not change it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from bigdl_tpu.obs.registry import MetricsRegistry, get_registry

__all__ = ["ScrapeServer"]


class ScrapeServer:
    """One-process scrape endpoint over registry + sampler + alerts.

    >>> srv = ScrapeServer(sampler=sampler, alert_engine=aeng)
    >>> port = srv.start()          # daemon thread; 0 → ephemeral
    >>> # curl http://127.0.0.1:<port>/metrics | /health | /alerts
    >>> srv.close()
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sampler=None, alert_engine=None,
                 host: str = "127.0.0.1", port: int = 0):
        self._registry = registry
        self.sampler = sampler
        self.alert_engine = alert_engine
        self.host = host
        self._port = port
        self._srv: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._scrapes = 0

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    @property
    def port(self) -> int:
        return self._srv.server_address[1] if self._srv is not None \
            else self._port

    # ------------------------------------------------------------ wiring
    def start(self) -> int:
        """Bind, start the daemon serving thread, return the port."""
        if self._srv is not None:
            return self.port
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                # quiet: BaseHTTPRequestHandler logs every request to
                # stderr by default — core code owns no stdio
                pass

            def do_GET(self):
                try:
                    body, ctype, code = outer._respond(self.path)
                except Exception as e:  # the endpoint must never die
                    body = json.dumps({"error": repr(e)},
                                      sort_keys=True).encode()
                    ctype, code = "application/json", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer((self.host, self._port),
                                        _Handler)
        self._thread = threading.Thread(target=self._serve,
                                        name="bigdl-obs-scrape",
                                        daemon=True)
        self._thread.start()
        return self.port

    def _serve(self) -> None:
        self._srv.serve_forever(poll_interval=0.1)

    def close(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- views
    def _respond(self, path: str) -> Tuple[bytes, str, int]:
        with self._lock:
            self._scrapes += 1
        route = path.split("?", 1)[0].rstrip("/") or "/"
        if route == "/metrics":
            return (self.registry.render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8", 200)
        if route == "/alerts":
            return (json.dumps(self.alerts_view(),
                               sort_keys=True).encode(),
                    "application/json", 200)
        if route in ("/", "/health", "/healthz"):
            return (json.dumps(self.health_view(),
                               sort_keys=True).encode(),
                    "application/json", 200)
        return (json.dumps({"error": f"no route {route!r}",
                            "routes": ["/metrics", "/health",
                                       "/alerts"]},
                           sort_keys=True).encode(),
                "application/json", 404)

    def alerts_view(self) -> dict:
        if self.alert_engine is None:
            return {"alerts": [], "firing": []}
        return {"alerts": self.alert_engine.alerts(),
                "firing": self.alert_engine.firing()}

    def health_view(self) -> dict:
        """The JSON ops rollup: scrape count, sampler freshness,
        objective compliance, alert states."""
        with self._lock:
            n = self._scrapes
        out: dict = {"schema": 1, "scrapes": n}
        if self.sampler is not None:
            latest = self.sampler.latest()
            out["sampler"] = {
                "samples": len(self.sampler),
                "interval_s": self.sampler.interval_s,
                "last_sample_t": latest["t"] if latest else None,
            }
        if self.alert_engine is not None:
            out.update(self.alerts_view())
            out["objectives"] = self.alert_engine.compliance()
        return out
