"""Declarative SLOs + deterministic alerting over the time-series
plane (ISSUE 14 tentpole).

BigDL 2.0's Cluster Serving ships an ops loop around its serving tier
(arXiv 2204.01715); the SoCC '19 paper's driver-side monitoring is the
training-plane analogue (arXiv 1804.05839 §4). This module closes the
same loop over OUR telemetry: an `SLOObjective` says what "healthy"
means (windowed p99 under a target, bad-terminal fraction inside an
error budget), an `AlertRule` says when to page (threshold with a
pending duration, multi-window burn rate, absence), and `AlertEngine`
walks the rule state machines once per scheduling round.

Determinism contract (graftlint's nondeterministic-drill scope covers
this module): every evaluation is a PURE FUNCTION of (the sampler's
window contents, the injected clock) — no wall-clock reads, no RNG.
Two replays of the same traffic under the same virtual clock produce
byte-identical alert transitions, which is what lets the slo_alert
drill (scripts/fault_drill.py) pin firing AND resolution bit-for-bit,
bundle bytes included.

State machine per rule::

    inactive --breach--> pending --for_s held--> firing
        ^                   |                       |
        |<---heals----------+        heals >= clear_s (flap
        |<--------------------------- suppression: any re-breach
                                      resets the healthy streak)

Transitions emit `alert_firing` / `alert_resolved` events (kinds +
required fields registered in obs/events.py::EVENT_KINDS — the
event-kind-contract gate), and `alert_firing` is a FlightRecorder
trigger: an SLO burn dumps a post-mortem bundle whose trigger record
names the window that breached (obs/flightrecorder.py, slo_burn
bundles).

The Autoscaler consumes the same `SLOObjective` (serving/autoscaler.py
`objective=`): at max_engines its shed-mode decision asks the
objective, not its own threshold math — one definition of "missing the
SLO" across scaling and alerting.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from bigdl_tpu.obs.timeseries import MetricsSampler

__all__ = ["BAD_STATUSES", "SLOObjective", "AlertRule", "AlertEngine"]

# the serving plane's bad terminal statuses (engine.py's terminal set
# minus 'done') — the default error-budget numerator
BAD_STATUSES: Tuple[str, ...] = ("shed", "expired", "poisoned",
                                 "failed")

_OBJECTIVE_KINDS = ("latency_quantile", "error_budget")
_RULE_KINDS = ("threshold", "burn_rate", "absence")


def _obs():
    """Call-time import (obs/__init__ imports this module — a
    top-level import would cycle)."""
    from bigdl_tpu import obs

    return obs


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 9)


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One declarative service-level objective.

    kind='latency_quantile': the `q`-quantile of the `metric`
    histogram series (`labels` selects it exactly) over the evaluation
    window must stay <= `target` seconds.

    kind='error_budget': of the `metric` counter family's increments
    over the window (optionally filtered to series whose labels
    contain `labels`), the fraction whose `bad_label` value is in
    `bad_values` must stay <= `target` — the goodput-error-budget
    form: `--slo-goodput 0.95` becomes target 0.05.

    `measure()` returns None with no data in the window (no
    completions, series not born yet) — "no data" is not a violation;
    the absence AlertRule exists for silence-is-an-incident cases."""

    name: str
    kind: str
    metric: str
    target: float
    q: float = 0.99
    labels: Optional[Mapping[str, str]] = None
    bad_label: str = "status"
    bad_values: Tuple[str, ...] = BAD_STATUSES

    def __post_init__(self):
        if self.kind not in _OBJECTIVE_KINDS:
            raise ValueError(f"objective kind {self.kind!r}: expected "
                             f"one of {_OBJECTIVE_KINDS}")
        if self.target < 0:
            raise ValueError("target must be >= 0")
        if not 0.0 < self.q <= 1.0:
            raise ValueError("q must be in (0, 1]")

    # --------------------------------------------------------- evaluation
    def measure(self, sampler: MetricsSampler,
                window_s: Optional[float] = None) -> Optional[float]:
        """The objective's current value over `window_s` (None: no
        data)."""
        if self.kind == "latency_quantile":
            return sampler.window_quantile(
                self.metric, self.q,
                labels=dict(self.labels) if self.labels else None,
                window_s=window_s)
        want = {k: str(v) for k, v in (self.labels or {}).items()}
        total = bad = 0.0
        for labels, d in sampler.series_deltas(self.metric,
                                               window_s=window_s):
            if any(labels.get(k) != v for k, v in want.items()):
                continue
            total += d
            if labels.get(self.bad_label) in self.bad_values:
                bad += d
        if total <= 0:
            return None
        return bad / total

    def violated(self, value: Optional[float]) -> bool:
        """Whether a measured value misses the objective (None — no
        data — never violates)."""
        return value is not None and value > self.target

    def evaluate(self, sampler: MetricsSampler,
                 window_s: Optional[float] = None) -> dict:
        """Compliance record: measured value vs target over the
        window (deterministic dict — report surfaces embed it)."""
        v = self.measure(sampler, window_s)
        return {"objective": self.name, "kind": self.kind,
                "metric": self.metric, "value": _round(v),
                "target": self.target, "ok": not self.violated(v),
                "window_s": window_s}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """When an objective's breach becomes a page.

    kind='threshold': objective violated over `window_s` continuously
    for `for_s` (pending duration) → firing.

    kind='burn_rate': the classic multi-window form — the objective's
    value exceeds `burn_factor * target` on BOTH `long_window_s` (the
    page is real) and `short_window_s` (it is STILL happening) →
    firing immediately (`for_s` is implicit in the long window).

    kind='absence': the `metric` family saw ZERO increments over
    `window_s` while the sampler has data → firing after `for_s` —
    the emitter died, which no value-threshold can see.

    `clear_s` is flap suppression on the way out: a firing rule must
    measure healthy for `clear_s` CONTINUOUSLY before it resolves;
    any re-breach resets the streak."""

    name: str
    objective: SLOObjective
    kind: str = "threshold"
    window_s: Optional[float] = None
    for_s: float = 0.0
    clear_s: float = 0.0
    long_window_s: float = 60.0
    short_window_s: float = 5.0
    burn_factor: float = 1.0

    def __post_init__(self):
        if self.kind not in _RULE_KINDS:
            raise ValueError(f"alert kind {self.kind!r}: expected one "
                             f"of {_RULE_KINDS}")
        if self.kind == "burn_rate" \
                and self.short_window_s > self.long_window_s:
            raise ValueError("burn_rate needs short_window_s <= "
                             "long_window_s")
        if self.for_s < 0 or self.clear_s < 0:
            raise ValueError("for_s/clear_s must be >= 0")

    @property
    def breach_window_s(self) -> Optional[float]:
        """The window a firing record names (the long window for burn
        rate — the one that makes the page real)."""
        return self.long_window_s if self.kind == "burn_rate" \
            else self.window_s


class AlertEngine:
    """Walk every rule's state machine once per `evaluate()` call.

    >>> eng = AlertEngine(sampler, [rule])     # clock: sampler's
    >>> while serving:
    ...     router.step(); sampler.tick(); eng.evaluate()

    Knobs are constructor args, never env: `sampler`, `rules`,
    `plane` (stamped on the alert events), `clock` (defaults to the
    sampler's injected clock so one virtual cell drives sampling and
    transitions). State is lock-guarded because the scrape endpoint
    (obs/exposition.py) serves `alerts()` from its own thread."""

    def __init__(self, sampler: MetricsSampler,
                 rules: List[AlertRule], *, plane: str = "serving",
                 clock: Optional[Callable[[], float]] = None):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names: {names}")
        self._sampler = sampler
        self._clock = clock or sampler.clock
        self.plane = plane
        self.rules = list(rules)
        self._st: Dict[str, dict] = {
            r.name: {"state": "inactive", "since": None,
                     "healthy_since": None, "fired_at": None,
                     "value": None}
            for r in rules}
        self._lock = threading.Lock()
        self.fired = 0
        self.resolved = 0

    # ----------------------------------------------------------- signals
    def _breach(self, rule: AlertRule
                ) -> Tuple[bool, Optional[float], dict]:
        """(breached, reported value, extra event fields) for one rule
        — a pure read of the sampler's windows."""
        obj = rule.objective
        if rule.kind == "burn_rate":
            lv = obj.measure(self._sampler, rule.long_window_s)
            sv = obj.measure(self._sampler, rule.short_window_s)
            thr = rule.burn_factor * obj.target
            breached = (lv is not None and sv is not None
                        and lv > thr and sv > thr)
            extra = {"long_value": _round(lv), "short_value": _round(sv)}
            if lv is not None and obj.target > 0:
                extra["burn"] = _round(lv / obj.target)
            return breached, _round(sv), extra
        if rule.kind == "absence":
            total = sum(d for _, d in self._sampler.series_deltas(
                obj.metric, window_s=rule.window_s))
            has_window = self._sampler.span(rule.window_s) is not None
            return (has_window and total <= 0), _round(total), {}
        v = obj.measure(self._sampler, rule.window_s)
        return obj.violated(v), _round(v), {}

    # ---------------------------------------------------------- evaluate
    def evaluate(self) -> List[dict]:
        """One evaluation round: read every rule's windows, advance
        its state machine, emit firing/resolution events. Returns one
        record per rule ({alert, state, value, ...}).

        Transitions are collected under the lock but EMITTED after it
        releases: emit_event runs listeners synchronously (the flight
        recorder dumps a whole bundle, calling registered health
        sources) — doing that inside this non-reentrant lock would
        block the scrape thread mid-incident and self-deadlock any
        health source that reads alerts()."""
        now = self._clock()
        out = []
        emissions: List[Tuple[str, dict]] = []
        with self._lock:
            for rule in self.rules:
                breached, value, extra = self._breach(rule)
                st = self._st[rule.name]
                st["value"] = value
                if st["state"] == "inactive":
                    if breached:
                        st["since"] = now
                        if rule.for_s <= 0:
                            emissions.append(self._fire(
                                rule, st, now, value, extra))
                        else:
                            st["state"] = "pending"
                elif st["state"] == "pending":
                    if not breached:
                        st["state"] = "inactive"
                        st["since"] = None
                    elif now - st["since"] >= rule.for_s - 1e-9:
                        extra = dict(extra)
                        extra["pending_s"] = _round(now - st["since"])
                        emissions.append(self._fire(
                            rule, st, now, value, extra))
                elif st["state"] == "firing":
                    if breached:
                        # flap suppression: the healthy streak resets
                        st["healthy_since"] = None
                    else:
                        if st["healthy_since"] is None:
                            st["healthy_since"] = now
                        if now - st["healthy_since"] \
                                >= rule.clear_s - 1e-9:
                            emissions.append(self._resolve(
                                rule, st, now, value))
                out.append({"alert": rule.name,
                            "objective": rule.objective.name,
                            "state": st["state"], "value": value,
                            **extra})
        obs = _obs()
        for kind, fields in emissions:
            obs.emit_event(kind, **fields)
        return out

    def _fire(self, rule: AlertRule, st: dict, now: float,
              value: Optional[float],
              extra: dict) -> Tuple[str, dict]:
        """Apply the firing transition (caller holds the lock) and
        return the event to emit once it releases."""
        st["state"] = "firing"
        st["fired_at"] = now
        st["healthy_since"] = None
        self.fired += 1
        return ("alert_firing", dict(
            plane=self.plane, alert=rule.name,
            objective=rule.objective.name, value=value,
            target=rule.objective.target,
            window_s=rule.breach_window_s, rule_kind=rule.kind,
            **extra))

    def _resolve(self, rule: AlertRule, st: dict, now: float,
                 value: Optional[float]) -> Tuple[str, dict]:
        """Apply the resolution transition (caller holds the lock) and
        return the event to emit once it releases."""
        firing_s = now - st["fired_at"] if st["fired_at"] is not None \
            else None
        st["state"] = "inactive"
        st["since"] = None
        st["healthy_since"] = None
        st["fired_at"] = None
        self.resolved += 1
        return ("alert_resolved", dict(
            plane=self.plane, alert=rule.name,
            objective=rule.objective.name, value=value,
            target=rule.objective.target, firing_s=_round(firing_s),
            rule_kind=rule.kind, window_s=rule.breach_window_s))

    # -------------------------------------------------------------- views
    def alerts(self) -> List[dict]:
        """Current state per rule (deterministic order: rule order) —
        the scrape endpoint's /alerts payload."""
        with self._lock:
            return [{"alert": r.name, "objective": r.objective.name,
                     "kind": r.kind, "state": self._st[r.name]["state"],
                     "value": self._st[r.name]["value"],
                     "target": r.objective.target,
                     "fired_at": self._st[r.name]["fired_at"]}
                    for r in self.rules]

    def firing(self) -> List[str]:
        with self._lock:
            return [r.name for r in self.rules
                    if self._st[r.name]["state"] == "firing"]

    def compliance(self, window_s: Optional[float] = None
                   ) -> List[dict]:
        """Per-objective compliance over `window_s` (each distinct
        objective once, rule order)."""
        seen, out = set(), []
        for r in self.rules:
            if r.objective.name in seen:
                continue
            seen.add(r.objective.name)
            out.append(r.objective.evaluate(self._sampler, window_s))
        return out
