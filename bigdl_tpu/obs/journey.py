"""Request-journey reconstruction — one cross-engine timeline per
request (ISSUE 11 tentpole).

The fleet moves a request between engines (rebalance, failover,
disaggregated-prefill handoff — PRs 7/10) but PR 5's telemetry
observes per-process: each event names ONE engine, and nothing ties a
request's hops together. This module closes that gap on the READ side
of a host-side trace context:

* every `Request` is stamped with a `trace_id` + `hop` counter at
  admission (router or engine — serving/router.py / engine.py), and
  the hop increments each time the request MOVES: failover
  resubmission, rebalance (`steal_queued` → receiver submit), and
  disaggregated-prefill `import_handoff`;
* every request-lifecycle event (`request_submit`, `prefix_hit`,
  `handoff_export`, `handoff_import`, `router_handoff`,
  `router_failover`, `request_terminal`, ...) carries `trace` + `hop`,
  and the seat-point events also carry the engine's `tp` and `role`;
* `build_journeys` folds a JSONL event list back into one journey per
  trace: an ordered hop table (engine / tp / role / seat kind / dwell
  time per hop), the terminal outcome, and integrity flags (`lost_hops`
  — a hop index that never seated; `superseded_terminals` — the
  transitional 'failed' records a failover replaced).

Everything here is pure host-side post-processing over already-emitted
dicts: zero device syncs, zero compiles, and bit-deterministic for a
fixed event list (the graftlint hidden-device-sync + telemetry-bypass
scopes cover this module like the rest of `bigdl_tpu/obs/`).

Export: `to_perfetto` renders one track per request (thread-name
metadata + one complete "X" span per hop), loadable in
chrome://tracing / ui.perfetto.dev next to the span tracer's doc:

    python scripts/obs_report.py /tmp/run.jsonl --perfetto /tmp/j.json
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from bigdl_tpu.obs.events import seat_kinds

__all__ = ["SEAT_KINDS", "build_journeys", "summarize_journeys",
           "journeys_json", "to_perfetto"]

# the event kinds that SEAT a request on an engine — each opens a hop
# (request_submit covers initial dispatch, failover resubmission and
# rebalance moves; handoff_import seats a disaggregated-prefill
# package on its decode engine). Derived from the machine-readable
# EVENT_KINDS registry (obs/events.py, ISSUE 13) — the `seat` flag
# there is the single source of truth, not a hand-maintained list.
SEAT_KINDS = seat_kinds()

def _new_hop(hop: int) -> dict:
    return {"hop": hop, "engine": None, "tp": None, "role": None,
            "via": None, "t_start": None, "dwell_s": None,
            "events": {}}


def build_journeys(events: List[dict]) -> List[dict]:
    """Fold an event list (oldest first — `EventLog.events()` order or
    a `read_jsonl` file) into one journey dict per trace id, sorted by
    trace id. Events without a `trace` field are ignored.

    Journey shape::

        {"trace": str, "request": id, "hops": [hop...],
         "status"/"reason"/"tokens"/"ttft_s"/"latency_s": <terminal>,
         "t_submit": first seat ts, "t_terminal": terminal ts,
         "engines": [engine per hop], "layouts": [tp per hop],
         "cross_engine": bool, "cross_layout": bool,
         "lost_hops": [missing hop indexes],
         "rejected_attempts": int, "complete": bool,
         "superseded_terminals": int}

    Each hop: engine / tp / role from its seat event, `via`
    ("request_submit" | "handoff_import"), `t_start`, `dwell_s` (seat →
    next seat, or seat → terminal on the last hop — the cross-engine
    latency attribution), and an `events` tally of every other event
    kind that landed on it. A terminal that is FOLLOWED by a later
    seat (the failover's transitional 'failed') is counted superseded,
    exactly mirroring the router's settlement semantics; a hop with a
    terminal but no seat (a request shed/expired at admission) is
    terminal-only, NOT lost."""
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        t = e.get("trace")
        if t is not None:
            by_trace.setdefault(t, []).append(e)
    out = []
    for trace in sorted(by_trace):
        evs = by_trace[trace]
        hops: Dict[int, dict] = {}
        terminal: Optional[dict] = None
        superseded = 0
        request_id = None
        for e in evs:
            kind = e.get("kind")
            hop = int(e.get("hop", 0))
            request_id = e.get("request", request_id)
            rec = hops.get(hop)
            if kind in SEAT_KINDS:
                if terminal is not None:
                    # a seat after a terminal: the terminal was the
                    # transitional 'failed' of a failover — superseded
                    superseded += 1
                    terminal = None
                if rec is None:
                    rec = hops[hop] = _new_hop(hop)
                if rec["via"] is None:
                    rec.update(engine=e.get("engine"), tp=e.get("tp"),
                               role=e.get("role"), via=kind,
                               t_start=e.get("ts"))
                else:
                    # double-seat on one hop index (spillover retries
                    # keep hop 0): keep the first seat, tally the rest
                    rec["events"]["reseat"] = \
                        rec["events"].get("reseat", 0) + 1
            else:
                if rec is None:
                    rec = hops[hop] = _new_hop(hop)
                rec["events"][kind] = rec["events"].get(kind, 0) + 1
                if kind == "request_terminal":
                    terminal = e
        # a hop record holding ONLY rejected-attempt records is a move
        # that bounced off a full queue before any seat (the router
        # pre-increments the hop, the target's _overload emits
        # request_rejected, the router undoes the increment and the
        # request settles elsewhere) — an ATTEMPT, not a hop the
        # request ever made: tally it, never report it lost
        rejected_attempts = 0
        for h in [h for h, r in hops.items()
                  if r["via"] is None
                  and set(r["events"]) == {"request_rejected"}]:
            rejected_attempts += hops[h]["events"]["request_rejected"]
            del hops[h]
        ordered = [hops[h] for h in sorted(hops)]
        for i, rec in enumerate(ordered):
            t0 = rec["t_start"]
            if t0 is None:
                continue
            if i + 1 < len(ordered) and ordered[i + 1]["t_start"] \
                    is not None:
                t1 = ordered[i + 1]["t_start"]
            elif terminal is not None:
                t1 = terminal.get("ts")
            else:
                t1 = None
            if t1 is not None:
                rec["dwell_s"] = round(max(t1 - t0, 0.0), 9)
        max_hop = max(hops) if hops else -1
        # a hop is LOST only if nothing seated it AND nothing settled
        # it: a request shed/expired at admission (the fleet's
        # shed-on-arrival path) yields a legitimate TERMINAL-ONLY hop
        # — the journey is complete, just never seated there
        lost = [h for h in range(max_hop + 1)
                if h not in hops
                or (hops[h]["via"] is None
                    and "request_terminal" not in hops[h]["events"])]
        engines = [r["engine"] for r in ordered]
        layouts = [r["tp"] for r in ordered]
        seated_engines = {e for e in engines if e is not None}
        seated_layouts = {t for t in layouts if t is not None}
        j = {
            "trace": trace,
            "request": request_id,
            "hops": ordered,
            "engines": engines,
            "layouts": layouts,
            "cross_engine": len(seated_engines) > 1,
            "cross_layout": len(seated_layouts) > 1,
            "lost_hops": lost,
            "rejected_attempts": rejected_attempts,
            "superseded_terminals": superseded,
            "complete": terminal is not None and not lost,
            "t_submit": ordered[0]["t_start"] if ordered else None,
            "t_terminal": terminal.get("ts") if terminal else None,
            "status": terminal.get("status") if terminal else None,
            "reason": terminal.get("reason") if terminal else None,
            "tokens": terminal.get("tokens") if terminal else None,
            "ttft_s": terminal.get("ttft_s") if terminal else None,
            "latency_s": terminal.get("latency_s") if terminal else None,
        }
        out.append(j)
    return out


def summarize_journeys(journeys: List[dict]) -> dict:
    """Compact rollup for reports (obs_report / loadgen): counts only,
    deterministic for a fixed journey list."""
    return {
        "count": len(journeys),
        "complete": sum(1 for j in journeys if j["complete"]),
        "cross_engine": sum(1 for j in journeys if j["cross_engine"]),
        "cross_layout": sum(1 for j in journeys if j["cross_layout"]),
        "max_hops": max((len(j["hops"]) for j in journeys), default=0),
        "lost_hops": sum(len(j["lost_hops"]) for j in journeys),
        "superseded_terminals": sum(j["superseded_terminals"]
                                    for j in journeys),
    }


def journeys_json(journeys: List[dict]) -> str:
    """Canonical JSON rendering (sorted keys) — the byte-identity
    surface the drills compare across runs."""
    return json.dumps(journeys, sort_keys=True)


def to_perfetto(journeys: List[dict]) -> dict:
    """Chrome-trace document with ONE track per request: a thread-name
    metadata record per journey plus one complete "X" span per hop
    (span args carry engine/tp/role/events), and an instant "i" marker
    at the terminal. Merges cleanly with SpanTracer.to_chrome_trace()
    output when both use the same clock."""
    evs: List[dict] = []
    for tid, j in enumerate(journeys):
        label = f"{j['trace']}"
        if j["status"] is not None:
            label += f" [{j['status']}]"
        evs.append({"ph": "M", "name": "thread_name", "pid": 1,
                    "tid": tid, "args": {"name": label}})
        for rec in j["hops"]:
            if rec["t_start"] is None:
                continue
            name = f"hop{rec['hop']} {rec['engine'] or '?'}"
            if rec["tp"] is not None:
                name += f" tp={rec['tp']}"
            evs.append({
                "name": name, "cat": "journey", "ph": "X",
                "ts": rec["t_start"] * 1e6,
                "dur": max(rec["dwell_s"] or 0.0, 0.0) * 1e6,
                "pid": 1, "tid": tid,
                "args": {"engine": rec["engine"], "tp": rec["tp"],
                         "role": rec["role"], "via": rec["via"],
                         "events": rec["events"]}})
        if j["t_terminal"] is not None:
            evs.append({"name": f"terminal[{j['status']}]",
                        "cat": "journey", "ph": "i", "s": "t",
                        "ts": j["t_terminal"] * 1e6, "pid": 1,
                        "tid": tid,
                        "args": {"reason": j["reason"],
                                 "tokens": j["tokens"]}})
    return {"traceEvents": evs, "displayTimeUnit": "ms"}
