"""One emission path for per-step training telemetry.

Before ISSUE 5, each training loop wrote the same numbers three ways:
`TrainSummary.add_scalar` (Loss/Throughput/LearningRate, duplicated in
LocalOptimizer._emit and DistriOptimizer.run), `optim.Metrics`
stopwatches rendered into the log line, and the log line itself —
three bookkeeping paths, no shared schema. `StepTelemetry` is now the
single path: the loops hand it one already-fetched step record and it
fans out to (1) the metrics registry, (2) the structured event log,
(3) the TrainSummary sink if configured, (4) the human log line.

Sync discipline: callers pass HOST floats they already fetched (the
loops fetch loss one step late so the fetch overlaps device compute —
see LocalOptimizer._emit); this module never touches a device array.
"""

from __future__ import annotations

import logging
from typing import Optional

from bigdl_tpu import obs

__all__ = ["StepTelemetry"]

logger = logging.getLogger("bigdl_tpu.optim")


class StepTelemetry:
    """Per-run fan-out for step records.

    `summary` — an optional TrainSummary-like sink (anything with
    `add_scalar(tag, value, step)`); the registry/event emission does
    not depend on it. `plane` labels the registry series so a process
    hosting several runs stays legible."""

    def __init__(self, summary=None, log_every: int = 1,
                 plane: str = "training"):
        self.summary = summary
        self.log_every = max(int(log_every), 1)
        self.plane = plane
        reg = obs.get_registry()
        self._steps = reg.counter(
            "training_steps_total", "optimizer steps observed")
        self._updates = reg.counter(
            "training_updates_applied_total",
            "optimizer updates actually applied (guard-discarded "
            "steps excluded)")
        self._records = reg.counter(
            "training_records_total", "training records consumed")
        self._loss = reg.gauge("training_loss", "last step loss")
        self._lr = reg.gauge("training_learning_rate",
                             "last step learning rate")
        self._thr = reg.gauge("training_throughput_records_per_sec",
                              "last step throughput")

    def emit_step(self, *, epoch: int, step: int,
                  loss: Optional[float], lr: float, throughput: float,
                  records: int, update_applied: bool = True,
                  gnorm: Optional[float] = None,
                  hists=None, metrics_summary: str = "") -> None:
        """`loss`/`gnorm` must already be host floats (no device
        fetches here) — and `loss` may be None: on a step where
        nothing else fenced the loss (no summary sink, not a log
        step), the loops do NOT fetch it just for telemetry (the
        piggyback-on-existing-fetches contract), so the event carries
        every host-side field and omits `loss`. `hists` is
        pre-materialized (name, ndarray) pairs for the TrainSummary
        parameter-histogram trigger."""
        if obs.enabled():
            self._steps.inc()
            self._records.inc(records)
            if update_applied:
                self._updates.inc()
            if loss is not None:
                self._loss.set(loss)
            self._lr.set(lr)
            self._thr.set(throughput)
            fields = {"plane": self.plane, "epoch": epoch, "step": step,
                      "lr": float(lr),
                      "throughput": round(float(throughput), 3),
                      "update_applied": bool(update_applied)}
            if loss is not None:
                fields["loss"] = float(loss)
            if gnorm is not None:
                fields["gnorm"] = float(gnorm)
            obs.emit_event("train_step", **fields)
        if self.summary is not None and loss is not None:
            self.summary.add_scalar("Loss", float(loss), step)
            self.summary.add_scalar("Throughput", throughput, step)
            self.summary.add_scalar("LearningRate", lr, step)
            for name, data in (hists or ()):
                self.summary.add_histogram(name, data, step)
        if step % self.log_every == 0 and loss is not None:
            logger.info(
                "epoch %d iter %d loss %.6f lr %.5g %.1f rec/s [%s]",
                epoch, step, float(loss), lr, throughput,
                metrics_summary)
