"""Structured JSONL event log — one schema-versioned record per
step / request / anomaly / checkpoint / fault-injection / degradation.

Replaces the ad-hoc prints that previously carried this information
(fault_drill stdout JSON, logger lines): a drill or a bench can now
assert on (and a later session can reconstruct) what a run DID from
machine-readable records instead of scraping text.

Record shape (every record)::

    {"schema": 1, "ts": <clock seconds>, "seq": <monotonic int>,
     "kind": "<event kind>", ...kind-specific fields}

Kinds in use across the codebase (the schema is open — new kinds are
fine; these are the wired ones):

    train_step          per optimizer step: step, epoch, loss, lr,
                        throughput, and (guard armed) gnorm/guard
    anomaly             guard observation: step, action, gnorm
    checkpoint_save / checkpoint_load / checkpoint_corrupt_skipped
                        checkpoint_save carries async/duration_s/
                        nshards (+ shard on per-unit records of a
                        sharded save — the whole-checkpoint publish
                        record is the one WITHOUT a shard field);
                        checkpoint_load carries sharded/nshards for
                        sharded dirs (ISSUE 9; obs_report's checkpoint
                        section digests these)
    fault_injected      every utils/faults shot that fires: fault, step
    request_submit / request_terminal   serving lifecycle endpoints
    engine_degraded     watchdog trip / retry exhaustion
    prefix_hit          paged-KV prefix reuse at admission: request,
                        matched_tokens, blocks (ISSUE 8)
    prefix_evict        LRU prefix blocks evicted under pool
                        pressure: blocks
    handoff_export / handoff_import / router_handoff
                        disaggregated prefill (ISSUE 10): a prefill-
                        role engine detaches a prefilled request
                        (request, prompt_len, blocks), a serving
                        engine seats it (+ source), and the router
                        records the move (source, target)
    metrics_snapshot    a full registry snapshot embedded as an event
                        (obs.log_metrics_snapshot) — gives a JSONL file
                        self-contained percentiles for obs_report
    preempted           a worker preemption propagating out of a
                        training loop (ISSUE 11): step — emitted on the
                        re-raise path (optim/optimizer.py,
                        parallel/distri_optimizer.py), a flight-
                        recorder trigger
    incident_dump       the flight recorder wrote a post-mortem bundle
                        (ISSUE 11): incident, bundle, component,
                        trigger_kind, events_in_tail
                        (obs/flightrecorder.py; obs_report's
                        "incidents" section digests these)

Request-journey tracing (ISSUE 11): every request-lifecycle event
above (request_submit / request_terminal / prefix_hit / handoff_* /
router_*) additionally carries `trace` (the host-side trace id stamped
on the Request at admission) and `hop` (how many times the request has
moved between engines — failover, rebalance, handoff import), and the
seat-point events (request_submit, handoff_import) carry the engine's
`tp` + `role`; `obs/journey.py` folds a JSONL file back into one
cross-engine timeline per request.

The log is ring-buffered in memory (default 4096 records) with an
optional JSONL file sink; both the clock and the buffer are injectable
so fault drills assert on bit-reproducible records. Listeners
(`add_listener`) observe every record synchronously AFTER it lands in
the ring — the flight recorder's subscription point; a process with no
listener installed pays one empty-list check per emit.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from typing import Dict, IO, Iterable, List, Optional

__all__ = ["SCHEMA_VERSION", "EventLog", "get_event_log",
           "set_event_log", "read_jsonl"]

SCHEMA_VERSION = 1


class EventLog:
    """In-memory ring buffer of event dicts + optional JSONL sink.

    `clock` is injectable (drills pass a fake); `path` opens an append
    sink whose lines are flushed per record (events must survive the
    crash legs — a torn final line is tolerated by `read_jsonl`)."""

    def __init__(self, capacity: int = 4096,
                 path: Optional[str] = None, clock=None):
        import time as _time

        self._clock = clock or _time.time
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()
        self._sink: Optional[IO[str]] = None
        self._listeners: List = []
        self.path = path
        if path:
            self._sink = open(path, "a")

    # ------------------------------------------------------------- emit
    def emit(self, kind: str, **fields) -> dict:
        with self._lock:
            rec = {"schema": SCHEMA_VERSION, "ts": self._clock(),
                   "seq": self._seq, "kind": kind, **fields}
            self._seq += 1
            self._ring.append(rec)
            if self._sink is not None:
                self._sink.write(json.dumps(rec, sort_keys=True,
                                            default=_jsonable) + "\n")
                self._sink.flush()
        # outside the lock: a listener (the flight recorder) may emit
        # its own record (incident_dump) re-entrantly
        for fn in list(self._listeners):
            try:
                fn(rec)
            except Exception:
                logging.getLogger("bigdl_tpu.obs").exception(
                    "event listener failed")
        return rec

    # -------------------------------------------------------- listeners
    def add_listener(self, fn) -> None:
        """Subscribe `fn(record)` to every emitted record (called
        synchronously, after the ring append, outside the lock). The
        flight recorder's hook; listeners must never emit
        unconditionally (re-entrancy is bounded, not infinite)."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # ------------------------------------------------------------ query
    def events(self, kind: Optional[str] = None,
               **match) -> List[dict]:
        """Records (oldest first), optionally filtered by kind and by
        exact field values (`events("request_terminal",
        status="poisoned")`)."""
        out = []
        for rec in self._ring:
            if kind is not None and rec["kind"] != kind:
                continue
            if any(rec.get(k) != v for k, v in match.items()):
                continue
            out.append(rec)
        return out

    def counts_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self._ring:
            out[rec["kind"]] = out.get(rec["kind"], 0) + 1
        return dict(sorted(out.items()))

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


def _jsonable(o):
    """Sink fallback for numpy scalars etc. — never let a telemetry
    write throw out of a training/serving loop, and NEVER fetch a
    device array: emission consumes already-fetched host values (the
    obs contract), so a jax.Array reaching the sink is a caller bug —
    it is repr'd, not synced (a silent `.item()` here would stall the
    decode loop once per event through the axon tunnel)."""
    import numpy as np

    if isinstance(o, np.generic) or (isinstance(o, np.ndarray)
                                     and o.ndim == 0):
        # host-memory numpy scalar: .item() is a pure host conversion
        return o.item()  # graftlint: disable=hidden-device-sync
    return repr(o)


def read_jsonl(path: str) -> List[dict]:
    """Parse a JSONL event file; a torn final line (crash mid-write)
    is dropped, not an error."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail
    return out


# BIGDL_OBS_EVENTS=<path> attaches a JSONL file sink to the default
# log at import — `BIGDL_OBS_EVENTS=/tmp/run.jsonl python train.py`
# then `python scripts/obs_report.py /tmp/run.jsonl`
import os as _os

_log = EventLog(path=_os.environ.get("BIGDL_OBS_EVENTS") or None)


def get_event_log() -> EventLog:
    return _log


def set_event_log(log: Optional[EventLog]) -> EventLog:
    """Install an event log (None → fresh default); returns the active
    one. (Explicit None check: an EMPTY EventLog is falsy via
    __len__.) A fresh default re-attaches the BIGDL_OBS_EVENTS file
    sink if the env var is set — resets must not silently drop the
    operator's JSONL sink (append mode, so prior records survive)."""
    global _log
    if log is None:
        log = EventLog(path=_os.environ.get("BIGDL_OBS_EVENTS") or None)
    if log is not _log:
        _log.close()   # don't leak the replaced log's file handle;
        _log = log     # its in-memory ring stays readable
    return _log
