"""Structured JSONL event log — one schema-versioned record per
step / request / anomaly / checkpoint / fault-injection / degradation.

Replaces the ad-hoc prints that previously carried this information
(fault_drill stdout JSON, logger lines): a drill or a bench can now
assert on (and a later session can reconstruct) what a run DID from
machine-readable records instead of scraping text.

Record shape (every record)::

    {"schema": 1, "ts": <clock seconds>, "seq": <monotonic int>,
     "kind": "<event kind>", ...kind-specific fields}

The kinds in use across the codebase live in the machine-readable
`EVENT_KINDS` registry below (ISSUE 13) — kind → required/optional
fields + a one-line doc. It is THE single source of truth: the journey
builder derives its seat/lifecycle sets from it, `obs_report` flags
kinds outside it, `validate_record` checks a parsed record against it,
and graftlint's `event-kind-contract` rule statically pins every
`emit_event` call site and kind-literal consumer to it. Emitting an
unregistered kind still WORKS at runtime (the schema stays open for
experiments) — but committing one fails the lint gate until it is
registered here.

Request-journey tracing (ISSUE 11): the kinds marked `journey` in the
registry additionally carry `trace` (the host-side trace id stamped
on the Request at admission) and `hop` (how many times the request has
moved between engines — failover, rebalance, handoff import), and the
`seat`-marked kinds (request_submit, handoff_import) carry the
engine's `tp` + `role`; `obs/journey.py` folds a JSONL file back into
one cross-engine timeline per request.

The log is ring-buffered in memory (default 4096 records) with an
optional JSONL file sink; both the clock and the buffer are injectable
so fault drills assert on bit-reproducible records. Listeners
(`add_listener`) observe every record synchronously AFTER it lands in
the ring — the flight recorder's subscription point; a process with no
listener installed pays one empty-list check per emit.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from typing import Dict, IO, Iterable, List, Optional

__all__ = ["SCHEMA_VERSION", "EVENT_KINDS", "EventLog",
           "get_event_log", "set_event_log", "read_jsonl",
           "required_fields", "seat_kinds", "validate_record"]

SCHEMA_VERSION = 1

# Machine-readable event-kind registry (ISSUE 13). Per kind:
#   required — fields every record of the kind carries (graftlint's
#              event-kind-contract checks call sites statically;
#              validate_record checks parsed records at runtime);
#   optional — fields a record MAY carry (everything else is a lint
#              error at the emit site);
#   journey  — carries trace/hop journey stamps (obs/journey.py);
#   seat     — opens a journey hop on an engine (SEAT_KINDS);
#   doc      — one line for humans.
# The envelope fields schema/ts/seq/kind are stamped by EventLog.emit
# and never listed. "plane" (training|serving) is conventional on most
# kinds and listed per kind.
EVENT_KINDS: Dict[str, dict] = {
    # ---- training plane ------------------------------------------------
    "train_step": {
        "required": ("plane", "step", "epoch", "lr", "throughput",
                     "update_applied"),
        "optional": ("loss", "gnorm"),
        "doc": "one optimizer step (obs/training.py; loss omitted when "
               "nothing else fenced it — the piggyback contract)"},
    "anomaly": {
        "required": ("plane", "step", "action", "policy", "gnorm"),
        "optional": (),
        "doc": "anomaly-guard observation (utils/anomaly.py)"},
    "fault_injected": {
        "required": ("fault", "step"),
        "optional": ("plane",),
        "doc": "a utils/faults shot fired (drill provenance)"},
    "preempted": {
        "required": ("plane", "step"),
        "optional": (),
        "doc": "worker preemption re-raised out of a training loop "
               "(ISSUE 11; flight-recorder trigger)"},
    "checkpoint_save": {
        "required": ("step", "path", "async", "duration_s", "nshards"),
        "optional": ("shard", "mid_cycle", "plane"),
        "doc": "one save unit; the whole-checkpoint publish record is "
               "the one WITHOUT a shard field (ISSUE 9)"},
    "checkpoint_load": {
        "required": ("path",),
        "optional": ("sharded", "nshards", "plane"),
        "doc": "a checkpoint directory loaded (sharded dirs carry "
               "sharded/nshards)"},
    "checkpoint_corrupt_skipped": {
        "required": ("path", "error"),
        "optional": ("plane",),
        "doc": "a corrupt checkpoint skipped during latest-discovery "
               "fallback (flight-recorder trigger)"},
    "perf_result": {
        "required": ("plane", "model", "batch_size", "iterations",
                     "compile_s", "steady_wall_s", "images_per_sec"),
        "optional": (),
        "doc": "models/perf.py benchmark result row"},
    # ---- serving plane: request lifecycle ------------------------------
    "request_submit": {
        "required": ("plane", "engine", "request", "prompt_len",
                     "priority", "tp", "role"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True, "seat": True,
        "doc": "request admitted to an engine queue (initial dispatch, "
               "failover resubmission, rebalance move)"},
    "request_rejected": {
        "required": ("plane", "engine", "request", "queue_depth"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True,
        "doc": "submission bounced off a full queue "
               "(overload_policy='reject')"},
    "request_terminal": {
        "required": ("plane", "engine", "request", "status", "reason",
                     "tokens", "ttft_s", "latency_s", "tp", "role"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True,
        "doc": "request reached a terminal status "
               "(done/shed/expired/poisoned/failed)"},
    "prefix_hit": {
        "required": ("plane", "engine", "request", "matched_tokens",
                     "blocks", "prompt_len"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True,
        "doc": "paged-KV prefix reuse at admission (ISSUE 8)"},
    "tenant_throttled": {
        "required": ("plane", "tenant", "action"),
        "optional": ("router", "engine", "request", "queued"),
        "doc": "a tenant's request was held back by ITS OWN isolation "
               "contract (ISSUE 19): action 'defer' (token bucket "
               "empty — waits for refill), 'shed' (deferred queue at "
               "max_pending — terminal status 'shed'), or 'kv_quota' "
               "(engine admission skipped it, exclusive KV blocks at "
               "quota). Other tenants' traffic is untouched by "
               "construction — the tenant_noisy drill pins it"},
    "prefix_evict": {
        "required": ("plane", "engine", "blocks"),
        "optional": (),
        "doc": "LRU prefix blocks evicted under pool pressure"},
    "kv_spill": {
        "required": ("plane", "engine", "blocks"),
        "optional": ("host_in_use", "host_evicted", "tp"),
        "doc": "refcount-0 device blocks spilled to the host-RAM tier "
               "instead of dying (ISSUE 16): `blocks` moved in one "
               "batched transfer; `host_evicted` = host-LRU nodes "
               "pushed to oblivion to make room"},
    "kv_readmit": {
        "required": ("plane", "engine", "blocks"),
        "optional": ("host_in_use", "tp"),
        "doc": "host-tier blocks re-admitted to device pools on a "
               "prefix hit (ISSUE 16) — a device_put + table patch, "
               "bytes never recomputed"},
    "handoff_export": {
        "required": ("plane", "engine", "request", "prompt_len",
                     "blocks"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True,
        "doc": "prefill-role engine detached a prefilled request "
               "(ISSUE 10)"},
    "handoff_import": {
        "required": ("plane", "engine", "request", "prompt_len",
                     "blocks", "source", "tp", "role"),
        "optional": ("trace", "hop", "tenant"),
        "journey": True, "seat": True,
        "doc": "serving engine seated a disaggregated-prefill package"},
    "spec_verify": {
        "required": ("plane", "engine", "draft_engine", "step",
                     "active", "proposed", "accepted", "emitted"),
        "optional": (),
        "doc": "one speculative draft-verify round (ISSUE 15): the "
               "draft proposed `proposed` tokens across `active` "
               "slots, the target's coupled samples accepted "
               "`accepted` of them, and `emitted` tokens (accepted + "
               "per-slot mismatch/bonus samples) left the engine"},
    "spec_fallback": {
        "required": ("plane", "engine", "draft_engine", "reason"),
        "optional": (),
        "doc": "the SpeculativeEngine lost its draft (watchdog trip / "
               "dispatch failure / pool exhaustion) and degraded to "
               "target-only decode — tokens bit-identical by "
               "construction (ISSUE 15; the draft's own "
               "engine_degraded event rides alongside)"},
    "spec_k_adjust": {
        "required": ("plane", "engine", "draft_engine", "round",
                     "k_from", "k_to", "accept"),
        "optional": ("suspended", "window"),
        "doc": "one adaptive-lookahead evaluation (ISSUE 18): every "
               "`adapt_window` speculative rounds the windowed accept "
               "rate (obs/timeseries.HistogramWindow over the per-"
               "round accept-fraction histogram) moves k_live "
               "k_from→k_to (equal = held); `suspended` marks the "
               "~0-tax collapse mode where rounds run target-only "
               "between probe rounds — emitted every evaluation, so "
               "the sequence IS obs_report's k-timeline"},
    "draft_swap": {
        "required": ("plane", "engine", "draft_engine", "swap",
                     "accept_before"),
        "optional": ("accept_after", "round", "source"),
        "doc": "improved draft weights hot-swapped into the live "
               "engine (ISSUE 18): pure re-placement through the "
               "param_layout spine — zero new executables, no "
               "quiesce, tokens stay the target's bitwise. "
               "accept_before = windowed accept at swap time; "
               "accept_after lands in health()['speculative'] at the "
               "first post-swap evaluation (events are immutable — "
               "obs_report pairs the swap with the NEXT spec_k_adjust "
               "instead)"},
    # ---- serving plane: fleet ------------------------------------------
    "engine_degraded": {
        "required": ("plane", "engine", "reason"),
        "optional": (),
        "doc": "watchdog trip / retry exhaustion (flight-recorder "
               "trigger)"},
    "engine_drain": {
        "required": ("plane", "engine", "queued", "active"),
        "optional": (),
        "doc": "engine entered drain mode (stop-admission)"},
    "engine_added": {
        "required": ("plane", "router", "engine", "pool_size"),
        "optional": (),
        "doc": "router grew the pool (autoscale / add_engine)"},
    "engine_removed": {
        "required": ("plane", "router", "engine", "state", "pool_size"),
        "optional": (),
        "doc": "router removed a drained/degraded engine"},
    "router_failover": {
        "required": ("plane", "router", "request", "source", "target"),
        "optional": ("trace", "hop"),
        "journey": True,
        "doc": "request rerouted off a degraded engine (tokens "
               "bit-identical by contract)"},
    "router_rebalance": {
        "required": ("plane", "router", "source", "target", "moved",
                     "requests"),
        "optional": (),
        "doc": "queued requests moved between engines at step time"},
    "router_handoff": {
        "required": ("plane", "router", "request", "source", "target",
                     "blocks"),
        "optional": ("trace", "hop"),
        "journey": True,
        "doc": "router moved a prefilled package to a serving engine"},
    "prefix_migrate": {
        "required": ("plane", "router", "source", "target", "blocks"),
        "optional": ("chains",),
        "doc": "a degraded/draining engine's radix tree migrated into "
               "a survivor's host tier (ISSUE 16): `blocks` grafted "
               "out of `chains` exported nodes — warm hit-rate "
               "survives failover"},
    "autoscale_decision": {
        "required": ("plane", "router", "action"),
        "optional": ("t", "p99_s", "engines", "target_p99_s",
                     "backlog", "occupancy", "objective", "q",
                     "group"),
        "doc": "autoscaler acted on the SLO loop (scale_up/scale_down/"
               "drain/shed_mode/restore_policy/rebalance_groups)"},
    "group_rebalance": {
        "required": ("plane", "router", "from_group", "to_group",
                     "action"),
        "optional": ("engine",),
        "doc": "capacity moved BETWEEN engine groups (ISSUE 19): "
               "action 'move' = EngineRouter.move_engine retagged a "
               "same-model engine compile-free; 'rebalance' = the "
               "Autoscaler drained an idle group's engine and grew "
               "the breaching group via its factory"},
    # ---- scenario plane (ISSUE 20) -------------------------------------
    "scenario_phase": {
        "required": ("plane", "scenario", "phase", "t"),
        "optional": ("arrivals", "note"),
        "doc": "a compiled scenario crossed a phase boundary during "
               "replay (ISSUE 20): `t` is the virtual-clock time, "
               "`arrivals` the number of requests the phase "
               "contributed — obs_report's scenario timeline reads "
               "the sequence"},
    "chaos_inject": {
        "required": ("plane", "scenario", "action", "target", "t"),
        "optional": ("note",),
        "doc": "a chaos-schedule entry fired during scenario replay "
               "(ISSUE 20): action watchdog_trip/drain/tenant_flood "
               "applied to `target` (engine name or tenant) at "
               "virtual time `t` — the marker that lets a post-mortem "
               "separate injected faults from organic ones"},
    "sim_calibration": {
        "required": ("plane", "sources", "decode_ms_per_token",
                     "prefill_ms_per_token"),
        "optional": ("engine", "factors"),
        "doc": "a SimulatedEngine cost model announced its provenance "
               "(ISSUE 20): `sources` names the committed "
               "BENCH_r0*.json rows the ms/token figures derive from "
               "and `factors` the documented transformation constants "
               "— the honesty trail behind every simulated latency"},
    # ---- observability plane -------------------------------------------
    "metrics_snapshot": {
        "required": ("snapshot",),
        "optional": ("plane", "note"),
        "doc": "full registry snapshot embedded as an event "
               "(obs.log_metrics_snapshot) — self-contained JSONL"},
    "incident_dump": {
        "required": ("incident", "bundle", "component", "trigger_kind",
                     "events_in_tail"),
        "optional": (),
        "doc": "the flight recorder wrote a post-mortem bundle "
               "(ISSUE 11; obs_report's incidents section)"},
    "alert_firing": {
        "required": ("plane", "alert", "objective", "value", "target",
                     "window_s"),
        "optional": ("rule_kind", "burn", "long_value", "short_value",
                     "pending_s"),
        "doc": "an AlertRule crossed into firing (ISSUE 14, "
               "obs/slo.py): value vs target over the window_s that "
               "breached (burn-rate rules name the long window and "
               "carry long/short values + the burn multiple); a "
               "flight-recorder trigger — an SLO burn dumps a "
               "slo_burn post-mortem bundle"},
    "alert_resolved": {
        "required": ("plane", "alert", "objective", "value", "target",
                     "firing_s"),
        "optional": ("rule_kind", "window_s"),
        "doc": "a firing alert measured healthy for its clear_s "
               "streak and resolved (ISSUE 14; firing_s = time spent "
               "firing — obs_report's firing→resolved timeline and "
               "compliance table read it)"},
}


def required_fields(kind: str) -> tuple:
    """Fields every record of `kind` must carry (empty for unknown
    kinds — the schema stays open at runtime)."""
    return tuple(EVENT_KINDS.get(kind, {}).get("required", ()))


def seat_kinds() -> tuple:
    """Kinds that open a journey hop on an engine, in registry order
    (obs/journey.py's SEAT_KINDS)."""
    return tuple(k for k, v in EVENT_KINDS.items() if v.get("seat"))


def validate_record(rec: dict) -> list:
    """Problems with one parsed event record against EVENT_KINDS:
    unknown kind, or a registered kind missing required fields. Empty
    list = conformant. Pure host-side; obs_report uses it to flag
    schema drift in a JSONL file."""
    kind = rec.get("kind")
    if kind not in EVENT_KINDS:
        return [f"unknown kind {kind!r}"]
    missing = [f for f in required_fields(kind) if f not in rec]
    if missing:
        return [f"kind {kind!r} missing required field(s): "
                + ", ".join(missing)]
    return []


class EventLog:
    """In-memory ring buffer of event dicts + optional JSONL sink.

    `clock` is injectable (drills pass a fake); `path` opens an append
    sink whose lines are flushed per record (events must survive the
    crash legs — a torn final line is tolerated by `read_jsonl`)."""

    def __init__(self, capacity: int = 4096,
                 path: Optional[str] = None, clock=None):
        import time as _time

        self._clock = clock or _time.time
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()
        self._sink: Optional[IO[str]] = None
        self._listeners: List = []
        self.path = path
        if path:
            self._sink = open(path, "a")

    # ------------------------------------------------------------- emit
    def emit(self, kind: str, **fields) -> dict:
        with self._lock:
            rec = {"schema": SCHEMA_VERSION, "ts": self._clock(),
                   "seq": self._seq, "kind": kind, **fields}
            self._seq += 1
            self._ring.append(rec)
            if self._sink is not None:
                self._sink.write(json.dumps(rec, sort_keys=True,
                                            default=_jsonable) + "\n")
                self._sink.flush()
        # outside the lock: a listener (the flight recorder) may emit
        # its own record (incident_dump) re-entrantly
        for fn in list(self._listeners):
            try:
                fn(rec)
            except Exception:
                logging.getLogger("bigdl_tpu.obs").exception(
                    "event listener failed")
        return rec

    # -------------------------------------------------------- listeners
    def add_listener(self, fn) -> None:
        """Subscribe `fn(record)` to every emitted record (called
        synchronously, after the ring append, outside the lock). The
        flight recorder's hook; listeners must never emit
        unconditionally (re-entrancy is bounded, not infinite)."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # ------------------------------------------------------------ query
    def events(self, kind: Optional[str] = None,
               **match) -> List[dict]:
        """Records (oldest first), optionally filtered by kind and by
        exact field values (`events("request_terminal",
        status="poisoned")`)."""
        out = []
        for rec in self._ring:
            if kind is not None and rec["kind"] != kind:
                continue
            if any(rec.get(k) != v for k, v in match.items()):
                continue
            out.append(rec)
        return out

    def counts_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self._ring:
            out[rec["kind"]] = out.get(rec["kind"], 0) + 1
        return dict(sorted(out.items()))

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


def _jsonable(o):
    """Sink fallback for numpy scalars etc. — never let a telemetry
    write throw out of a training/serving loop, and NEVER fetch a
    device array: emission consumes already-fetched host values (the
    obs contract), so a jax.Array reaching the sink is a caller bug —
    it is repr'd, not synced (a silent `.item()` here would stall the
    decode loop once per event through the axon tunnel)."""
    import numpy as np

    if isinstance(o, np.generic) or (isinstance(o, np.ndarray)
                                     and o.ndim == 0):
        # host-memory numpy scalar: .item() is a pure host conversion
        return o.item()  # graftlint: disable=hidden-device-sync
    return repr(o)


def stream_jsonl(path: str):
    """Yield events from a JSONL file one record at a time — the
    streaming twin of `read_jsonl` (ISSUE 20): a 10⁶-event simulator
    run must never be materialized as one list just to be summarized.
    Same torn-tail tolerance: an undecodable line (crash mid-write) is
    skipped, not an error."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail


def read_jsonl(path: str) -> List[dict]:
    """Parse a JSONL event file; a torn final line (crash mid-write)
    is dropped, not an error. Record conformance is judged against
    the EVENT_KINDS registry above — run each record through
    `validate_record` (obs_report does) rather than keeping a local
    kind list. Large files should prefer `stream_jsonl`."""
    return list(stream_jsonl(path))


# BIGDL_OBS_EVENTS=<path> attaches a JSONL file sink to the default
# log at import — `BIGDL_OBS_EVENTS=/tmp/run.jsonl python train.py`
# then `python scripts/obs_report.py /tmp/run.jsonl`
import os as _os

_log = EventLog(path=_os.environ.get("BIGDL_OBS_EVENTS") or None)


def get_event_log() -> EventLog:
    return _log


def set_event_log(log: Optional[EventLog]) -> EventLog:
    """Install an event log (None → fresh default); returns the active
    one. (Explicit None check: an EMPTY EventLog is falsy via
    __len__.) A fresh default re-attaches the BIGDL_OBS_EVENTS file
    sink if the env var is set — resets must not silently drop the
    operator's JSONL sink (append mode, so prior records survive)."""
    global _log
    if log is None:
        log = EventLog(path=_os.environ.get("BIGDL_OBS_EVENTS") or None)
    if log is not _log:
        _log.close()   # don't leak the replaced log's file handle;
        _log = log     # its in-memory ring stays readable
    return _log
