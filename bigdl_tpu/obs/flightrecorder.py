"""Incident flight recorder — a bounded black box that dumps a
post-mortem bundle the moment something goes wrong (ISSUE 11
tentpole).

The serving and training planes already EMIT the truth (structured
events, registry counters, health() snapshots), but an incident dumps
nothing: by the time an operator looks, the ring buffer has rolled and
the registry only shows totals. `FlightRecorder` subscribes to the
active event log (EventLog listener — zero cost when no recorder is
installed), keeps bounded per-component rings of recent events, and on
a trigger event writes one self-contained bundle directory:

    <outdir>/incident-NNN-<kind>/
        manifest.json    trigger event, bundle name, recorder clock ts
        events.jsonl     global tail (the last `capacity` events,
                         trigger included — the record that names the
                         failing step)
        components.json  per-component tails (engine / router / plane)
        health.json      every registered health source's snapshot
        registry.json    registry snapshot + counter deltas since
                         install()
        journeys.json    journey fragments reconstructed from the tail
                         (obs/journey.py) — the requests in flight when
                         it happened

Triggers (exactly the incident set ISSUE 11 names): a watchdog trip or
any engine degradation (`engine_degraded`), a poisoned request or a
pool-exhausted finish (`request_terminal`), a worker preemption
(`preempted`, emitted by the optimizer loops when a Preempted
propagates — plus the injected `fault_injected fault=preempt`), and
checkpoint corruption (`checkpoint_corrupt_skipped`). ISSUE 14 adds
SLO burns: an `alert_firing` event (obs/slo.py) dumps a `slo_burn`
bundle whose trigger record names the alert, objective, and the
window that breached — the post-mortem exists the moment the page
does.

Contracts (the standing obs rules, tests/test_journey.py):
* BIGDL_OBS=off kills it — the listener early-outs on `obs.enabled()`
  (and emission never reaches it anyway);
* zero device syncs / zero compiles: everything recorded is an
  already-emitted host dict;
* bit-deterministic under injected clocks: bundle content is a pure
  function of the event sequence + the injected registry/recorder
  clocks (all JSON sorted), so drills pin bundle bytes across runs;
* a dump emits one `incident_dump` event (bundle name, trigger kind,
  component) so the JSONL record itself indexes its bundles
  (scripts/obs_report.py "incidents" section).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("bigdl_tpu.obs")

__all__ = ["FlightRecorder", "default_trigger"]


def _obs():
    """Call-time import (obs/__init__ imports this module — a
    top-level import would cycle)."""
    from bigdl_tpu import obs

    return obs


def default_trigger(rec: dict) -> Optional[str]:
    """The ISSUE-11 incident set. Returns a short slug naming the
    incident kind, or None for a non-incident event."""
    kind = rec.get("kind")
    if kind == "engine_degraded":
        return "engine_degraded"
    if kind == "request_terminal":
        if rec.get("status") == "poisoned":
            return "poisoned"
        if rec.get("reason") == "pool_exhausted":
            return "pool_exhausted"
        return None
    if kind == "preempted":
        return "preempted"
    if kind == "fault_injected" and rec.get("fault") == "preempt":
        return "preempted"
    if kind == "checkpoint_corrupt_skipped":
        return "checkpoint_corrupt"
    if kind == "alert_firing":
        # ISSUE 14: an SLO burn is an incident — the bundle's trigger
        # record names the alert, its objective, and the window that
        # breached; resolution is not an incident
        return "slo_burn"
    return None


class FlightRecorder:
    """Bounded black box over the active event log.

    >>> rec = FlightRecorder(outdir, clock=clk)    # injectable clock
    >>> rec.register_health_source("e0", engine.health)
    >>> rec.install()          # subscribe to the ACTIVE event log
    >>> ... traffic ...
    >>> rec.close()            # unsubscribe; rec.bundles lists dumps

    Knobs are constructor args, never env (graftlint trace-env-read):
    `capacity` (global tail length), `per_component` (per-component
    ring length), `max_bundles` (dump budget — a poison storm writes
    the first N bundles, then only counts), `trigger` (predicate
    `event -> slug|None`, default `default_trigger`), `clock`
    (seconds source for the manifest stamp — inject the drill clock
    for bit-deterministic bundles)."""

    def __init__(self, outdir: str, capacity: int = 256,
                 per_component: int = 64, max_bundles: int = 8,
                 trigger: Callable[[dict], Optional[str]] = None,
                 clock: Callable[[], float] = None):
        import time as _time

        self.outdir = outdir
        self._clock = clock or _time.time
        self._trigger = trigger or default_trigger
        self._capacity = capacity
        self._per_component = per_component
        self.max_bundles = max_bundles
        self._ring: deque = deque(maxlen=capacity)
        self._components: Dict[str, deque] = {}
        self._health: Dict[str, Callable[[], dict]] = {}
        self._counter_base: Dict[str, float] = {}
        self._log = None
        self._n = 0
        # EventLog calls listeners OUTSIDE its lock, so concurrent
        # emitters (the async checkpoint writer thread, a serving
        # loop) can reach _on_event simultaneously — serialize ring
        # mutation and bundle numbering. REENTRANT because _dump's
        # own incident_dump emission re-enters the listener on the
        # same thread.
        self._lock = threading.RLock()
        self.triggers_seen = 0
        self.bundles: List[str] = []

    # ---------------------------------------------------------- wiring
    def install(self, log=None) -> "FlightRecorder":
        """Subscribe to `log` (default: the active event log) and
        baseline the registry counters for the per-bundle delta."""
        obs = _obs()
        self._log = log if log is not None else obs.get_event_log()
        self._log.add_listener(self._on_event)
        self._counter_base = self._flat_counters()
        os.makedirs(self.outdir, exist_ok=True)
        return self

    def close(self) -> None:
        if self._log is not None:
            self._log.remove_listener(self._on_event)
            self._log = None

    def register_health_source(self, name: str,
                               fn: Callable[[], dict]) -> None:
        """Attach a health() callable (engine, router) whose snapshot
        rides in every bundle under `name`."""
        self._health[name] = fn

    # -------------------------------------------------------- recording
    @staticmethod
    def _component_of(rec: dict) -> str:
        return str(rec.get("engine") or rec.get("router")
                   or rec.get("plane") or "global")

    def _on_event(self, rec: dict) -> None:
        obs = _obs()
        if not obs.enabled():
            return
        with self._lock:
            self._ring.append(rec)
            comp = self._component_of(rec)
            ring = self._components.get(comp)
            if ring is None:
                ring = self._components[comp] = deque(
                    maxlen=self._per_component)
            ring.append(rec)
            slug = None
            if rec.get("kind") != "incident_dump":
                try:
                    slug = self._trigger(rec)
                except Exception:
                    logger.exception("flight-recorder trigger failed")
            if slug is not None:
                self.triggers_seen += 1
                if len(self.bundles) < self.max_bundles:
                    try:
                        self._dump(rec, slug, comp)
                    except Exception:
                        # the black box must never take down the loop
                        # it observes; the failure stays diagnosable
                        logger.exception("flight-recorder dump failed")

    # ----------------------------------------------------------- dumps
    def _flat_counters(self) -> Dict[str, float]:
        from bigdl_tpu.obs.registry import series_key

        obs = _obs()
        out: Dict[str, float] = {}
        snap = obs.get_registry().snapshot()
        for name, fam in snap["metrics"].items():
            if fam["kind"] != "counter":
                continue
            for s in fam["series"]:
                out[series_key(name, s["labels"])] = s["value"]
        return out

    def _write(self, bundle: str, fname: str, obj) -> None:
        with open(os.path.join(bundle, fname), "w") as f:
            if fname.endswith(".jsonl"):
                for rec in obj:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
            else:
                json.dump(obj, f, sort_keys=True, indent=1)

    def _dump(self, trigger_rec: dict, slug: str, component: str) -> str:
        from bigdl_tpu.obs.journey import build_journeys

        obs = _obs()
        name = f"incident-{self._n:03d}-{slug}"
        self._n += 1
        bundle = os.path.join(self.outdir, name)
        os.makedirs(bundle, exist_ok=True)
        # tails in seq order: listeners run outside the EventLog lock,
        # so concurrent emitters can deliver records to the ring out
        # of stamp order — the bundle is canonicalized on the seq the
        # log stamped under ITS lock (stable for equal seqs)
        tail = sorted(self._ring, key=lambda r: r.get("seq", 0))
        self._write(bundle, "events.jsonl", tail)
        self._write(bundle, "components.json",
                    {c: sorted(r, key=lambda x: x.get("seq", 0))
                     for c, r in sorted(self._components.items())})
        health = {}
        for hname in sorted(self._health):
            try:
                health[hname] = self._health[hname]()
            except Exception as e:        # a degraded source still dumps
                health[hname] = {"error": repr(e)}
        self._write(bundle, "health.json", health)
        now_counters = self._flat_counters()
        delta = {k: round(v - self._counter_base.get(k, 0.0), 9)
                 for k, v in sorted(now_counters.items())
                 if v != self._counter_base.get(k, 0.0)}
        self._write(bundle, "registry.json",
                    {"snapshot": obs.get_registry().snapshot(),
                     "counters_delta_since_install": delta})
        self._write(bundle, "journeys.json", build_journeys(tail))
        manifest = {
            "schema": 1,
            "bundle": name,
            "ts": self._clock(),
            "incident": slug,
            "component": component,
            "trigger": trigger_rec,
            "events_in_tail": len(tail),
            "components": sorted(self._components),
            "health_sources": sorted(self._health),
        }
        self._write(bundle, "manifest.json", manifest)
        self.bundles.append(name)
        obs.emit_event("incident_dump", incident=slug,
                       bundle=name, component=component,
                       trigger_kind=trigger_rec.get("kind"),
                       events_in_tail=len(tail))
        return bundle
