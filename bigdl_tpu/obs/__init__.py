"""Unified telemetry plane (ISSUE 5 tentpole).

One process-wide home for the three observability primitives both the
training loop and the serving engine report into:

* `registry` — metrics (counter / gauge / fixed-bucket histogram with
  label sets; deterministic snapshot, Prometheus text, JSON export)
* `events`   — schema-versioned JSONL event log (ring buffer +
  optional file sink); the machine-readable record of what a run did
* `spans`    — host-side span tracer emitting Chrome-trace/Perfetto
  JSON, aligned with `utils/profiler` device traces

ISSUE 14 adds the LIVE layer on top: `timeseries` (bounded ring of
registry samples, windowed rate/delta/quantile queries — the
autoscaler's windowing now lives here), `slo` (declarative
SLOObjective + deterministic AlertRule/AlertEngine; alert_firing is a
flight-recorder trigger), and `exposition` (stdlib-HTTP scrape
endpoint: /metrics Prometheus text, /health + /alerts JSON).

Hard contracts (tests/test_obs.py):
* telemetry NEVER touches jitted code: zero new compiles with it on
  (the serving #buckets+1 guard passes with telemetry enabled);
* zero new device→host syncs on hot paths — emission consumes only
  values the loop already fetched;
* everything is bit-reproducible under an injected clock (the fault
  drills assert on telemetry, scripts/fault_drill.py);
* <1% step overhead on the lmdecode_batched bench row (bench.py
  measures on-vs-off in one invocation).

Global switch: `BIGDL_OBS=off` (env, read at import) or
`set_enabled(False)` at runtime — every emission path early-outs on
`enabled()`. Core serving/training bookkeeping (engine.stats, loss
logging) does NOT depend on telemetry being on.
"""

from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.obs.events import (EventLog, get_event_log, read_jsonl,
                                  set_event_log, stream_jsonl)
from bigdl_tpu.obs.exposition import ScrapeServer
from bigdl_tpu.obs.flightrecorder import FlightRecorder, default_trigger
from bigdl_tpu.obs.journey import (build_journeys, journeys_json,
                                   summarize_journeys, to_perfetto)
from bigdl_tpu.obs.registry import (DEFAULT_LATENCY_BUCKETS, Counter,
                                    Gauge, Histogram, MetricsRegistry,
                                    get_registry, series_key,
                                    set_registry)
from bigdl_tpu.obs.slo import AlertEngine, AlertRule, SLOObjective
from bigdl_tpu.obs.spans import SpanTracer, get_tracer, set_tracer
from bigdl_tpu.obs.timeseries import HistogramWindow, MetricsSampler

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS", "get_registry", "set_registry",
    "EventLog", "get_event_log", "set_event_log", "read_jsonl",
    "stream_jsonl",
    "SpanTracer", "get_tracer", "set_tracer",
    "FlightRecorder", "default_trigger",
    "build_journeys", "journeys_json", "summarize_journeys",
    "to_perfetto",
    "MetricsSampler", "HistogramWindow",
    "SLOObjective", "AlertRule", "AlertEngine", "ScrapeServer",
    "enabled", "set_enabled", "emit_event", "log_metrics_snapshot",
    "provenance", "reset_all",
]

_enabled = os.environ.get("BIGDL_OBS", "on").lower() not in (
    "off", "0", "false", "no")


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> bool:
    """Runtime switch for every emission path (registry mirrors, event
    records, spans). Returns the previous value."""
    global _enabled
    prev, _enabled = _enabled, bool(value)
    return prev


def emit_event(kind: str, **fields) -> Optional[dict]:
    """Emit into the active event log iff telemetry is enabled — THE
    call every instrumented site uses (optimizer, engine, checkpoint,
    faults, anomaly guard)."""
    if not _enabled:
        return None
    return get_event_log().emit(kind, **fields)


def log_metrics_snapshot(**extra) -> Optional[dict]:
    """Embed a full registry snapshot as a `metrics_snapshot` event,
    making a JSONL file self-contained for scripts/obs_report.py."""
    if not _enabled:
        return None
    return get_event_log().emit("metrics_snapshot",
                                snapshot=get_registry().snapshot(),
                                **extra)


def provenance(prefix: Optional[str] = None) -> dict:
    """Compact registry view for attaching to bench rows: counter and
    gauge values (histograms reduced to count/sum), optionally
    restricted to names starting with `prefix`. Deterministic ordering
    (sorted)."""
    snap = get_registry().snapshot()
    out = {}
    for name, fam in snap["metrics"].items():
        if prefix is not None and not name.startswith(prefix):
            continue
        for s in fam["series"]:
            key = series_key(name, s["labels"])
            if fam["kind"] == "histogram":
                out[key] = {"count": s["count"],
                            "sum": round(s["sum"], 6)}
            else:
                out[key] = s["value"]
    return {"telemetry": "on" if _enabled else "off", "metrics": out}


def reset_all(clock=None) -> None:
    """Fresh registry + event log + (disabled) tracer — drill/test
    isolation. `clock` (if given) is injected into all three. The
    fresh event log keeps the BIGDL_OBS_EVENTS file sink (append), so
    resetting never silently drops the operator's JSONL record.

    Caveat: objects that cache registry children at construction
    (InferenceEngine, Optimizer loops, AnomalyGuard, optim.Metrics)
    keep writing to the registry that was active WHEN THEY WERE BUILT
    — install custom telemetry first, construct after (the fault
    drills do exactly this)."""
    set_registry(MetricsRegistry(clock=clock))
    set_event_log(EventLog(
        path=os.environ.get("BIGDL_OBS_EVENTS") or None, clock=clock))
    set_tracer(SpanTracer(clock=clock))
