"""Metrics time-series layer — bounded ring of registry samples with
windowed queries (ISSUE 14 tentpole).

The registry answers "what are the totals NOW"; everything that wants
to watch the RUNNING fleet — the autoscaler, the SLO/alert engine
(obs/slo.py), the scrape endpoint's freshness view, the ops console —
needs "what happened over the last W seconds". Before this module each
consumer hand-rolled that windowing (`Autoscaler._window_p99` diffed
cumulative bucket counts privately); here the primitive lives once:

* `delta_quantile` / `HistogramWindow` — windowed quantiles over
  cumulative-bucket deltas, the exact evaluation-to-evaluation math
  the autoscaler used (it now consumes `HistogramWindow`; decisions
  are bit-identical by construction — same snapshot points, same
  delta, same shared `quantile_from_buckets` estimator, pinned by the
  fleet_autoscale drill);
* `MetricsSampler` — a bounded ring of periodic registry samples with
  `rate()` / `delta()` / `window_quantile()` queries over any window,
  the alert engine's and the scrape endpoint's data plane.

Design rules (the standing obs contracts):

* **Constructor knobs only** (graftlint trace-env-read): `registry`,
  `interval_s`, `capacity`, `clock` — never env.
* **Driven, not driving.** `tick()` is called from the owning loop (a
  scheduling round, a drill loop, a bench wave) and self-rate-limits
  to one sample per `interval_s` of the INJECTED clock; the sampler
  never starts a thread and never reads the wall clock behind the
  caller's back, so a drill under a virtual clock samples
  bit-deterministically (the slo_alert drill pins byte-identity).
* **Host-side only.** A sample is a flattened `registry.snapshot()` —
  already-fetched host ints/floats; zero device syncs, zero compiles
  (tests/test_slo.py re-pins the serving compile guard with the
  sampler armed).
* **Locked on both sides.** The ring and its queries take the
  sampler's lock because the scrape endpoint (obs/exposition.py)
  reads them from its serving thread while the owning loop ticks
  (lock-discipline).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.obs.registry import (MetricsRegistry, get_registry,
                                    quantile_from_buckets)

__all__ = ["MetricsSampler", "HistogramWindow", "delta_quantile",
           "counts_delta"]


def counts_delta(counts_now: Sequence[int],
                 counts_then: Optional[Sequence[int]]) -> List[int]:
    """Per-bucket delta between two cumulative count vectors (`then`
    of None means "before any observation" — all zeros)."""
    if counts_then is None:
        counts_then = [0] * len(counts_now)
    return [c - p for c, p in zip(counts_now, counts_then)]


def delta_quantile(buckets: Sequence[float],
                   counts_now: Sequence[int],
                   counts_then: Optional[Sequence[int]],
                   q: float) -> Optional[float]:
    """q-quantile of the observations that landed BETWEEN two
    cumulative bucket-count snapshots — THE windowed-quantile
    primitive. `HistogramWindow` (autoscaler) and
    `MetricsSampler.window_quantile` (alert engine, ops views) both
    reduce to this one call into the shared estimator, so a windowed
    p99 can never drift between consumers."""
    return quantile_from_buckets(
        buckets, counts_delta(counts_now, counts_then), q)


class HistogramWindow:
    """Stateful delta window over one LIVE histogram child: each
    `quantile()` call reports on the observations since the PREVIOUS
    call, then re-opens the window. This is exactly the
    evaluation-to-evaluation windowing `Autoscaler._window_p99` used
    to hand-roll (cumulative counts snapshotted per evaluation, delta
    quantile between them) — hoisted here so the SLO plane shares it;
    the autoscaler's decisions are bit-identical before/after the
    refactor (fleet_autoscale drill)."""

    def __init__(self, child):
        self._child = child
        self._last: Optional[List[int]] = None

    def quantile(self, q: float) -> Optional[float]:
        """Quantile of the observations since the previous call (None
        when the window saw none)."""
        counts = list(self._child.counts)
        prev, self._last = self._last, counts
        return delta_quantile(self._child.buckets, counts, prev, q)


class MetricsSampler:
    """Bounded ring of periodic registry samples + windowed queries.

    >>> sampler = MetricsSampler(interval_s=0.5, clock=drill_clock)
    >>> while serving:
    ...     router.step(); sampler.tick()
    >>> sampler.window_quantile("router_request_latency_seconds",
    ...                         0.99, labels={"router": "r0"},
    ...                         window_s=10.0)

    Knobs are CONSTRUCTOR args, never env: `registry` (default: the
    active one at first use), `interval_s` (tick rate limit),
    `capacity` (ring length — memory is bounded at
    capacity × registry size), `clock` (seconds source; inject the
    drill/fleet virtual clock for bit-deterministic sampling —
    `time.monotonic` is only the injection-point default)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 interval_s: float = 1.0, capacity: int = 512,
                 clock: Optional[Callable[[], float]] = None):
        if interval_s < 0:
            raise ValueError("interval_s must be >= 0")
        if capacity < 2:
            raise ValueError(
                "capacity must be >= 2 (window queries diff two "
                "samples)")
        self._registry = registry
        self.interval_s = float(interval_s)
        self._clock = clock or time.monotonic
        self._samples: deque = deque(maxlen=capacity)
        # whole-run baseline (ISSUE 20): the FIRST sample ever taken,
        # held outside the ring so eviction can't touch it. Found by
        # the fleet simulator: a 10^5-request scenario ticks the
        # sampler far past any reasonable capacity, and every
        # `window_s=None` query ("whole run" by contract) silently
        # became "the last `capacity` samples" once the ring rolled —
        # loadgen's end-of-run SLO compliance read only the tail of
        # the run it claimed to summarize. `span(window_s=None)` now
        # anchors at this baseline, so whole-run deltas/quantiles
        # count from the actual start at any scale; bounded windows
        # keep the ring's memory bound.
        self._first: Optional[dict] = None
        self._lock = threading.Lock()

    @property
    def clock(self) -> Callable[[], float]:
        """The injected seconds source (the AlertEngine defaults to
        it, so one cell drives sampling AND alert transitions)."""
        return self._clock

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    # ----------------------------------------------------------- sampling
    def sample(self) -> dict:
        """Take one sample NOW (no rate limit): the flattened registry
        state stamped with the injected clock. Appends to the ring and
        returns the sample."""
        rec = {"t": self._clock(),
               "metrics": self.registry.snapshot()["metrics"]}
        with self._lock:
            self._samples.append(rec)
            if self._first is None:
                self._first = rec
        return rec

    def tick(self) -> Optional[dict]:
        """Sample iff `interval_s` has elapsed since the newest sample
        (the first call always samples). The owning loop calls this
        once per round; returns the new sample or None between
        intervals."""
        with self._lock:
            last = self._samples[-1]["t"] if self._samples else None
        if last is not None \
                and self._clock() - last < self.interval_s - 1e-9:
            return None
        return self.sample()

    # ------------------------------------------------------------ queries
    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def samples(self, window_s: Optional[float] = None) -> List[dict]:
        """Samples oldest-first; `window_s` keeps only those within
        that many seconds of the NEWEST sample (sample time, not wall
        time — deterministic under an injected clock)."""
        with self._lock:
            out = list(self._samples)
        if window_s is None or not out:
            return out
        cutoff = out[-1]["t"] - window_s
        return [s for s in out if s["t"] >= cutoff - 1e-9]

    def span(self, window_s: Optional[float] = None
             ) -> Optional[Tuple[dict, dict]]:
        """(oldest-in-window, newest) sample pair — the two endpoints
        every window query diffs; None with fewer than two samples in
        the window. `window_s=None` means WHOLE RUN: the old endpoint
        is the never-evicted first-sample baseline, so the answer
        stays correct after the ring rolls (the sim-found truncation
        bug — see the `_first` note in __init__)."""
        xs = self.samples(window_s)
        if window_s is None:
            with self._lock:
                first = self._first
            if first is not None and xs \
                    and first["t"] < xs[0]["t"] - 1e-9:
                xs = [first] + xs       # ring rolled past the start
        if len(xs) < 2:
            return None
        return xs[0], xs[-1]

    @staticmethod
    def _series(sample: dict, name: str,
                labels: Optional[Dict[str, str]]) -> Optional[dict]:
        fam = sample["metrics"].get(name)
        if fam is None:
            return None
        want = {k: str(v) for k, v in (labels or {}).items()}
        for s in fam["series"]:
            if s["labels"] == want:
                return s
        return None

    @staticmethod
    def _scalar(series: dict) -> float:
        """One comparable number per series: counter/gauge value,
        histogram observation count."""
        return series["count"] if "counts" in series else series["value"]

    def delta(self, name: str, *,
              labels: Optional[Dict[str, str]] = None,
              window_s: Optional[float] = None) -> Optional[float]:
        """Value increase of one series over the window (histogram:
        observation-count increase). None without two samples or when
        the newest sample lacks the series; a series absent from the
        window's OLD endpoint counts from zero (it was born inside the
        window)."""
        pair = self.span(window_s)
        if pair is None:
            return None
        old, new = pair
        sn = self._series(new, name, labels)
        if sn is None:
            return None
        so = self._series(old, name, labels)
        return self._scalar(sn) - (self._scalar(so)
                                   if so is not None else 0.0)

    def rate(self, name: str, *,
             labels: Optional[Dict[str, str]] = None,
             window_s: Optional[float] = None) -> Optional[float]:
        """delta / elapsed-seconds over the window endpoints (None on
        a zero-width window)."""
        pair = self.span(window_s)
        if pair is None:
            return None
        old, new = pair
        dt = new["t"] - old["t"]
        d = self.delta(name, labels=labels, window_s=window_s)
        if d is None or dt <= 0:
            return None
        return d / dt

    def window_quantile(self, name: str, q: float, *,
                        labels: Optional[Dict[str, str]] = None,
                        window_s: Optional[float] = None
                        ) -> Optional[float]:
        """Windowed quantile of a histogram series: the cumulative
        bucket counts at the window's two endpoints go through
        `delta_quantile` — the same estimator as the live child and
        obs_report, generalizing the autoscaler's old private
        `_window_p99` to any window over any histogram family."""
        pair = self.span(window_s)
        if pair is None:
            return None
        old, new = pair
        sn = self._series(new, name, labels)
        if sn is None or "counts" not in sn:
            return None
        so = self._series(old, name, labels)
        then = so["counts"] if so is not None and "counts" in so \
            else None
        return delta_quantile(sn["buckets"], sn["counts"], then, q)

    def series_deltas(self, name: str, *,
                      window_s: Optional[float] = None
                      ) -> List[Tuple[Dict[str, str], float]]:
        """(labels, delta) per series of a family over the window,
        series order as snapshotted (sorted) — the error-budget
        objective sums label subsets of these."""
        pair = self.span(window_s)
        if pair is None:
            return []
        old, new = pair
        fam = new["metrics"].get(name)
        if fam is None:
            return []
        out = []
        for s in fam["series"]:
            so = self._series(old, name, s["labels"])
            out.append((dict(s["labels"]),
                        self._scalar(s) - (self._scalar(so)
                                           if so is not None else 0.0)))
        return out
