"""Spatial upsampling.

Reference parity: nn/SpatialUpSamplingNearest.scala,
nn/SpatialUpSamplingBilinear.scala (integer scale; bilinear supports
align_corners). NHWC; lowered to gather/resize ops XLA vectorizes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class SpatialUpSamplingNearest(Module):
    def __init__(self, scale: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.scale = int(scale)

    def apply(self, variables, x, training=False, rng=None):
        s = self.scale
        y = jnp.repeat(jnp.repeat(x, s, axis=1), s, axis=2)
        return y, variables["state"]


class SpatialUpSamplingBilinear(Module):
    """Bilinear ×scale upsampling; align_corners=True matches the
    reference's (torch-style) default."""

    def __init__(self, scale: int, align_corners: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.scale = int(scale)
        self.align_corners = align_corners

    def apply(self, variables, x, training=False, rng=None):
        n, h, w, c = x.shape
        oh, ow = h * self.scale, w * self.scale
        if self.align_corners and oh > 1 and ow > 1:
            ys = jnp.linspace(0.0, h - 1.0, oh)
            xs = jnp.linspace(0.0, w - 1.0, ow)
        else:
            ys = (jnp.arange(oh) + 0.5) * (h / oh) - 0.5
            xs = (jnp.arange(ow) + 0.5) * (w / ow) - 0.5
            ys = jnp.clip(ys, 0.0, h - 1.0)
            xs = jnp.clip(xs, 0.0, w - 1.0)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        g = lambda yi, xi: x[:, yi][:, :, xi]
        top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
        bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
        return top * (1 - wy) + bot * wy, variables["state"]
