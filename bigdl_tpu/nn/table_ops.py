"""Table (multi-activity) arithmetic and routing layers.

Reference parity: nn/CAddTable.scala, nn/CMulTable.scala, nn/CDivTable.scala,
nn/CSubTable.scala, nn/CMaxTable.scala, nn/CMinTable.scala,
nn/JoinTable.scala, nn/SplitTable.scala, nn/SelectTable.scala,
nn/FlattenTable.scala, nn/MM.scala, nn/MV.scala, nn/Cosine /
nn/CosineDistance.scala, nn/DotProduct.scala, nn/Mean.scala, nn/Sum.scala,
nn/Max.scala, nn/Min.scala.

A "table" input here is any sequence or Table pytree of arrays.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table, T


def _elems(input):
    if isinstance(input, dict):
        from bigdl_tpu.utils.table import sort_key

        return [input[k] for k in sorted(input.keys(), key=sort_key)]
    return list(input)


class _TableReduce(Module):
    def _op(self, a, b):
        raise NotImplementedError

    def apply(self, variables, input, training=False, rng=None):
        elems = _elems(input)
        out = elems[0]
        for e in elems[1:]:
            out = self._op(out, e)
        return out, variables["state"]


class CAddTable(_TableReduce):
    def __init__(self, inplace: bool = False, name: Optional[str] = None):
        super().__init__(name=name)

    def _op(self, a, b):
        return a + b


class CMulTable(_TableReduce):
    def _op(self, a, b):
        return a * b


class CSubTable(_TableReduce):
    def _op(self, a, b):
        return a - b


class CDivTable(_TableReduce):
    def _op(self, a, b):
        return a / b


class CMaxTable(_TableReduce):
    def _op(self, a, b):
        return jnp.maximum(a, b)


class CMinTable(_TableReduce):
    def _op(self, a, b):
        return jnp.minimum(a, b)


class JoinTable(Module):
    """Concatenate table elements along `dimension` (1-based over
    n_input_dims-ranked elements; batch handled as in the reference)
    (reference: nn/JoinTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name: Optional[str] = None):
        super().__init__(name=name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, variables, input, training=False, rng=None):
        elems = _elems(input)
        ax = self.dimension - 1
        if self.n_input_dims > 0 and elems[0].ndim == self.n_input_dims + 1:
            ax += 1  # batched input: shift past batch dim
        return jnp.concatenate(elems, axis=ax), variables["state"]


class SplitTable(Module):
    """Split a tensor along a dim into a table (reference: nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name: Optional[str] = None):
        super().__init__(name=name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, variables, x, training=False, rng=None):
        ax = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim == self.n_input_dims + 1:
            ax += 1
        parts = [jnp.squeeze(p, axis=ax) for p in jnp.split(x, x.shape[ax], axis=ax)]
        return T(*parts), variables["state"]


class SelectTable(Module):
    """Pick the i-th (1-based) table element (reference: nn/SelectTable.scala)."""

    def __init__(self, index: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.index = index

    def apply(self, variables, input, training=False, rng=None):
        elems = _elems(input)
        idx = self.index - 1 if self.index > 0 else len(elems) + self.index
        return elems[idx], variables["state"]


class FlattenTable(Module):
    """Flatten nested tables (reference: nn/FlattenTable.scala)."""

    def apply(self, variables, input, training=False, rng=None):
        out = Table()

        def rec(v):
            if isinstance(v, (dict, list, tuple)):
                for e in _elems(v):
                    rec(e)
            else:
                out.insert(v)

        rec(input)
        return out, variables["state"]


class MM(Module):
    """Batch matrix-matrix product of a 2-table (reference: nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def apply(self, variables, input, training=False, rng=None):
        a, b = _elems(input)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b, variables["state"]


class MV(Module):
    """Batch matrix-vector product of a 2-table (reference: nn/MV.scala)."""

    def __init__(self, trans: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.trans = trans

    def apply(self, variables, input, training=False, rng=None):
        m, v = _elems(input)
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), variables["state"]


class DotProduct(Module):
    """Row-wise dot product of a 2-table (reference: nn/DotProduct.scala)."""

    def apply(self, variables, input, training=False, rng=None):
        a, b = _elems(input)
        return jnp.sum(a * b, axis=-1), variables["state"]


class CosineDistance(Module):
    """Row-wise cosine similarity of a 2-table (reference: nn/CosineDistance.scala)."""

    def apply(self, variables, input, training=False, rng=None):
        a, b = _elems(input)
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), 1e-12)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.sum(a * b, axis=-1) / (na * nb), variables["state"]


class _AxisReduce(Module):
    _keep = False

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def _op(self, x, ax, keepdims):
        raise NotImplementedError

    def apply(self, variables, x, training=False, rng=None):
        ax = self.dimension - 1 if self.dimension > 0 else x.ndim + self.dimension
        if self.n_input_dims > 0 and x.ndim == self.n_input_dims + 1:
            ax += 1
        return self._op(x, ax, not self.squeeze), variables["state"]


class Sum(_AxisReduce):
    def _op(self, x, ax, keepdims):
        return jnp.sum(x, axis=ax, keepdims=keepdims)


class Mean(_AxisReduce):
    def _op(self, x, ax, keepdims):
        return jnp.mean(x, axis=ax, keepdims=keepdims)


class Max(_AxisReduce):
    def _op(self, x, ax, keepdims):
        return jnp.max(x, axis=ax, keepdims=keepdims)


class Min(_AxisReduce):
    def _op(self, x, ax, keepdims):
        return jnp.min(x, axis=ax, keepdims=keepdims)
